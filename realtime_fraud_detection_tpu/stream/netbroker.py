"""Networked, durable transport: a standalone TCP log broker + client.

The reference's data backbone is an *external* Kafka cluster — the stream
job, simulator, and serving tier are separate processes joined by brokers
(docker-compose.yml, FraudDetectionJob.java:141-213). Round 1 of this
framework only had the in-process ``InMemoryBroker``; this module makes the
transport genuinely external without taking a client-library dependency:

- ``BrokerServer`` — a TCP server exposing the partitioned-log operations
  (produce / fetch / commit / committed / lag / end_offsets / create_topic)
  over a length-prefixed JSON protocol. State is an ``InMemoryBroker`` plus
  an optional write-ahead segment directory: every produce is appended to
  ``<log_dir>/<topic>-<partition>.jsonl`` and fsync'd before the ack (the
  acks=all analog of config/kafka/producer.properties), group offsets land
  in ``<log_dir>/offsets.json`` on commit, and a restarting server replays
  both — so the broker survives process death the way Kafka's log does.
- ``NetBrokerClient`` — speaks the same protocol from any process and
  implements the exact broker interface ``stream.transport.Consumer``
  consumes (committed/partitions/read/commit/lag), so
  ``StreamJob(broker=NetBrokerClient(...))`` runs unchanged against a
  remote broker. One TCP connection, pipelined request/response framing,
  thread-safe.

Replication (the RF/minISR story — reference runs 3 brokers with RF=3,
minISR=2, scripts/setup/create-topics.sh:9-12):

- A second ``BrokerServer`` started with ``role="replica"`` serves reads
  but refuses writes (``READONLY``). ``primary.add_replica(host, port)``
  catches it up (topic layout, record backlog, group offsets) and then
  ships every produce to it SYNCHRONOUSLY before the producer's ack —
  the acks=all analog. ``min_isr`` gates the ack: a produce that cannot
  reach ``min_isr`` in-sync copies (self included) fails loudly instead of
  pretending durability. A replica that errors is dropped from the ISR
  (exactly Kafka's shrink-then-ack behavior with minISR).
- Offset commits are forwarded to replicas too, so a promoted replica
  resumes every consumer group where the dead primary acked it.
- ``promote()`` (or the ``promote`` wire op) flips a replica to primary.
- ``HaBrokerClient([(h1, p1), (h2, p2)])`` is the client side of failover:
  on connection loss or READONLY it rotates to the next address and
  retries. A retried produce can duplicate (at-least-once, like any
  acks=all producer retry) — consumers dedupe by transaction id
  (stream/job.py dispatch_batch).

Acked-record guarantee: an acked produce is fsync'd on the primary's WAL
AND applied on min_isr-1 replicas (their WALs included) before the ack, so
SIGKILL of the primary loses nothing acked — pinned by the kill-the-primary
soak in tests/test_netbroker.py.

Unacked-record guarantee (high watermark): consumers read only up to the
per-partition high watermark, which advances when a produce reaches its
min_isr copies. A produce that FAILS replication leaves its records on the
local log above the watermark — no consumer ever observes a record whose
producer was told it was not written (the read-uncommitted window is
closed, not documented away). The tail re-surfaces only once a later
``add_replica`` backlog sync makes it min_isr-replicated, consistent with
the at-least-once producer-retry contract. Pinned watermarks are persisted
(``hw.json``) so a primary RESTART cannot re-expose a WAL-replayed unacked
tail either; the residual window is a crash between a failing produce's
WAL fsync and its pin write (the same compromise as Kafka's checkpointed
HW). Pinned by the regression tests in tests/test_netbroker.py.

Producer generation fencing (the zombie-writer story, ISSUE 13): the
cluster coordinator's rebalance fence step calls ``fence_producers`` for
every moved partition at the new assignment generation; workers stamp
their produces/commits with the generation they last adopted
(``NetBrokerClient.generation``), and a stamped write below a
partition's fence is refused whole-frame with ``StaleGenerationError``
(counted; unstamped external producers pass). This closes the asymmetric
partition: a worker that cannot hear the coordinator but still reaches
the broker is fenced at the WRITE seam, not just the checkpoint seam
(cluster/handoff.py's offset-epoch fence) — Kafka's zombie-producer
epoch fencing, in-house. Fences forward to replicas like commits, so a
promoted replica keeps refusing the same zombies.

The wire format is 4-byte big-endian length + JSON — deliberately boring:
the contract (offsets, groups, keyed partitions, commit-after-fanout) is
what's load-bearing, and the contract tests run identically against
``InMemoryBroker`` and a live ``BrokerServer`` (tests/test_netbroker.py).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence

from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS, TopicSpec
from realtime_fraud_detection_tpu.stream.transport import (
    Consumer,
    FaultInjector,
    InMemoryBroker,
    Record,
    StaleGenerationError,
)

__all__ = ["BrokerServer", "NetBrokerClient", "HaBrokerClient",
           "StaleGenerationError"]

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 * 1024 * 1024


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> Optional[bytes]:
    """Read exactly ``n`` bytes. With ``deadline`` (an absolute monotonic
    instant) the WHOLE read is bounded — a hung-not-dead peer (SIGSTOP'd
    broker, stalled middlebox) trickling one byte per socket-timeout
    window would otherwise reset the per-recv timeout forever and wedge
    the caller; here every chunk shrinks the remaining budget."""
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None:
            # rtfd-lint: allow[wall-clock] socket I/O deadlines are genuinely wall-bound
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"frame read deadline exceeded with {n - len(buf)} "
                    f"bytes outstanding")
            sock.settimeout(remaining)
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket,
                deadline: Optional[float] = None) -> Optional[Any]:
    header = _recv_exact(sock, _LEN.size, deadline)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds limit")
    payload = _recv_exact(sock, length, deadline)
    if payload is None:
        return None
    return json.loads(payload)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        server: BrokerServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server._conns.add(sock)
        try:
            while True:
                try:
                    req = _recv_frame(sock)
                except (ConnectionError, ValueError, json.JSONDecodeError,
                        OSError):
                    return
                if req is None:
                    return
                try:
                    resp = server.dispatch(req)
                except Exception as e:  # noqa: BLE001 - per-request isolation
                    resp = {"error": f"{type(e).__name__}: {e}"}
                try:
                    _send_frame(sock, resp)
                except (ConnectionError, OSError):
                    return
        finally:
            server._conns.discard(sock)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _ReplicaLink:
    """Primary-held connection to one replica server (the shipping lane)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.addr = (host, port)
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        with self._lock:
            _send_frame(self._sock, req)
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("replica closed the connection")
        if "error" in resp:
            raise RuntimeError(f"replica error: {resp['error']}")
        return resp

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class NotEnoughReplicasError(RuntimeError):
    """Produce could not reach min_isr in-sync copies (Kafka's
    NOT_ENOUGH_REPLICAS). The record may exist on the primary's log but was
    NOT acked — a retried producer may duplicate it (at-least-once)."""


class BrokerServer:
    """Serve an (optionally durable, optionally replicated) partitioned log
    over TCP. ``role="replica"`` starts read-only; ``min_isr`` counts the
    primary itself (min_isr=2 means "me plus at least one replica")."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Sequence[TopicSpec] = TOPIC_SPECS,
                 log_dir: Optional[str] = None,
                 role: str = "primary", min_isr: int = 1):
        if role not in ("primary", "replica"):
            raise ValueError(f"role must be primary|replica, got {role!r}")
        self.broker = InMemoryBroker(topics)
        self.log_dir = Path(log_dir) if log_dir else None
        self.role = role
        self.min_isr = int(min_isr)
        # High watermark per (topic, partition): consumers only ever read
        # up to it. It advances when a produce reaches min_isr in-sync
        # copies, so a record whose replication FAILED sits on the local
        # log above the watermark — never exposed to a consumer before its
        # durability ack (Kafka's HW semantics; closes the read-uncommitted
        # window where a consumer could act on a record whose producer was
        # told it was NOT written). A partition with no entry is fully
        # visible. Because the WAL is written BEFORE replication, a pinned
        # watermark (hw < log end) is also persisted to ``hw.json`` —
        # without that, a restart would replay the fsync'd-but-unacked
        # tail as visible. Only the pin set is persisted (rare,
        # failure-path writes; the steady state costs no I/O).
        self._hw: Dict[tuple, int] = {}
        self._persisted_pins: Dict[str, int] = {}
        self._replicas: List[_ReplicaLink] = []
        self._conns: set = set()          # live handler sockets (for stop())
        self._seg_files: Dict[tuple, Any] = {}
        self._io_lock = threading.Lock()
        if self.log_dir is not None:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            self._replay()
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="broker-server", daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        # drop live connections so peers (clients, a primary's replica
        # link) observe the death immediately — a stopped server must not
        # keep acking replication traffic from a lingering handler thread
        for sock in list(self._conns):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        with self._io_lock:
            for link in self._replicas:
                link.close()
            self._replicas.clear()
            for f in self._seg_files.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._seg_files.clear()

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ----------------------------------------------------------- durability
    def _segment(self, topic: str, partition: int):
        key = (topic, partition)
        f = self._seg_files.get(key)
        if f is None:
            path = self.log_dir / f"{topic}-{partition}.jsonl"
            f = open(path, "a", encoding="utf-8")
            self._seg_files[key] = f
        return f

    def _produce(self, topic: str, items: List[tuple],
                 generation: Optional[int] = None) -> List[Record]:
        """Produce with WAL-first durability + synchronous replication:
        partition is chosen, the WAL line is written + fsync'd, the record
        is published to the in-memory log, and it is shipped to every
        in-sync replica — the ack happens only once ``min_isr`` copies
        (self included) hold it. A WAL write failure errors the produce
        *before* any consumer could see the record; ``_io_lock`` serializes
        produces so WAL line order always matches log offset order per
        partition AND replicas receive offsets contiguously.
        ``items``: [(key, value, timestamp|None)].

        A stamped ``generation`` is fence-checked for EVERY target
        partition BEFORE the WAL write — a refused frame is all-or-
        nothing (no partial batch, no invisible above-watermark residue),
        so a zombie writer's whole fan-out bounces with
        ``StaleGenerationError`` and nothing it wrote can surface later.
        """
        b = self.broker
        with self._io_lock:
            planned = [
                (b.select_partition(topic, k), k, v,
                 # rtfd-lint: allow[wall-clock] record-timestamp default; callers pass ts
                 ts if ts is not None else time.time())
                for k, v, ts in items
            ]
            if generation is not None:
                for part in sorted({p for p, _k, _v, _ts in planned}):
                    b.check_producer_generation(topic, part, generation)
            if self.log_dir is not None:
                touched = set()
                for part, k, v, ts in planned:
                    f = self._segment(topic, part)
                    f.write(json.dumps({"k": k, "v": v, "ts": ts},
                                       separators=(",", ":")) + "\n")
                    touched.add(f)
                for f in touched:
                    f.flush()
                    os.fsync(f.fileno())
            # the watermark shipped WITH the records is the pre-produce
            # visible end: these records are not acked yet, so a replica
            # applying them must not expose them to its readers. The same
            # watermark is PRE-PINNED locally BEFORE the append: fetch/lag
            # handlers run on other threads without _io_lock, and a
            # partition with no _hw entry defaults to the physical log end
            # — without the pre-pin, a fetch racing the replication round
            # trip would serve the not-yet-acked record.
            pre_hw = {p: self._visible_end(topic, p)
                      for p in range(len(b._logs(topic)))}
            for p, hw in pre_hw.items():
                self._hw[(topic, p)] = hw
            recs = [b.append(topic, part, v, k, ts)
                    for part, k, v, ts in planned]
            try:
                self._replicate(topic, recs, pre_hw)
            except Exception:
                # NOT acked: the pre-pinned watermark stays — consumers
                # never see the unreplicated tail (it stays on the local
                # log; a successful later replication round — e.g.
                # add_replica's backlog sync — re-advances past it). The
                # pin is persisted so a RESTART cannot re-expose the
                # WAL-replayed tail either.
                self._sync_hw_pins()
                raise
            for p, log in enumerate(b._logs(topic)):
                self._hw[(topic, p)] = len(log.records)
            self._sync_hw_pins()
            # acked: let replicas expose the records too (their visible end
            # follows the primary's watermark, never their raw log end)
            self._sync_replica_hw(topic)
            return recs

    def _sync_replica_hw(self, topic: str) -> None:
        """Push the primary's committed watermark to replicas after an ack.
        A replica that misses the sync just serves a slightly stale (more
        conservative) view until the next one — never the unsafe
        direction — so errors here do not shrink the ISR. Caller holds
        ``_io_lock``. COST: one extra frame per replica per acked produce,
        chosen deliberately — Kafka piggybacks the HW on the next data
        frame and lets follower reads lag one produce; this broker's
        replicas promise read-your-ack freshness (tests pin it), and the
        produce path is already synchronous per replica, so the extra
        frame is a constant factor, not a new round-trip class."""
        if not self._replicas:
            return
        hws = {str(p): self._visible_end(topic, p)
               for p in range(len(self.broker._logs(topic)))}
        for link in self._replicas:
            try:
                link.call({"op": "hw_sync", "topic": topic, "hws": hws})
            except Exception:  # noqa: BLE001 — stale-but-safe on failure
                pass

    def _sync_hw_pins(self) -> None:
        """Persist the PIN SET — partitions whose watermark sits below the
        log end (an unacked, replication-failed tail). Written only when
        the set changes (pins appear on the failure path and clear on
        re-sync), so the acked steady state never touches this file.
        Residual window: a crash between a produce's WAL fsync and this
        pin write re-exposes that produce's tail on restart — the same
        at-least-once compromise as Kafka's periodically-checkpointed HW.
        Caller holds ``_io_lock``."""
        if self.log_dir is None:
            return
        pins = {
            f"{t}\x00{p}": hw
            for (t, p), hw in self._hw.items()
            if p < len(self.broker._logs(t))
            and hw < len(self.broker._logs(t)[p].records)
        }
        if pins == self._persisted_pins:
            return
        tmp = self.log_dir / "hw.json.tmp"
        tmp.write_text(json.dumps(pins))
        tmp.replace(self.log_dir / "hw.json")
        self._persisted_pins = pins

    def _visible_end(self, topic: str, part: int) -> int:
        """Consumer-visible end offset: the high watermark when one is
        tracked, else the physical log end."""
        logs = self.broker._logs(topic)
        end = len(logs[part].records) if part < len(logs) else 0
        return min(end, self._hw.get((topic, part), end))

    # ---------------------------------------------------------- replication
    def _replicate(self, topic: str, recs: List[Record],
                   ship_hw: Optional[Dict[int, int]] = None) -> None:
        """Ship freshly appended records to every replica, synchronously.
        Caller holds ``_io_lock``. A replica that errors is dropped from
        the ISR; if fewer than ``min_isr`` copies hold the records, the
        produce fails (the records stay on the local log unacked — a
        producer retry may duplicate them: at-least-once)."""
        acks = 1  # self: WAL already fsync'd (or in-memory by configuration)
        if self._replicas:
            parts: Dict[int, List[Dict[str, Any]]] = {}
            for r in recs:
                parts.setdefault(r.partition, []).append(
                    {"k": r.key, "v": r.value, "ts": r.timestamp,
                     "o": r.offset})
            req = {
                "op": "replicate", "topic": topic,
                # partition COUNT rides along: an auto-created topic must
                # have the same layout on the replica even for partitions
                # that never received a record, or key routing diverges
                # after a promote
                "n_parts": len(self.broker._logs(topic)),
                # the primary's CURRENT watermark rides along too: the
                # replica's visible end follows the primary's (a record
                # being shipped is not yet acked — the replica must not
                # serve reads past what the primary has committed)
                "parts": [{"p": p, "base": rows[0]["o"], "records": rows,
                           "hw": (ship_hw.get(p, 0) if ship_hw is not None
                                  else self._visible_end(topic, p))}
                          for p, rows in parts.items()],
            }
            alive = []
            for link in self._replicas:
                try:
                    link.call(req)
                    acks += 1
                    alive.append(link)
                except Exception:  # noqa: BLE001 — ISR shrink on any failure
                    link.close()
            self._replicas[:] = alive
        if acks < self.min_isr:
            raise NotEnoughReplicasError(
                f"produce reached {acks} in-sync copies < min_isr "
                f"{self.min_isr}; record NOT acked")

    def add_replica(self, host: str, port: int,
                    chunk: int = 500) -> None:
        """Attach a replica server: sync topic layout, push the record
        backlog and group offsets, then admit it to the ISR — every later
        produce ships to it before the producer's ack."""
        link = _ReplicaLink(host, port)
        with self._io_lock:
            b = self.broker
            for t in list(b._topics):
                logs = b._logs(t)
                link.call({"op": "sync_topic", "name": t,
                           "partitions": len(logs)})
                rends = link.call({"op": "end_offsets", "topic": t})["ends"]
                for p, log in enumerate(logs):
                    start = rends[p] if p < len(rends) else 0
                    while start < len(log.records):
                        rows = [
                            {"k": r.key, "v": r.value, "ts": r.timestamp,
                             "o": r.offset}
                            for r in log.records[start:start + chunk]
                        ]
                        link.call({"op": "replicate", "topic": t,
                                   "parts": [{"p": p, "base": rows[0]["o"],
                                              "records": rows,
                                              "hw": self._visible_end(
                                                  t, p)}]})
                        start += len(rows)
            link.call({"op": "offsets_sync", "committed": {
                f"{g}\x00{t}\x00{p}": off
                for (g, t, p), off in b._committed.items()
            }})
            self._replicas.append(link)
            if 1 + len(self._replicas) >= self.min_isr:
                # the full backlog (any previously unacked tail included)
                # now holds on min_isr copies: expose it, replicas included
                for t in list(b._topics):
                    for p, log in enumerate(b._logs(t)):
                        self._hw[(t, p)] = len(log.records)
                self._sync_hw_pins()
                for t in list(b._topics):
                    self._sync_replica_hw(t)

    def _apply_replicated(self, topic: str, part: int, base: int,
                          rows: List[Mapping[str, Any]],
                          primary_hw: Optional[int] = None) -> None:
        """Replica side: append shipped records at their primary offsets,
        WAL-first when durable. Idempotent for already-held offsets; a gap
        (shipped offset beyond local end) is refused loudly — the primary
        re-syncs via add_replica rather than silently diverging."""
        b = self.broker
        logs = b._logs(topic)
        if part >= len(logs):
            with b._lock:
                while len(logs) < part + 1:
                    logs.append(type(logs[0])())
        log = logs[part]
        with self._io_lock:
            local_end = len(log.records)
            fresh = [(base + j, d) for j, d in enumerate(rows)
                     if base + j >= local_end]
            if fresh and fresh[0][0] > local_end:
                raise RuntimeError(
                    f"replication gap on {topic}-{part}: local end "
                    f"{local_end}, shipped base {fresh[0][0]}")
            if self.log_dir is not None and fresh:
                f = self._segment(topic, part)
                for _, d in fresh:
                    f.write(json.dumps(
                        {"k": d.get("k"), "v": d.get("v"),
                         "ts": d.get("ts", 0.0)},
                        separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            for _, d in fresh:
                b.append(topic, part, d.get("v"), d.get("k"),
                         d.get("ts", 0.0))
            # visibility follows the PRIMARY's watermark, not the local
            # log end: the shipped records are not yet acked (the primary
            # is still collecting min_isr acks when this runs), so a read
            # from this warm standby must not run ahead of what the
            # primary serves. Legacy replicate frames without "hw" keep
            # the old expose-on-apply behavior.
            self._hw[(topic, part)] = (
                min(int(primary_hw), len(log.records))
                if primary_hw is not None else len(log.records))
            # deliberately NOT persisted here: on the acked path this pin
            # is transient (the post-ack hw_sync clears it milliseconds
            # later), and persisting would cost two hw.json writes per
            # produce on a durable replica's synchronous path. The cost: a
            # replica crashing inside that window replays the applied-but-
            # not-yet-acked records as visible — the same bounded
            # WAL-vs-pin compromise the primary documents.

    def _forward_commit(self, group: str, wire: Mapping[str, Any]) -> None:
        """Ship an offset commit to replicas so a promoted replica resumes
        every group where the primary acked it. A failing replica drops
        from the ISR (same policy as record shipping)."""
        with self._io_lock:
            if not self._replicas:
                return
            alive = []
            for link in self._replicas:
                try:
                    link.call({"op": "commit_sync", "group": group,
                               "offsets": dict(wire)})
                    alive.append(link)
                except Exception:  # noqa: BLE001
                    link.close()
            self._replicas[:] = alive

    def _grow_topic(self, name: str, partitions: int) -> None:
        """Ensure ``name`` exists with AT LEAST ``partitions`` partitions
        (replica-side layout sync; partition counts only ever grow)."""
        b = self.broker
        b.create_topic(name, partitions)
        logs = b._logs(name)
        if len(logs) < partitions:
            with b._lock:
                while len(logs) < partitions:
                    logs.append(type(logs[0])())

    def promote(self) -> None:
        """Replica -> primary: start accepting writes. The log, offsets and
        WAL carry over as-is (they were kept in sync by the shipping lane).

        Promotion commits the local log tail: the new primary's log IS the
        partition's truth, so the watermark advances to the log end — the
        same retroactive commit a Kafka leader election performs. A record
        whose producer was told "not written" (its ack round died with the
        old primary) may therefore surface after failover; producer
        retries then duplicate it, which is the documented at-least-once
        contract (consumers dedupe by transaction id).
        """
        with self._io_lock:
            for t in list(self.broker._topics):
                for p, log in enumerate(self.broker._logs(t)):
                    self._hw[(t, p)] = len(log.records)
            self._sync_hw_pins()
        self.role = "primary"

    def isr_size(self) -> int:
        with self._io_lock:
            return 1 + len(self._replicas)

    def _persist_offsets(self) -> None:
        if self.log_dir is None:
            return
        with self._io_lock:
            snap = {
                f"{g}\x00{t}\x00{p}": off
                for (g, t, p), off in self.broker._committed.items()
            }
            tmp = self.log_dir / "offsets.json.tmp"
            tmp.write_text(json.dumps(snap))
            tmp.replace(self.log_dir / "offsets.json")

    def _replay(self) -> None:
        for path in sorted(self.log_dir.glob("*-*.jsonl")):
            topic, _, part_s = path.stem.rpartition("-")
            try:
                part = int(part_s)
            except ValueError:
                continue
            logs = self.broker._logs(topic)
            if part >= len(logs):
                self.broker._topics[topic].extend(
                    type(logs[0])() for _ in range(part + 1 - len(logs)))
            log = self.broker._logs(topic)[part]
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    d = json.loads(line)
                    log.records.append(Record(
                        topic, part, len(log.records), d.get("k"),
                        d.get("v"), d.get("ts", 0.0)))
        off_path = self.log_dir / "offsets.json"
        if off_path.exists():
            for key, off in json.loads(off_path.read_text()).items():
                g, t, p = key.split("\x00")
                self.broker._committed[(g, t, int(p))] = int(off)
        hw_path = self.log_dir / "hw.json"
        if hw_path.exists():
            # re-pin watermarks for partitions whose WAL tail was never
            # acked: the replayed records stay invisible until a replica
            # re-sync makes them min_isr-replicated
            self._persisted_pins = json.loads(hw_path.read_text())
            for key, hw in self._persisted_pins.items():
                t, p = key.split("\x00")
                self._hw[(t, int(p))] = int(hw)

    # ------------------------------------------------------------- dispatch
    _WRITE_OPS = frozenset({"produce", "produce_batch", "commit",
                            "create_topic"})

    def dispatch(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        b = self.broker
        if self.role == "replica" and op in self._WRITE_OPS:
            # reads stay served (a replica is a warm standby + read scale-
            # out); writes go to the primary or wait for promote()
            return {"error": "READONLY: replica accepts reads and "
                             "replication traffic only; promote() to "
                             "accept writes"}
        if op == "replicate":
            n_parts = req.get("n_parts")
            if n_parts:
                self._grow_topic(req["topic"], int(n_parts))
            for blob in req["parts"]:
                hw = blob.get("hw")
                self._apply_replicated(req["topic"], int(blob["p"]),
                                       int(blob["base"]), blob["records"],
                                       primary_hw=(int(hw) if hw is not None
                                                   else None))
            return {}
        if op == "hw_sync":
            # post-ack watermark push: expose records the primary just
            # committed (clamped to the local log — never past what this
            # replica actually holds)
            topic = req["topic"]
            logs = self.broker._logs(topic)
            with self._io_lock:
                for p_s, hw in req["hws"].items():
                    p = int(p_s)
                    if p < len(logs):
                        self._hw[(topic, p)] = min(
                            int(hw), len(logs[p].records))
                self._sync_hw_pins()
            return {}
        if op == "sync_topic":
            self._grow_topic(req["name"], int(req["partitions"]))
            return {}
        if op == "commit_sync":
            offsets = {}
            for key, off in req["offsets"].items():
                t, _, p = key.rpartition(":")
                offsets[(t, int(p))] = int(off)
            b.commit(req["group"], offsets)
            self._persist_offsets()
            return {}
        if op == "offsets_sync":
            for key, off in req["committed"].items():
                g, t, p = key.split("\x00")
                b._committed[(g, t, int(p))] = int(off)
            self._persist_offsets()
            return {}
        if op == "promote":
            self.promote()
            return {"role": self.role}
        if op == "status":
            return {"role": self.role, "min_isr": self.min_isr,
                    "isr": self.isr_size(),
                    **self.broker.producer_fence_stats()}
        if op == "fence_producers":
            # the coordinator's rebalance fence step: stamped writes to
            # these partitions below `generation` are refused from now
            # on. Forwarded to replicas like offset commits, so a
            # promoted replica keeps fencing the same zombies.
            self.broker.fence_producers(req["topic"], req["partitions"],
                                        int(req["generation"]))
            with self._io_lock:
                alive = []
                for link in self._replicas:
                    try:
                        link.call({"op": "fence_producers",
                                   "topic": req["topic"],
                                   "partitions": req["partitions"],
                                   "generation": int(req["generation"])})
                        alive.append(link)
                    except Exception:  # noqa: BLE001 — ISR shrink policy
                        link.close()
                self._replicas[:] = alive
            return {}
        if op == "produce":
            gen = req.get("gen")
            rec = self._produce(req["topic"], [(
                req.get("key"), req["value"], req.get("timestamp"))],
                generation=int(gen) if gen is not None else None)[0]
            return {"partition": rec.partition, "offset": rec.offset}
        if op == "produce_batch":
            # optional per-record "ts": drills stamp virtual arrival times
            # so consumer-side budget/latency math shares one time base
            gen = req.get("gen")
            recs = self._produce(req["topic"], [
                (item.get("k"), item["v"], item.get("ts"))
                for item in req["records"]],
                generation=int(gen) if gen is not None else None)
            return {"n": len(recs)}
        if op == "fetch":
            # reads stop at the high watermark: a record above it exists on
            # the log but its produce was never acked (min_isr not reached)
            end = self._visible_end(req["topic"], req["partition"])
            limit = min(int(req["max_records"]),
                        max(0, end - int(req["offset"])))
            recs = b.read(req["topic"], req["partition"], req["offset"],
                          limit) if limit > 0 else []
            return {"records": [
                {"p": r.partition, "o": r.offset, "k": r.key, "v": r.value,
                 "ts": r.timestamp} for r in recs]}
        if op == "commit":
            offsets = {}
            for key, off in req["offsets"].items():
                t, _, p = key.rpartition(":")
                offsets[(t, int(p))] = int(off)
            gen = req.get("gen")
            b.commit(req["group"], offsets,
                     generation=int(gen) if gen is not None else None)
            self._persist_offsets()
            self._forward_commit(req["group"], req["offsets"])
            return {}
        if op == "committed":
            return {"offset": b.committed(req["group"], req["topic"],
                                          req["partition"])}
        if op == "partitions":
            return {"n": b.partitions(req["topic"])}
        if op == "end_offsets":
            # replication internals (add_replica's catch-up) need PHYSICAL
            # ends; consumer-facing visibility is enforced at fetch/lag
            return {"ends": b.end_offsets(req["topic"])}
        if op == "lag":
            # lag against the VISIBLE ends, matching what fetch can serve —
            # otherwise a drain loop would spin forever on an unacked tail
            topic, group = req["topic"], req["group"]
            total = 0
            for p in range(len(b._logs(topic))):
                total += max(0, self._visible_end(topic, p)
                             - b.committed(group, topic, p))
            return {"lag": total}
        if op == "create_topic":
            b.create_topic(req["name"], req["partitions"])
            # layout changes ship to replicas like records do: a topic
            # created after add_replica must exist with the same partition
            # count on the survivor, or key routing diverges post-promote
            with self._io_lock:
                alive = []
                for link in self._replicas:
                    try:
                        link.call({"op": "sync_topic", "name": req["name"],
                                   "partitions": req["partitions"]})
                        alive.append(link)
                    except Exception:  # noqa: BLE001
                        link.close()
                self._replicas[:] = alive
            return {}
        if op == "ping":
            return {"pong": True}
        raise ValueError(f"unknown op {op!r}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class NetBrokerClient:
    """Broker-interface client over one pipelined TCP connection.

    Implements the five methods ``transport.Consumer`` needs (committed /
    partitions / read / commit / lag) plus the producer surface, so every
    component that takes an ``InMemoryBroker`` takes one of these.

    Reconnect semantics (broker RESTART survival): on a dead connection
    the client retries up to ``reconnect_attempts`` times with bounded
    exponential backoff + deterministic jitter, reconnecting to the same
    address — a broker that restarts from its WAL resumes serving the
    same log. A retried *produce* across the gap may duplicate (the ack
    may have been lost in flight — standard at-least-once; consumers
    dedupe by transaction id). Every reconnect bumps the client's
    ``reconnect_epoch``: each ``transport.Consumer`` sharing this client
    observes the change independently and re-fetches from the last
    COMMITTED offset instead of its in-memory cursor — records
    polled-but-uncommitted at the moment of the outage are re-delivered
    rather than silently skipped past by a later commit (the
    crash-recovery contract; pinned in tests/test_netbroker.py).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 9092,
                 timeout_s: float = 30.0, reconnect_attempts: int = 5,
                 retry_sleep=None, link=None):
        from realtime_fraud_detection_tpu.utils.backoff import (
            DeterministicBackoff,
            instance_seed,
        )

        self._addr = (host, int(port))
        self._timeout_s = timeout_s
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._part_cache: Dict[str, int] = {}
        self._reconnect_attempts = max(0, int(reconnect_attempts))
        # optional in-path chaos link (chaos/netfaults.py): consulted
        # before every send and after every recv — latency/throttle
        # sleeps, partition/drop connection errors. None in production.
        self._link = link
        # optional producer assignment generation: when set, every
        # produce/commit frame is stamped with it and the broker refuses
        # the write if the target partition was fenced at a newer
        # generation (StaleGenerationError — the zombie-writer fence).
        # The cluster worker sets this each time it adopts an assignment.
        self.generation: Optional[int] = None
        # monotonically increasing reconnect epoch: EVERY consumer sharing
        # this client compares its last-seen epoch and rewinds to committed
        # offsets when it observes a newer one (a read-and-clear flag would
        # rewind only the first consumer to poll — the others would keep a
        # stale cursor past re-delivered records)
        self._reconnect_epoch = 0
        # per-instance seed: all clients of one broker port are exactly
        # the herd whose reconnect storms must de-correlate
        self._backoff = DeterministicBackoff(
            base_s=0.05, mult=2.0, max_s=0.8,
            seed=instance_seed(str(port)), sleep=retry_sleep)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _reconnect_locked(self) -> None:
        """Drop the dead socket and dial the same address. Caller holds
        ``_lock``. Raises OSError while the broker is still down."""
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reconnect_epoch += 1

    def reconnect_epoch(self) -> int:
        """Monotonic count of reconnects this client has survived.
        ``transport.Consumer`` compares against its own last-seen value
        and rewinds to committed offsets on any change — epoch-based so
        EVERY consumer sharing this client observes every reconnect (a
        read-and-clear flag would rewind only the first to poll)."""
        with self._lock:
            return self._reconnect_epoch

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        resp = None
        last: Optional[Exception] = None
        for attempt in range(self._reconnect_attempts + 1):
            resp = None
            try:
                with self._lock:
                    if self._link is not None:
                        # frame size rides along so slow-link throttling
                        # can pace by bytes (the double serialization is
                        # paid only while a chaos link is attached)
                        self._link.before_send(
                            req, len(json.dumps(
                                req, separators=(",", ":")).encode()))
                    _send_frame(self._sock, req)
                    # absolute per-op deadline: a hung-not-dead broker
                    # (SIGSTOP, stalled VM) trickling bytes cannot reset
                    # the budget — the whole frame read is bounded
                    deadline = time.monotonic() + self._timeout_s  # rtfd-lint: allow[wall-clock] socket I/O deadline is genuinely wall-bound
                    try:
                        resp = _recv_frame(self._sock, deadline=deadline)
                    finally:
                        # the deadline path shrinks the socket timeout to
                        # the residual budget; restore the full op
                        # timeout so the NEXT call's sendall never runs
                        # under a near-zero leftover
                        try:
                            self._sock.settimeout(self._timeout_s)
                        except OSError:
                            pass
                if resp is None:
                    raise ConnectionError("broker closed the connection")
                if self._link is not None:
                    # one-way partition: the op was APPLIED broker-side
                    # but the ack is lost — surfaces as a connection
                    # error, so a retried produce may duplicate
                    # (at-least-once; consumers dedupe by txn id)
                    self._link.after_recv(req)
                break
            except (ConnectionError, OSError) as e:
                last = e
                if attempt >= self._reconnect_attempts:
                    raise
                self._backoff.sleep(attempt)
                try:
                    with self._lock:
                        self._reconnect_locked()
                except OSError as e2:
                    last = e2          # still down: next attempt backs off
        if resp is None:
            raise ConnectionError(f"broker unreachable: {last}")
        if "error" in resp:
            msg = str(resp["error"])
            if msg.startswith("StaleGenerationError"):
                raise StaleGenerationError(f"broker refused: {msg}")
            raise RuntimeError(f"broker error: {msg}")
        return resp

    def _stamp_gen(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if self.generation is not None:
            req["gen"] = int(self.generation)
        return req

    # ------------------------------------------------------------- produce
    def produce(self, topic: str, value: Any, key: Optional[str] = None,
                timestamp: Optional[float] = None) -> Record:
        r = self._call(self._stamp_gen(
            {"op": "produce", "topic": topic, "value": value,
             "key": key, "timestamp": timestamp}))
        return Record(topic, r["partition"], r["offset"], key, value,
                      timestamp or 0.0)

    def produce_batch(self, topic: str, values, key_fn=None) -> int:
        items = [{"v": v, "k": key_fn(v) if key_fn else None} for v in values]
        if not items:
            return 0
        return self._call(self._stamp_gen(
            {"op": "produce_batch", "topic": topic,
             "records": items}))["n"]

    def produce_batch_keyed(self, topic: str, items) -> int:
        """(key, value) pairs in ONE frame — the fan-out hot path
        (one TCP round trip instead of one per record)."""
        records = [{"v": v, "k": k} for k, v in items]
        if not records:
            return 0
        return self._call(self._stamp_gen(
            {"op": "produce_batch", "topic": topic,
             "records": records}))["n"]

    def produce_batch_stamped(self, topic: str, items) -> int:
        """(key, value, timestamp) triples in ONE frame — the drill/replay
        producer path: explicit record timestamps (virtual-clock arrivals)
        at produce_batch_keyed's wire efficiency."""
        records = [{"v": v, "k": k, "ts": ts} for k, v, ts in items]
        if not records:
            return 0
        return self._call(self._stamp_gen(
            {"op": "produce_batch", "topic": topic,
             "records": records}))["n"]

    def fence_producers(self, topic: str, partitions, generation: int,
                        ) -> None:
        """Coordinator op: refuse stamped writes below ``generation`` for
        these partitions (the rebalance fence step's write-seam half)."""
        self._call({"op": "fence_producers", "topic": topic,
                    "partitions": [int(p) for p in partitions],
                    "generation": int(generation)})

    # ------------------------------------------------------------- consume
    def consumer(self, topics: Sequence[str], group_id: str,
                 faults: Optional[FaultInjector] = None,
                 partitions: Optional[Mapping[str, Sequence[int]]] = None,
                 ) -> Consumer:
        """``partitions`` scopes the consumer to an explicit topic →
        partition-list assignment (the partition-parallel worker plane,
        cluster/fleet.py) — same contract as ``InMemoryBroker.consumer``,
        so a partition-scoped worker runs unchanged over TCP."""
        return Consumer(self, list(topics), group_id, faults,
                        partitions=partitions)

    def read(self, topic: str, partition: int, start: int,
             limit: int) -> List[Record]:
        resp = self._call({"op": "fetch", "topic": topic,
                           "partition": partition, "offset": start,
                           "max_records": limit})
        return [
            Record(topic, d["p"], d["o"], d.get("k"), d.get("v"),
                   d.get("ts", 0.0))
            for d in resp["records"]
        ]

    # ------------------------------------------------------------- offsets
    def committed(self, group: str, topic: str, partition: int) -> int:
        return self._call({"op": "committed", "group": group, "topic": topic,
                           "partition": partition})["offset"]

    def commit(self, group: str, offsets: Mapping[tuple, int]) -> None:
        wire = {f"{t}:{p}": off for (t, p), off in offsets.items()}
        self._call(self._stamp_gen(
            {"op": "commit", "group": group, "offsets": wire}))

    def partitions(self, topic: str) -> int:
        n = self._part_cache.get(topic)
        if n is None:
            n = self._call({"op": "partitions", "topic": topic})["n"]
            self._part_cache[topic] = n
        return n

    def end_offsets(self, topic: str) -> List[int]:
        return self._call({"op": "end_offsets", "topic": topic})["ends"]

    def lag(self, group: str, topic: str) -> int:
        return self._call({"op": "lag", "group": group, "topic": topic})["lag"]

    def create_topic(self, name: str, partitions: int) -> None:
        self._part_cache.pop(name, None)
        self._call({"op": "create_topic", "name": name,
                    "partitions": partitions})

    def ping(self) -> bool:
        return bool(self._call({"op": "ping"}).get("pong"))

    def status(self) -> Dict[str, Any]:
        return self._call({"op": "status"})

    def promote(self) -> Dict[str, Any]:
        """Remote promote (the ops-script path for failover drills)."""
        return self._call({"op": "promote"})


class HaBrokerClient(NetBrokerClient):
    """Failover-aware client over an ordered broker list.

    On connection loss or a READONLY response (we were talking to a
    not-yet-promoted replica) the client rotates to the next address,
    reconnects, and retries the request. NOTE the produce-retry semantics:
    a produce whose ack was lost mid-failover may already be on the log,
    so a retry can duplicate it — at-least-once, exactly like a Kafka
    acks=all producer retrying across a leader change. Stream consumers
    dedupe by transaction id (stream/job.py dispatch_batch).
    """

    def __init__(self, addrs: Sequence[tuple], timeout_s: float = 30.0):
        if not addrs:
            raise ValueError("HaBrokerClient needs at least one address")
        self._addrs = [(str(h), int(p)) for h, p in addrs]
        self._which = 0
        self._timeout_s = timeout_s
        # construction must survive a dead first broker (a process started
        # AFTER the failover still lists the old primary first): try each
        # address in order
        last: Optional[Exception] = None
        for i, (host, port) in enumerate(self._addrs):
            try:
                # failover is THIS class's rotation, not same-address
                # reconnection — the base client's reconnect loop stays off
                super().__init__(host=host, port=port, timeout_s=timeout_s,
                                 reconnect_attempts=0)
                self._which = i
                return
            except OSError as e:
                last = e
        raise ConnectionError(
            f"no broker in {self._addrs} reachable: {last}")

    def _rotate(self) -> None:
        self._which = (self._which + 1) % len(self._addrs)
        host, port = self._addrs[self._which]
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = socket.create_connection(
                (host, port), timeout=self._timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        last: Optional[Exception] = None
        for attempt in range(2 * len(self._addrs)):
            try:
                return super()._call(req)
            except RuntimeError as e:
                if "READONLY" not in str(e):
                    raise
                last = e
            except (ConnectionError, OSError) as e:
                last = e
            try:
                self._rotate()
                # a successful rotation is a reconnect: sharing consumers
                # must rewind to committed offsets (transport.Consumer)
                with self._lock:
                    self._reconnect_epoch += 1
            except OSError as e:
                last = e
                self._backoff.sleep(attempt)
        raise ConnectionError(
            f"no broker in {self._addrs} reachable and writable: {last}")

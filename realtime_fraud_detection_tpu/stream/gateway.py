"""Ingress gateway: many producer threads → lock-free queue → one sender.

The reference's ingest edge is payment gateways POSTing into Kafka through
librdkafka's background sender thread (producer.properties tuning); the
framework analog is this gateway: application threads call ``submit(txn)``
— a lock-free MPMC push into the C++ microbatch queue (native/, the Vyukov
ring the TSAN harness stresses) costing ~100 ns and never blocking on the
network — while one background sender drains deadline-batches and produces
them to any broker behind the transport contract (InMemory/NetBroker/
Kafka). This is the production call site for ``NativeMicrobatchQueue``;
when the native library is unavailable the gateway degrades to a locked
deque with identical semantics.

Delivery: at-least-once from the submit() caller's perspective once
``flush()`` returns — the sender retries a failed produce_batch once and
counts drops otherwise (backpressure surfaces as ``submit() == False``
when the ring is full, so callers can shed or spin).
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Callable, Mapping, Optional

__all__ = ["IngressGateway"]


class _DequeFallback:
    """Locked-deque stand-in with the native queue's push/next_batch API
    (including the max_batch bound, so batch-size tuning behaves the same
    on both backends)."""

    def __init__(self, capacity: int, max_batch: int):
        self._dq: collections.deque = collections.deque()
        self._capacity = capacity
        self._max_batch = max_batch
        self._lock = threading.Lock()

    def push(self, payload: bytes) -> bool:
        with self._lock:
            if len(self._dq) >= self._capacity:
                return False
            self._dq.append(payload)
            return True

    def next_batch(self, block_ms: int = 0) -> list:
        # rtfd-lint: allow[wall-clock] real network/backpressure pacing
        deadline = time.monotonic() + block_ms / 1000.0
        while True:
            with self._lock:
                if self._dq:
                    out = [self._dq.popleft()
                           for _ in range(min(len(self._dq),
                                              self._max_batch))]
                    return out
            # rtfd-lint: allow[wall-clock] real network/backpressure pacing
            if time.monotonic() >= deadline:
                return []
            time.sleep(0.001)

    def pending(self) -> int:
        with self._lock:
            return len(self._dq)

    def close(self) -> None:
        pass


class IngressGateway:
    """Thread-safe transaction ingress in front of a broker."""

    def __init__(self, broker: Any, topic: str,
                 key_fn: Optional[Callable[[Mapping[str, Any]], str]] = None,
                 capacity: int = 8192, max_batch: int = 512,
                 max_delay_ms: float = 5.0, stamp_ingest: bool = False,
                 tracer: Optional[Any] = None):
        self.broker = broker
        self.topic = topic
        self.key_fn = key_fn or (lambda r: str(r.get("user_id", "")))
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        # tracing support: stamp each submitted txn with the wall-clock
        # instant it entered THIS process (``ingest_ts``), so the tracing
        # plane's ``ingest`` stage covers the gateway ring + sender +
        # broker hop, not just broker-to-admission. Off by default — the
        # stamp adds a field to every produced record.
        self.stamp_ingest = bool(stamp_ingest)
        # distributed tracing: with a Tracer attached (obs/tracing.py),
        # every submitted txn additionally carries a root trace carrier
        # (trace id + this process's origin + produce wall stamp) that
        # the consuming worker re-hydrates — the consume-side wall stamp
        # minus this one IS the broker_transit stage
        self.tracer = tracer if tracer is not None \
            and getattr(tracer, "enabled", False) else None
        self.sent = 0
        self.dropped = 0
        self.native = False
        try:
            from realtime_fraud_detection_tpu.native import (
                NativeMicrobatchQueue,
                native_available,
            )

            if native_available():
                self._q: Any = NativeMicrobatchQueue(
                    capacity=capacity, slot_bytes=8192,
                    max_batch=max_batch, max_delay_ms=max_delay_ms)
                self.native = True
                self._slot_bytes = 8192
            else:
                self._q = _DequeFallback(capacity, max_batch)
                self._slot_bytes = None
        except Exception:  # noqa: BLE001 — build toolchain absent
            self._q = _DequeFallback(capacity, max_batch)
            self._slot_bytes = None
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._thread = threading.Thread(
            target=self._sender, name="ingress-gateway", daemon=True)
        self._thread.start()

    # ---------------------------------------------------------------- submit
    def submit(self, txn: Mapping[str, Any]) -> bool:
        """Lock-free enqueue from any thread. False == ring full —
        backpressure, NOT a drop: the caller sheds or retries, and the
        ``dropped`` counter only ever counts records actually lost."""
        if self.stamp_ingest or self.tracer is not None:
            txn = dict(txn)
            # rtfd-lint: allow[wall-clock] ingest stamp is wall-clock by contract (broker-lag attribution)
            now_wall = time.time()
            if self.stamp_ingest:
                txn["ingest_ts"] = now_wall
            if self.tracer is not None:
                carrier = self.tracer.root_carrier(produced_ts=now_wall)
                if carrier is not None:
                    txn["trace_carrier"] = carrier
        payload = json.dumps(txn, separators=(",", ":")).encode()
        if self._slot_bytes is not None and len(payload) > self._slot_bytes:
            # oversized for a ring slot: drain what's queued first so this
            # thread's per-key ordering survives, then produce directly
            self.flush()
            self.broker.produce(self.topic, dict(txn), key=self.key_fn(txn))
            self.sent += 1
            return True
        ok = self._q.push(payload)
        if ok:
            self._idle.clear()
        return ok

    # ---------------------------------------------------------------- sender
    def _sender(self) -> None:
        while not self._stop.is_set():
            batch = self._q.next_batch(block_ms=int(self.max_delay_ms))
            if not batch:
                self._idle.set()
                continue
            records = [json.loads(p) for p in batch]
            try:
                self.broker.produce_batch(self.topic, records,
                                          key_fn=self.key_fn)
            except Exception:  # noqa: BLE001 — one retry, then count drops
                try:
                    time.sleep(0.05)
                    self.broker.produce_batch(self.topic, records,
                                              key_fn=self.key_fn)
                except Exception:  # noqa: BLE001
                    self.dropped += len(records)
                    continue
            self.sent += len(records)

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Block until everything submitted so far has been produced."""
        # rtfd-lint: allow[wall-clock] real network/backpressure pacing
        deadline = time.monotonic() + timeout_s
        # rtfd-lint: allow[wall-clock] real network/backpressure pacing
        while time.monotonic() < deadline:
            if self._q.pending() == 0 and self._idle.is_set():
                return True
            time.sleep(0.005)
        return False

    def close(self, timeout_s: float = 30.0) -> None:
        if not self.flush(timeout_s):
            # shutdown with the broker wedged: whatever is still in the
            # ring is lost when the queue is destroyed — count it
            self.dropped += self._q.pending()
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._q.close()

"""Windowed stream joins: this framework's StreamJoiner.

Equivalent of the reference's Flink join layer (StreamJoiner.java:29-127):

    1. txn x user-behavior        keyBy user,      tumbling 5m
    2. txn x merchant-update      keyBy merchant,  tumbling 10m
    3. txn x historical-pattern   keyBy (payment, category, amount//100),
                                                   tumbling 1h, similarity-
                                                   scored risk factors
    4. multi-stream correlation   connected per-user streams (txn + behavior
                                  + device + network) -> complex events

The reference wires the join graphs but every event class they join against
(UserBehaviorEvent, MerchantProfileUpdate, HistoricalFraudPattern,
ComplexEvent, EnrichedTransaction — StreamJoiner.java:29-127) is missing
from its tree (SURVEY.md §0.2); the schemas here are reconstructed from the
getter calls in the join functions. Join outputs are enriched-transaction
dicts: the original txn fields plus a ``risk_factors`` map and the joined
context, matching the addRiskFactor/addContext usage.

Engine: inner join over per-(key, window) buffers of both sides, emitted as
a cross product when the combined watermark (min of both streams'
max_event_time - out_of_orderness) passes the window end — the semantics of
Flink's tumbling-window join with bounded out-of-orderness watermarks.
Single-writer discipline, same as stream/windows.py.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from realtime_fraud_detection_tpu.stream.windows import TumblingWindow

__all__ = [
    "WindowJoin", "MultiStreamCorrelator",
    "txn_user_behavior_join", "txn_merchant_update_join",
    "txn_historical_pattern_join",
    "pattern_similarity", "historical_pattern_key",
]

Event = Mapping[str, Any]
JoinFn = Callable[[Event, Event], Dict[str, Any]]

TXN_OOO_S = 5.0                 # StreamJoiner.java:36 (5s txn watermark)
PATTERN_OOO_S = 60.0            # :94 (1m for the historical-pattern side)


class WindowJoin:
    """Inner join of two keyed streams over tumbling event-time windows.

    ``process_left`` / ``process_right`` buffer events; pairs for a window
    are emitted (via ``join_fn``) once the combined watermark passes the
    window end. Returns newly fired joined records.
    """

    def __init__(
        self,
        name: str,
        window: TumblingWindow,
        left_key: Callable[[Event], str],
        right_key: Callable[[Event], str],
        join_fn: JoinFn,
        left_ooo_s: float = TXN_OOO_S,
        right_ooo_s: float = TXN_OOO_S,
    ):
        self.name = name
        self.window = window
        self.left_key = left_key
        self.right_key = right_key
        self.join_fn = join_fn
        self.left_ooo_s = left_ooo_s
        self.right_ooo_s = right_ooo_s
        # (key, window) -> ([left events], [right events])
        self._buffers: Dict[Tuple[str, Tuple[float, float]],
                            Tuple[List[Event], List[Event]]] = {}
        self._left_max_ts = -math.inf
        self._right_max_ts = -math.inf
        self._fired_wm = -math.inf    # watermark at the last eviction scan
        self._min_open_end = math.inf  # earliest buffered window end
        self.joined = 0
        self.late_dropped = 0

    @property
    def watermark(self) -> float:
        """Joint watermark: min of both inputs' watermarks (Flink aligns
        watermarks across a two-input operator)."""
        return min(self._left_max_ts - self.left_ooo_s,
                   self._right_max_ts - self.right_ooo_s)

    def _add(self, side: int, key: str, event: Event,
             ts: float) -> List[Dict[str, Any]]:
        (start, end), = self.window.assign(ts)
        if end <= self.watermark:
            self.late_dropped += 1
        else:
            slot = self._buffers.get((key, (start, end)))
            if slot is None:
                slot = self._buffers[(key, (start, end))] = ([], [])
                self._min_open_end = min(self._min_open_end, end)
            slot[side].append(event)
        return self.advance_watermark()

    def process_left(self, event: Event, ts: float) -> List[Dict[str, Any]]:
        self._left_max_ts = max(self._left_max_ts, ts)
        return self._add(0, self.left_key(event), event, ts)

    def process_right(self, event: Event, ts: float) -> List[Dict[str, Any]]:
        self._right_max_ts = max(self._right_max_ts, ts)
        return self._add(1, self.right_key(event), event, ts)

    def advance_watermark(self) -> List[Dict[str, Any]]:
        wm = self.watermark
        # fast exit when the joint watermark hasn't advanced or hasn't yet
        # crossed the earliest buffered window's end (hot path)
        if wm <= self._fired_wm or wm < self._min_open_end:
            if wm > self._fired_wm:
                self._fired_wm = wm
            return []
        self._fired_wm = wm
        out: List[Dict[str, Any]] = []
        ready = sorted([kw for kw in self._buffers if kw[1][1] <= wm],
                       key=lambda kw: kw[1][1])
        for kw in ready:
            lefts, rights = self._buffers.pop(kw)
            for le in lefts:
                for re in rights:
                    out.append(self.join_fn(le, re))
                    self.joined += 1
        self._min_open_end = min(
            (kw[1][1] for kw in self._buffers), default=math.inf)
        return out

    def flush(self) -> List[Dict[str, Any]]:
        """End-of-stream: join every buffered window."""
        out: List[Dict[str, Any]] = []
        for kw in sorted(self._buffers, key=lambda kw: kw[1][1]):
            lefts, rights = self._buffers.pop(kw)
            for le in lefts:
                for re in rights:
                    out.append(self.join_fn(le, re))
                    self.joined += 1
        self._min_open_end = math.inf
        return out

    def __len__(self) -> int:
        return len(self._buffers)


# ------------------------------------------------------------ join function 1
def _enrich(txn: Event, risk_factors: Dict[str, float],
            context_key: str, context: Event) -> Dict[str, Any]:
    enriched = dict(txn)
    rf = dict(enriched.get("risk_factors") or {})
    rf.update({k: v for k, v in risk_factors.items() if v})
    enriched["risk_factors"] = rf
    enriched[context_key] = dict(context)
    return enriched


def _join_user_behavior(txn: Event, behavior: Event) -> Dict[str, Any]:
    """TransactionUserBehaviorJoinFunction (StreamJoiner.java:193-216):
    anomalous login 0.3, short session 0.2, anomalous navigation 0.25."""
    return _enrich(txn, {
        "recent_login_anomaly": 0.3 if behavior.get("anomalous_login") else 0.0,
        "session_duration_anomaly": 0.2 if behavior.get("short_session") else 0.0,
        "navigation_pattern_anomaly":
            0.25 if behavior.get("anomalous_navigation") else 0.0,
    }, "user_behavior_context", behavior)


def txn_user_behavior_join() -> WindowJoin:
    """txn x user-behavior, keyBy user, tumbling 5m (:29-49)."""
    return WindowJoin(
        "txn_user_behavior", TumblingWindow(300.0),
        lambda t: str(t.get("user_id")), lambda b: str(b.get("user_id")),
        _join_user_behavior)


# ------------------------------------------------------------ join function 2
def _join_merchant_update(txn: Event, update: Event) -> Dict[str, Any]:
    """TransactionMerchantUpdateJoinFunction (:218-244): risk-level increase
    0.4, fraud-rate increase 0.3, newly blacklisted 0.8."""
    return _enrich(txn, {
        "merchant_risk_increase":
            0.4 if update.get("risk_level_increased") else 0.0,
        "merchant_fraud_rate_increase":
            0.3 if update.get("fraud_rate_increased") else 0.0,
        "merchant_newly_blacklisted":
            0.8 if update.get("newly_blacklisted") else 0.0,
    }, "merchant_update_context", update)


def txn_merchant_update_join() -> WindowJoin:
    """txn x merchant-profile-update, keyBy merchant, tumbling 10m (:52-76)."""
    return WindowJoin(
        "txn_merchant_update", TumblingWindow(600.0),
        lambda t: str(t.get("merchant_id")),
        lambda u: str(u.get("merchant_id")),
        _join_merchant_update)


# ------------------------------------------------------------ join function 3
def historical_pattern_key(payment_method: Any, category: Any,
                           amount: float) -> str:
    """Composite pattern key (TransactionPatternKeySelector, :160-170):
    payment method, merchant category, amount rounded down to 100s."""
    return (f"{payment_method or 'unknown'}:{category or 'unknown'}:"
            f"{math.floor(float(amount or 0.0) / 100) * 100:.0f}")


def pattern_similarity(txn: Event, pattern: Event) -> float:
    """calculatePatternSimilarity (:278-301): payment-method 0.3 + amount
    closeness 0.4 + hour-of-day closeness 0.3, capped at 1."""
    sim = 0.0
    if txn.get("payment_method") and (
            txn.get("payment_method") == pattern.get("payment_method")):
        sim += 0.3
    t_amount = float(txn.get("amount") or 0.0)
    p_amount = float(pattern.get("amount_range") or 0.0)
    denom = max(t_amount, p_amount)
    if denom > 0:
        sim += max(0.0, 1.0 - abs(t_amount - p_amount) / denom) * 0.4
    t_hour, p_hour = txn.get("hour_of_day"), pattern.get("hour_of_day")
    if t_hour is not None and p_hour is not None:
        sim += max(0.0, 1.0 - abs(int(t_hour) - int(p_hour)) / 12.0) * 0.3
    return min(1.0, sim)


def _join_historical_pattern(txn: Event, pattern: Event) -> Dict[str, Any]:
    """TransactionHistoricalPatternJoinFunction (:246-276)."""
    fraud_rate = float(pattern.get("fraud_rate") or 0.0)
    factors = {
        "historical_pattern_similarity":
            pattern_similarity(txn, pattern) * fraud_rate,
    }
    if pattern.get("recent_pattern") and fraud_rate > 0.5:
        factors["recent_high_fraud_pattern"] = 0.4
    if int(pattern.get("occurrence_count") or 0) > 100 and fraud_rate > 0.3:
        factors["frequent_fraud_pattern"] = 0.3
    return _enrich(txn, factors, "historical_pattern_context", pattern)


def txn_historical_pattern_join() -> WindowJoin:
    """txn x historical-fraud-pattern, keyed by the composite pattern key,
    tumbling 1h, pattern side with a 1m watermark (:79-103)."""
    def txn_key(t: Event) -> str:
        return historical_pattern_key(
            t.get("payment_method"), t.get("merchant_category"),
            float(t.get("amount") or 0.0))

    def pattern_key(p: Event) -> str:
        return historical_pattern_key(
            p.get("payment_method"), p.get("merchant_category"),
            float(p.get("amount_range") or 0.0))

    return WindowJoin(
        "txn_historical_pattern", TumblingWindow(3600.0),
        txn_key, pattern_key, _join_historical_pattern,
        right_ooo_s=PATTERN_OOO_S)


# -------------------------------------------------------------- correlation
class MultiStreamCorrelator:
    """Per-user complex-event correlation across four streams
    (connectMultipleStreams, :106-127 — the reference's
    MultiStreamCorrelationFunction does not exist; semantics designed here).

    Keeps a rolling horizon of behavior / device / network events per user;
    each transaction is correlated against them and emits a ComplexEvent
    when at least ``min_signals`` anomalous signals coincide:
    anomalous behavior, a new/changed device, and a risky network origin.
    """

    def __init__(self, horizon_s: float = 300.0, min_signals: int = 2,
                 max_events_per_user: int = 50,
                 sweep_interval_events: int = 10_000):
        self.horizon_s = horizon_s
        self.min_signals = min_signals
        self.max_events = max_events_per_user
        self.sweep_interval = sweep_interval_events
        self._behavior: Dict[str, deque] = {}
        self._device: Dict[str, deque] = {}
        self._network: Dict[str, deque] = {}
        self._max_ts = -math.inf
        self._ops_since_sweep = 0
        self.emitted = 0

    def _push(self, table: Dict[str, deque], user: str, event: Event,
              ts: float) -> None:
        q = table.setdefault(user, deque(maxlen=self.max_events))
        q.append((ts, dict(event)))
        self._max_ts = max(self._max_ts, ts)
        self._ops_since_sweep += 1
        if self._ops_since_sweep >= self.sweep_interval:
            self.sweep()

    def sweep(self) -> int:
        """Evict users whose newest event fell behind the horizon — bounds
        memory at (active users in horizon) x max_events instead of growing
        with all-time user cardinality."""
        cutoff = self._max_ts - self.horizon_s
        evicted = 0
        for table in (self._behavior, self._device, self._network):
            stale = [u for u, q in table.items() if not q or q[-1][0] < cutoff]
            for u in stale:
                del table[u]
                evicted += 1
        self._ops_since_sweep = 0
        return evicted

    def on_behavior(self, event: Event, ts: float) -> None:
        self._push(self._behavior, str(event.get("user_id")), event, ts)

    def on_device(self, event: Event, ts: float) -> None:
        self._push(self._device, str(event.get("user_id")), event, ts)

    def on_network(self, event: Event, ts: float) -> None:
        self._push(self._network, str(event.get("user_id")), event, ts)

    def _recent(self, table: Dict[str, deque], user: str,
                ts: float) -> List[Event]:
        return [e for (t, e) in table.get(user, ())
                if ts - self.horizon_s <= t <= ts]

    def on_transaction(self, txn: Event,
                       ts: float) -> Optional[Dict[str, Any]]:
        user = str(txn.get("user_id"))
        behavior = self._recent(self._behavior, user, ts)
        device = self._recent(self._device, user, ts)
        network = self._recent(self._network, user, ts)

        signals: Dict[str, Any] = {}
        if any(b.get("anomalous_login") or b.get("anomalous_navigation")
               for b in behavior):
            signals["anomalous_behavior"] = True
        if any(d.get("is_new_device") or d.get("fingerprint_changed")
               for d in device):
            signals["device_change"] = True
        if any(n.get("is_proxy") or n.get("is_vpn")
               or n.get("country_mismatch") for n in network):
            signals["risky_network"] = True
        if float(txn.get("amount") or 0.0) > 5000:
            signals["large_amount"] = True

        if len(signals) < self.min_signals:
            return None
        self.emitted += 1
        return {
            "event_type": "COMPLEX_CORRELATION",
            "transaction_id": txn.get("transaction_id"),
            "user_id": user,
            "signals": signals,
            "signal_count": len(signals),
            "correlated_events": {
                "behavior": len(behavior),
                "device": len(device),
                "network": len(network),
            },
            "timestamp": ts,
        }

"""The streaming scoring job: this framework's FraudDetectionJob.

Equivalent of the reference's Flink job graph (FraudDetectionJob.java:33-106)
*with the ML seam actually wired* (the reference never connects Flink to the
ML service — SURVEY.md §0.3):

    payment-transactions ──▶ microbatch assembler ──▶ FraudScorer (TPU)
        ├─▶ fraud-predictions   (every scored txn; §2.7 response schema)
        ├─▶ fraud-alerts        (fraud_score > alert threshold 0.7,
        │                        FraudDetectionJob.java:66-81)
        ├─▶ transaction-enriched (txn + score/decision fields)
        └─▶ transaction-features (the 64-wide §2.3 vector)

Offsets are committed only AFTER all produces + state write-back — crash
replays the uncommitted tail, and replayed transaction_ids are deduplicated
against the scorer's transaction cache (at-least-once delivery, effectively-
once scoring).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from realtime_fraud_detection_tpu.scoring.scorer import FraudScorer
from realtime_fraud_detection_tpu.serving.validation import sanitize_for_stream
from realtime_fraud_detection_tpu.state.stores import _event_time_ms
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.microbatch import MicrobatchAssembler
from realtime_fraud_detection_tpu.stream.transport import (
    FaultInjector,
    InMemoryBroker,
    Record,
)
from realtime_fraud_detection_tpu.stream.windows import WindowedAnalytics


@dataclasses.dataclass
class JobConfig:
    """Streaming-job parameters (reference JobConfig.java:14-200 analog)."""

    group_id: str = "fraud-detection-job"
    max_batch: int = 256
    max_delay_ms: float = 5.0
    alert_threshold: float = 0.7      # FraudDetectionJob.java:66
    emit_features: bool = True
    emit_enriched: bool = True
    # attach the windowed-analytics stage (the reference built its
    # WindowProcessor but never wired it into the job graph — SURVEY.md §0.3)
    enable_analytics: bool = False
    # blend the 6-category feature score 60/40 into the enriched output
    # (FeatureEnrichmentProcessor semantics — also built-but-unwired in the
    # reference, FeatureEnrichmentProcessor.java:84-150)
    enable_enrichment: bool = False
    # how many dispatched microbatches may be in flight before the oldest is
    # completed. 2 overlaps host assembly with device compute; 3 additionally
    # overlaps the device->host result transfer with a full batch period —
    # on a remote/tunneled TPU that transfer costs a network RTT, so depth 3
    # takes it off the critical path (r4 soak measurements). Completion
    # stays in dispatch order; commit-after-fan-out semantics are unchanged.
    # TRADEOFF: state write-back (velocity/txn-cache) for a batch happens at
    # completion, so a batch is assembled while up to depth-1 earlier
    # batches' write-backs are pending — at depth D a user's transactions
    # landing in D consecutive microbatches see velocity counts missing up
    # to D-1 batches' updates (vs 1 at the default depth 2). Raise depth for
    # throughput soaks; keep 2 where freshest velocity features matter.
    pipeline_depth: int = 2
    # overlapped host assembly (scoring/host_pipeline.AssemblerStage): a
    # background thread runs assemble+dispatch for batch N+1 while this
    # thread waits out batch N's device time in finalize — 2-stage software
    # pipelining of the host→device seam. Admission/dedupe/ladder stay on
    # THIS thread (decisions are never reordered or dropped); the velocity-
    # staleness tradeoff is the same as pipeline_depth's, but the exact
    # interleaving of batch N's write-back with batch N+1's assembly
    # becomes timing-dependent — keep off where bit-reproducible replays
    # matter, on for throughput.
    overlap_assembly: bool = False
    # device-pool scoring plane (scoring/device_pool.py): replicate the
    # scorer's params onto every addressable device and dispatch whole
    # microbatches round-robin across per-device in-flight queues — the
    # multi-chip throughput lever (one chip idles seven on a v5e-8
    # otherwise). Scores stay bit-identical to single-device; completion
    # (fan-out + commit) stays FIFO. The run loops raise their in-flight
    # window to the pool's capacity (devices x inflight_depth) so every
    # replica receives work — the velocity-staleness tradeoff documented
    # at pipeline_depth scales with that window.
    device_pool: bool = False
    # per-replica in-flight depth (>= 2 keeps each device's compute
    # back-to-back while the next batch's H2D stages)
    inflight_depth: int = 2
    # deadline-aware QoS plane (qos/): admission control, per-transaction
    # latency budgets (the assembler closes batches early when the oldest
    # waiter's budget runs low), and the degradation ladder fed by the
    # backlog signal (consumer lag + pipelined in-flight records). None or
    # enabled=False = the plane is off, behavior unchanged.
    qos: Optional[Any] = None            # utils.config.QosSettings
    # continuous-learning plane (feedback/): a FeedbackPlane instance the
    # job feeds after every completed batch (emitted predictions +
    # assembled feature rows into the label join / drift monitor) and
    # whose labels topic it drains in the run loops. None = off.
    feedback: Optional[Any] = None       # feedback.FeedbackPlane
    # tracing plane (obs/tracing.py): a TracingSettings (or a live Tracer)
    # — every admitted transaction gets a trace context riding the batch
    # through dispatch/completion into the flight recorder; sheds get a
    # terminal `shed` trace. None or enabled=False = off, and the scoring
    # path pays one `is None` branch per batch (the measured no-op path).
    tracing: Optional[Any] = None        # utils.config.TracingSettings|Tracer
    # distributed tracing: when True, every consumed record is EXPECTED to
    # carry a producer-stamped trace carrier (obs.tracing.CARRIER_KEY in
    # the raw record value); a record without a parseable one opens a
    # fresh root trace counted in the tracer's carrier_lost — the
    # netfault-dropped-frame degradation contract. False (default) means
    # carriers are adopted opportunistically when present, never counted
    # as lost when absent (single-process deployments stay quiet).
    expect_carrier: bool = False
    # self-tuning host pipeline (tuning/): a TuningSettings (or a live
    # TuningPlane) — the assembler's close decisions move from the fixed
    # deadline to the arrival-aware just-in-time controller, and the
    # online tuner adjusts the max-wait bound / bucket set / in-flight
    # depth from completed-batch observations. None or enabled=False =
    # off, and batch-close decisions are BIT-IDENTICAL to the fixed-
    # deadline path (the assembler takes the controller branch only when
    # one is attached).
    autotune: Optional[Any] = None       # utils.config.TuningSettings|plane
    labels_topic: str = T.LABELS
    # topic names (reference JobConfig.java topic parameters); defaults are
    # the §2.5 contract (stream/topics.py) — overridable per deployment,
    # e.g. the reference's test-transactions topic for shadow traffic
    transactions_topic: str = T.TRANSACTIONS
    predictions_topic: str = T.PREDICTIONS
    alerts_topic: str = T.ALERTS
    enriched_topic: str = T.ENRICHED
    features_topic: str = T.FEATURES


@dataclasses.dataclass
class _BatchCtx:
    """A microbatch between dispatch and completion (device in flight)."""

    fresh: List[Record]
    ids: set
    pending: Any                      # scoring.scorer.PendingScore | None
    positions: Dict[tuple, int]       # offsets to commit at completion
    now: Optional[float]
    # records rejected by per-record ingest sanitization; each gets its own
    # error result at completion — they never poison the rest of the batch
    invalid: List[tuple] = dataclasses.field(default_factory=list)
    # txn-cache duplicates: (record, cached result) pairs. State write-back
    # happens BEFORE fan-out (finalize order), so a crash between the two
    # leaves a record cached but its prediction never produced; on replay
    # the dedupe path re-emits the prediction from the cache instead of
    # silently swallowing it. Predictions are thereby at-least-once while
    # scoring + state stay effectively-once (consumers dedupe by txn id).
    cached_dups: List[tuple] = dataclasses.field(default_factory=list)
    # QoS admission sheds: (record, AdmissionDecision) pairs. Each gets an
    # explicit score-with-reason on the predictions topic at completion —
    # a shed is a recorded decision, never a silent drop.
    shed: List[tuple] = dataclasses.field(default_factory=list)
    # tracing plane: this batch's TraceBatch carrier (None = tracing off)
    trace: Optional[Any] = None
    # dispatch instant on the record-timestamp clock base (wall in
    # production, virtual in drills): the tuning plane's service-time
    # observation is completion minus this
    t_dispatch: float = 0.0


class StreamJob:
    """Consume → score → fan out → commit. One instance per process.

    The run loops keep up to ``JobConfig.pipeline_depth`` microbatches in
    flight: while the device computes batch N, the host polls + assembles +
    dispatches later batches, completing (fan-out + offset commit) strictly
    in dispatch order. Depth 2 overlaps host work with device compute;
    depth 3 additionally overlaps the result transfer with a full batch
    period (see JobConfig.pipeline_depth for the staleness tradeoff).
    """

    def __init__(
        self,
        broker: InMemoryBroker,
        scorer: FraudScorer,
        config: Optional[JobConfig] = None,
        faults: Optional[FaultInjector] = None,
    ):
        self.broker = broker
        self.scorer = scorer
        self.config = config or JobConfig()
        self.consumer = broker.consumer(
            [self.config.transactions_topic], self.config.group_id, faults
        )
        # QoS plane: admission + ladder + budget (qos/plane.py); the
        # assembler consults the budget so batches close early when the
        # oldest waiter's remaining deadline drops under the margin
        self.qos = None
        qs = self.config.qos
        if qs is not None and getattr(qs, "enabled", False):
            from realtime_fraud_detection_tpu.qos import QosPlane

            self.qos = qs if isinstance(qs, QosPlane) else QosPlane(qs)
        # self-tuning plane: the assembler consults its just-in-time
        # controller instead of the fixed deadline; the run loops re-read
        # its recommended in-flight depth each iteration
        self.tuning = None
        ts = self.config.autotune
        if ts is not None and getattr(ts, "enabled", False):
            from realtime_fraud_detection_tpu.tuning import TuningPlane

            self.tuning = ts if isinstance(ts, TuningPlane) \
                else TuningPlane(ts)
        self.assembler = MicrobatchAssembler(
            self.consumer,
            max_batch=self.config.max_batch,
            max_delay_ms=self.config.max_delay_ms,
            budget=self.qos.budget if self.qos is not None else None,
            controller=self.tuning,
        )
        self.analytics = (
            WindowedAnalytics(broker) if self.config.enable_analytics else None
        )
        # continuous-learning plane: its own consumer group on the labels
        # topic (labels are a separate stream with its own offsets — a
        # replayed label batch must not disturb transaction offsets)
        self.feedback = self.config.feedback
        self._labels_consumer = None
        if self.feedback is not None:
            self._labels_consumer = broker.consumer(
                [self.config.labels_topic],
                f"{self.config.group_id}-labels")
        # device pool: replicate params onto every addressable device; the
        # scorer's dispatch_assembled routes through it from here on. An
        # already-attached pool (caller-constructed) is respected. getattr:
        # drills drive this job with duck-typed scorer stand-ins
        self.pool = getattr(scorer, "pool", None)
        if self.config.device_pool and self.pool is None:
            from realtime_fraud_detection_tpu.scoring import DevicePool

            self.pool = DevicePool(
                scorer, inflight_depth=self.config.inflight_depth)
        # overlapped host assembly: scorer.dispatch moves to a background
        # stage thread; this thread keeps admission/dedupe/commit order
        self._stage = None
        if self.config.overlap_assembly:
            from realtime_fraud_detection_tpu.scoring.host_pipeline import (
                AssemblerStage,
            )

            self._stage = AssemblerStage(
                scorer, depth=max(1, self.config.pipeline_depth))
        # tracing plane: per-transaction flight recorder + SLO burn rate.
        # A live Tracer is adopted (the drills pass a virtual-clock one);
        # TracingSettings with enabled=True constructs one here.
        self.tracer = None
        tr = self.config.tracing
        if tr is not None:
            from realtime_fraud_detection_tpu.obs.tracing import Tracer

            if isinstance(tr, Tracer):
                self.tracer = tr if tr.enabled else None
            elif getattr(tr, "enabled", False):
                self.tracer = Tracer(tr)
        self.counters: Dict[str, int] = {
            "scored": 0, "alerts": 0, "batches": 0, "duplicates_skipped": 0,
            "errors": 0, "shed": 0,
        }
        # transaction_ids dispatched but not yet written back: the pipelined
        # loop dedupes batch N+1 against these before batch N lands in the
        # txn cache (keeps effectively-once scoring under pipelining)
        self._inflight_ids: set = set()
        # graceful-shutdown seam (cli.py installs SIGTERM/SIGINT handlers
        # that set this): the run loops stop POLLING but still complete
        # every dispatched batch and commit its offsets — a signal drains
        # the in-flight tail instead of losing it to replay-on-restart
        self.stop_requested = False

    def request_stop(self) -> None:
        """Ask the run loops to drain in-flight microbatches, commit, and
        return (signal-handler safe: one attribute write)."""
        self.stop_requested = True

    def _inflight_depth(self) -> int:
        """Run-loop in-flight window: the configured pipeline depth, set
        to the device pool's capacity when one is attached — a window
        smaller than devices x depth would leave replicas starved, and a
        window LARGER than capacity would deadlock the single-threaded
        run loop (the executor's dispatch blocks for a slot that only
        this loop's own finalize can free; a 1-replica MeshExecutor at
        depth 2 under a configured depth 3 hit exactly this). With the
        tuning plane attached, its online-tuned depth replaces the
        configured one (re-read every loop iteration, so a tuner move
        takes effect one batch later); an attached pool's capacity still
        overrides — it IS the hardware window."""
        depth = max(1, self.config.pipeline_depth)
        if self.tuning is not None:
            depth = max(1, self.tuning.recommended_inflight_depth())
        if self.pool is not None:
            depth = self.pool.total_slots()
        return depth

    # ----------------------------------------------------------------- steps
    def process_batch(self, records: List[Record],
                      now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Score one microbatch and fan results out to the output topics."""
        ctx = self.dispatch_batch(records, now=now)
        return self.complete_batch(ctx) if ctx is not None else []

    def dispatch_batch(self, records: List[Record],
                       now: Optional[float] = None) -> Optional["_BatchCtx"]:
        """Stage 1 of the pipelined step: dedupe + launch on device.

        Returns without blocking on the device — the caller overlaps the
        next batch's poll/assembly with this batch's compute and calls
        ``complete_batch`` (in dispatch order) to fan out + commit. Offsets
        are snapshotted HERE so a later poll can't advance what this
        batch's commit covers.
        """
        if not records:
            return None
        fresh: List[Record] = []
        invalid: List[tuple] = []
        cached_dups: List[tuple] = []
        shed: List[tuple] = []
        trace_ctxs: List[Any] = []
        tracer = self.tracer
        batch_ids: set = set()
        # rtfd-lint: allow[wall-clock] production default time base; drills pass now
        t_adm = now if now is not None else time.time()

        def _ingest_lag(rec: Record) -> float:
            # upstream-of-admission lag: gateway ingest stamp when present
            # (IngressGateway stamp_ingest), else the broker produce
            # timestamp — wall-minus-wall (or virtual-minus-virtual in the
            # drills), never mixed with the tracer's monotonic base
            src = None
            if isinstance(rec.value, dict):
                src = rec.value.get("ingest_ts")
            if src is None:
                src = rec.timestamp
            try:
                return max(0.0, t_adm - float(src)) if src is not None \
                    else 0.0
            except (TypeError, ValueError):
                return 0.0

        expect_carrier = self.config.expect_carrier

        def _carrier(rec: Record) -> Any:
            # read from the RAW record value (the ingest_ts precedent):
            # sanitize strips unknown fields, so the carrier must be
            # lifted before the sanitized copy replaces the value
            return rec.value.get("trace_carrier") \
                if isinstance(rec.value, dict) else None

        for r in records:
            txn, errors = sanitize_for_stream(r.value)
            if errors:
                # per-record degradation (TransactionProcessor.java:83-91):
                # one poisoned record must not drag its batch-mates onto
                # the error path — it alone gets an error result
                invalid.append((r, errors))
                continue
            txn_id = txn["transaction_id"]  # sanitizer guarantees non-empty
            if txn_id in batch_ids or txn_id in self._inflight_ids:
                # first instance (this batch / a dispatched batch) will
                # emit the prediction itself — skip silently
                self.counters["duplicates_skipped"] += 1
                continue
            cached = self.scorer.txn_cache.get_transaction(txn_id, now=now)
            if cached is not None:
                # already scored + written back. Its prediction may never
                # have been produced (crash between write-back and
                # fan-out), so re-emit from the cache at completion —
                # at-least-once predictions, no re-scoring, no
                # double-counted velocity. batch_ids gets the id so a
                # second copy in this same poll re-emits only once.
                self.counters["duplicates_skipped"] += 1
                batch_ids.add(txn_id)
                cached_dups.append((r, cached))
                continue
            priority = ""
            if self.qos is not None:
                # admission AFTER dedupe (a replayed duplicate must not
                # burn tokens) and BEFORE dispatch: a shed is an explicit
                # decision recorded at completion, never a silent drop
                decision = self.qos.admit(txn, t_adm)
                priority = decision.priority
                if not decision.admitted:
                    self.counters["shed"] += 1
                    shed.append((dataclasses.replace(r, value=txn),
                                 decision))
                    if tracer is not None:
                        # a shed is a recorded terminal trace, not a gap
                        tracer.finish_terminal(
                            tracer.begin(txn_id,
                                         ingest_lag_s=_ingest_lag(r),
                                         priority=decision.priority,
                                         carrier=_carrier(r),
                                         now_wall=t_adm,
                                         expect_carrier=expect_carrier),
                            "shed", reason=decision.reason,
                            priority=decision.priority)
                    continue
            batch_ids.add(txn_id)
            fresh.append(dataclasses.replace(r, value=txn))
            if tracer is not None:
                trace_ctxs.append(
                    tracer.begin(txn_id, ingest_lag_s=_ingest_lag(r),
                                 priority=priority, carrier=_carrier(r),
                                 now_wall=t_adm,
                                 expect_carrier=expect_carrier))
        positions = self.consumer.snapshot_positions()
        if self.qos is not None:
            # backlog signal, one ladder observation per dispatched
            # microbatch: consumer lag counts everything not yet COMMITTED
            # — the unread topic backlog plus every pipelined in-flight
            # batch (commit happens at completion) — minus THIS batch,
            # which is being handled right now, not waiting
            self.qos.observe_backlog(
                max(0, self.consumer.lag() - len(records)))
            if self._stage is not None:
                # a ladder step writes the scorer's qos mask + rules_only
                # flag; the stage thread reads both at dispatch — take the
                # stage lock so one batch never sees a torn pair
                with self._stage.lock:
                    self.qos.apply_degradation(self.scorer)
            else:
                # rtfd-lint: allow[lock-order] stream job is single-writer: consume, score, QoS share one thread
                self.qos.apply_degradation(self.scorer)
        if not fresh:
            return _BatchCtx([], set(), None, positions, now, invalid,
                             cached_dups, shed)
        trace = None
        if tracer is not None:
            trace = tracer.batch(
                trace_ctxs, batch_size=len(fresh),
                close_reason=self.assembler.last_close_reason)
        pending = None
        try:
            # the trace kwarg is passed ONLY when tracing is live: drills
            # and tests drive this job with duck-typed scorer stand-ins
            # whose dispatch() may not know the parameter, and an
            # unexpected-kwarg TypeError here would silently take the
            # whole-batch degradation path
            kw = {"trace": trace} if trace is not None else {}
            if self._stage is not None:
                # background assembly: the handle resolves to a
                # PendingScore at completion; errors surface there and take
                # the same whole-batch degradation path. The trace rides
                # the queue item, so the stage thread's marks land on the
                # batch they belong to (identity, not timing).
                pending = self._stage.submit([r.value for r in fresh],
                                             now=now, **kw)
            else:
                pending = self.scorer.dispatch([r.value for r in fresh],
                                               now=now, **kw)
        except Exception:
            # whole-batch degradation fallback: score 0.5, REVIEW, keep the
            # stream alive; counted at completion
            pass
        self._inflight_ids |= batch_ids
        return _BatchCtx(fresh, batch_ids, pending, positions, now, invalid,
                         cached_dups, shed, trace, t_adm)

    def complete_batch(self, ctx: "_BatchCtx",
                       now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Stage 2: block on the device result, fan out, commit offsets.

        ``now`` is the COMPLETION time (for QoS budget accounting on the
        drill's virtual clock); ``ctx.now`` remains the dispatch-time
        event clock for state TTLs. Default None = wall clock.
        """
        cfg = self.config
        fresh = ctx.fresh
        t_done = now if now is not None else (
            # rtfd-lint: allow[wall-clock] production default time base; drills pass now
            ctx.now if ctx.now is not None else time.time())
        now = ctx.now
        if not fresh:
            invalid_results = self._emit_invalid(ctx)  # no ids at risk
            self._emit_shed(ctx)
            self._emit_cached_dups(ctx)
            self.consumer.commit(ctx.positions)
            return invalid_results

        scored_ok, results, feats = False, None, None
        if ctx.pending is not None:
            try:
                pending = ctx.pending
                if self._stage is not None and hasattr(pending, "result"):
                    # overlapped mode: join the background assembly; an
                    # assembly/dispatch error takes the same whole-batch
                    # degradation path as a finalize error
                    pending = pending.result()
                results = self.scorer.finalize(
                    pending, now=now,
                    lock=self._stage.lock if self._stage is not None
                    else None)
                feats = pending.features
                scored_ok = True
            except Exception:
                results = None
        if results is None:
            self.counters["errors"] += len(fresh)
            results = [
                {
                    "transaction_id": str(r.value.get("transaction_id", "")),
                    "fraud_probability": 0.5,
                    "fraud_score": 0.5,
                    "risk_level": "ERROR",
                    "decision": "REVIEW",
                    "model_predictions": {},
                    "confidence": 0.0,
                    "processing_time_ms": 0.0,
                    "explanation": {"error": True},
                }
                for r in fresh
            ]

        if self.qos is not None:
            self.qos.record_scored(len(fresh))
            for r in fresh:
                # budget headroom at completion, from the record's ingest
                # timestamp (negative = deadline blown; explicit None
                # check — t=0.0 is a legitimate virtual-clock timestamp)
                self.qos.record_completion(
                    r.timestamp if r.timestamp is not None else t_done,
                    t_done)
        try:
            # inside the protective try: a produce failure here must release
            # the in-flight ids like any other fan-out failure
            invalid_results = self._emit_invalid(ctx)
            self._emit_shed(ctx)
            self._emit_cached_dups(ctx)
            out = invalid_results + self._fan_out(
                ctx, fresh, results, feats, scored_ok, now)
            burn = None
            if ctx.trace is not None and self.tracer is not None:
                # emit complete: close every trace in the batch (the
                # per-txn e2e/SLO observation happens here), then consult
                # the SLO burn gate — latency can burn the error budget
                # without the backlog signal ever tripping
                self.tracer.finish_batch(
                    ctx.trace, terminal="scored" if scored_ok else "error")
                # burn rate and trace completion share the tracer's
                # clock (virtual in the drills), so no ``now`` is
                # passed — one time base end to end. Computed once: the
                # QoS gate and the tuning plane both consume it.
                ts = self.tracer.settings
                burn = self.tracer.slo.burn_rate(ts.slo_fast_window_s)
                if self.qos is not None:
                    self.qos.observe_slo_burn(
                        burn,
                        threshold=ts.slo_burn_threshold,
                        patience=ts.slo_gate_patience,
                        up_patience=ts.slo_gate_up_patience)
            if self.tuning is not None:
                # close the tuning loop: the batch's dispatch→complete
                # duration feeds the controller's T(bucket) model, the
                # per-txn completion latencies feed the tuner's
                # admitted-p99 objective, and the SLO burn + ladder level
                # gate it (the tuner freezes during an emergency — it
                # never fights the QoS ladder)
                lat = [max(0.0, t_done - r.timestamp) * 1e3
                       for r in fresh if r.timestamp is not None]
                self.tuning.on_batch_complete(
                    len(fresh), max(0.0, t_done - ctx.t_dispatch), t_done,
                    latencies_ms=lat,
                    burn_rate=burn if burn is not None else 0.0,
                    ladder_level=(self.qos.effective_level()
                                  if self.qos is not None else 0))
            if self.feedback is not None and scored_ok:
                # feed the label join with exactly what was emitted, plus
                # the assembled feature rows (the retrain corpus), then
                # drain any due labels and run the cheap policy check —
                # the expensive retrain stays with the caller (react)
                self.feedback.on_predictions(
                    [r.value for r in fresh], results,
                    features=feats[:len(fresh)] if feats is not None
                    else None,
                    now=t_done)
                self.drain_labels()
                self.feedback.check_trigger(now=t_done)
            return out
        finally:
            # ALWAYS release, even when fan-out raises mid-way (broker down):
            # a leaked id makes the replayed record look like an in-flight
            # duplicate, so it would be skipped and the next commit would
            # advance past it — silent record loss (ADVICE r2). With the ids
            # released, an uncommitted batch replays and rescans normally
            # (txn-cache dedupe still guards the already-written-back case).
            self._inflight_ids -= ctx.ids

    def _emit_invalid(self, ctx: "_BatchCtx") -> List[Dict[str, Any]]:
        """Per-record error results for sanitization rejects: produced to
        the predictions topic so downstream sees a REVIEW decision, never a
        silent gap. Covered by this batch's offset commit."""
        results = []
        items = []
        for rec, errors in ctx.invalid:
            value = rec.value if isinstance(rec.value, dict) else {}
            res = {
                "transaction_id": str(value.get("transaction_id", "")),
                "fraud_probability": 0.5,
                "fraud_score": 0.5,
                "risk_level": "ERROR",
                "decision": "REVIEW",
                "model_predictions": {},
                "confidence": 0.0,
                "processing_time_ms": 0.0,
                "explanation": {"error": True, "validation_errors": errors},
            }
            self.counters["errors"] += 1
            items.append((str(value.get("user_id", "")), res))
            results.append(res)
        if items:
            self.broker.produce_batch_keyed(self.config.predictions_topic,
                                            items)
        return results

    def _emit_shed(self, ctx: "_BatchCtx") -> None:
        """Produce an explicit score-with-reason for every shed record
        (qos.QosPlane.shed_result): downstream sees a REVIEW with the shed
        reason and priority class in the explanation — load shedding is an
        auditable decision, not record loss. Covered by this batch's
        offset commit."""
        if not ctx.shed or self.qos is None:
            return
        items = []
        for rec, decision in ctx.shed:
            value = rec.value if isinstance(rec.value, dict) else {}
            items.append((str(value.get("user_id", "")),
                          self.qos.shed_result(value, decision)))
        self.broker.produce_batch_keyed(self.config.predictions_topic, items)

    def _emit_cached_dups(self, ctx: "_BatchCtx") -> None:
        """Re-emit predictions for txn-cache duplicates from their cached
        results. A record lands here only if it was scored AND written back
        previously; whether its prediction was actually produced before a
        crash is unknowable, so re-emitting is the at-least-once answer —
        downstream consumers dedupe by transaction_id."""
        items = []
        for rec, cached in ctx.cached_dups:
            value = rec.value if isinstance(rec.value, dict) else {}
            items.append((
                str(value.get("user_id", "")),
                {
                    "transaction_id": str(cached.get("transaction_id") or
                                          value.get("transaction_id", "")),
                    "fraud_probability": float(cached.get("fraud_score", 0.5)),
                    "fraud_score": float(cached.get("fraud_score", 0.5)),
                    "risk_level": str(cached.get("risk_level", "UNKNOWN")),
                    "decision": str(cached.get("decision", "REVIEW")),
                    "model_predictions": {},
                    "confidence": float(cached.get("confidence", 0.0)),
                    "processing_time_ms": 0.0,
                    "explanation": {"replayed_from_cache": True},
                },
            ))
        if items:
            self.broker.produce_batch_keyed(self.config.predictions_topic,
                                            items)

    def _fan_out(self, ctx: "_BatchCtx", fresh: List[Record],
                 results: List[Dict[str, Any]], feats, scored_ok: bool,
                 now: Optional[float]) -> List[Dict[str, Any]]:
        """Enrich + produce to output topics + commit (stage-2 tail)."""
        cfg = self.config
        enriched_scores = None
        wants_enriched = cfg.emit_enriched or self.analytics is not None
        if cfg.enable_enrichment and scored_ok and wants_enriched:
            import numpy as np

            from realtime_fraud_detection_tpu.core.batching import (
                pad_to_bucket,
            )
            from realtime_fraud_detection_tpu.features.rules import (
                DECISIONS as _DECISIONS,
                RISK_LEVEL_NAMES as _RISK,
                blend_enrichment,
            )

            n = len(results)
            prior = np.asarray([r["fraud_score"] for r in results], np.float32)
            # pad to the scoring buckets so blend_enrichment compiles once
            # per bucket, not once per tail-batch size
            (prior_p, feats_p), _, _ = pad_to_bucket(
                (prior, feats[:n]), n)
            blended, dec, risk = blend_enrichment(prior_p, feats_p)
            enriched_scores = (
                np.asarray(blended)[:n],
                [_DECISIONS[i] for i in np.asarray(dec)[:n]],
                [_RISK[i] for i in np.asarray(risk)[:n]],
            )

        # accumulate per topic and flush as ONE batched produce each: over
        # a networked broker, per-record produces cost a round trip apiece
        # (measured 8.6x slower on loopback at batch 256; worse over a
        # real network) — the fan-out is the job's per-record hot loop
        out_preds: List[tuple] = []
        out_alerts: List[tuple] = []
        out_enriched: List[tuple] = []
        out_features: List[tuple] = []
        for i, (rec, res) in enumerate(zip(fresh, results)):
            uid = str(rec.value.get("user_id", ""))
            out_preds.append((uid, res))
            if res["fraud_score"] > cfg.alert_threshold:
                out_alerts.append((uid, self._to_alert(rec.value, res)))
                self.counters["alerts"] += 1
            if cfg.emit_enriched or self.analytics is not None:
                enriched = dict(rec.value)
                enriched.update(
                    fraud_score=res["fraud_score"],
                    risk_level=res["risk_level"],
                    decision=res["decision"],
                )
                if enriched_scores is not None:
                    blended, decisions, risks = enriched_scores
                    enriched.update(
                        fraud_score=float(blended[i]),
                        risk_level=risks[i],
                        decision=decisions[i],
                        ensemble_score=res["fraud_score"],
                    )
                if cfg.emit_enriched:
                    out_enriched.append((uid, enriched))
                if self.analytics is not None:
                    self.analytics.process(
                        enriched, _event_time_ms(enriched, now) / 1000.0)
            # features exist only when scoring succeeded (the error fallback
            # never ran assemble, so there are no feature rows for the batch)
            if cfg.emit_features and scored_ok:
                out_features.append((uid, {
                    "transaction_id": res["transaction_id"],
                    "features": feats[i].tolist()}))
        self.broker.produce_batch_keyed(cfg.predictions_topic, out_preds)
        if out_alerts:
            self.broker.produce_batch_keyed(cfg.alerts_topic, out_alerts)
        if out_enriched:
            self.broker.produce_batch_keyed(cfg.enriched_topic, out_enriched)
        if out_features:
            self.broker.produce_batch_keyed(cfg.features_topic, out_features)
        self.counters["scored"] += len(fresh)
        self.counters["batches"] += 1
        # commit AFTER fan-out + scorer write-back: at-least-once
        self.consumer.commit(ctx.positions)
        return results

    @staticmethod
    def _to_alert(txn: Dict[str, Any], res: Dict[str, Any]) -> Dict[str, Any]:
        """Alert payload (Transaction.toFraudAlert analog, SURVEY.md §2.10)."""
        return {
            "alert_type": "FRAUD_DETECTED",
            "transaction_id": res["transaction_id"],
            "user_id": txn.get("user_id"),
            "merchant_id": txn.get("merchant_id"),
            "amount": txn.get("amount"),
            "fraud_score": res["fraud_score"],
            "risk_level": res["risk_level"],
            "decision": res["decision"],
            "timestamp": txn.get("timestamp"),
        }

    def drain_labels(self, max_records: int = 10_000) -> int:
        """Poll the labels topic into the feedback plane (no-op without
        one). Label offsets commit immediately after ingestion: the join +
        prequential state is process-local anyway, and a replayed label is
        deduplicated by the join."""
        if self.feedback is None or self._labels_consumer is None:
            return 0
        recs = self._labels_consumer.poll(max_records)
        if not recs:
            return 0
        matched = self.feedback.on_labels(
            [r.value for r in recs if isinstance(r.value, dict)])
        self._labels_consumer.commit()
        return matched

    # ------------------------------------------------------------------ run
    def run_until_drained(self, max_batches: int = 10_000,
                          now: Optional[float] = None) -> int:
        """Process until the input topic is fully consumed. Returns #scored."""
        from collections import deque

        start_scored = self.counters["scored"]
        depth = self._inflight_depth()
        in_flight: deque = deque()
        for _ in range(max_batches):
            if self.stop_requested:
                # drain: dispatch the assembler's polled-but-unbatched
                # tail too — those records' offsets are past the last
                # commit snapshot, and leaving them unscored would replay
                # them on every restart (the satellite this seam exists
                # for: SIGTERM loses nothing, only SIGKILL replays)
                tail = self.assembler.flush()
                while tail:
                    in_flight.append(self.dispatch_batch(tail, now=now))
                    tail = self.assembler.flush()
                break
            batch = self.assembler.next_batch(block=False)
            if not batch:
                batch = self.assembler.flush()
            if not batch:
                if in_flight:
                    self.complete_batch(in_flight.popleft())
                    continue
                if self.consumer.lag() == 0:
                    break
                continue
            in_flight.append(self.dispatch_batch(batch, now=now))
            while len(in_flight) >= depth:
                self.complete_batch(in_flight.popleft())
            if self.feedback is not None \
                    and self.feedback.pending_trigger is not None:
                # retrain between batches (the job is a batch process; the
                # serving app instead hands this to a worker thread)
                self.feedback.react(now=now)
        while in_flight:
            self.complete_batch(in_flight.popleft())
        self.drain_labels()
        return self.counters["scored"] - start_scored

    def close(self) -> None:
        """Stop the background assembler stage (no-op without overlap)."""
        if self._stage is not None:
            self._stage.close()

    def run_for(self, duration_s: float) -> int:
        """Process the stream for a wall-clock window (soak-test entry)."""
        from collections import deque

        # rtfd-lint: allow[wall-clock] consume-only slice duration is wall-bound by definition
        t_end = time.monotonic() + duration_s
        start = self.counters["scored"]
        depth = self._inflight_depth()
        in_flight: deque = deque()
        # rtfd-lint: allow[wall-clock] consume-only slice duration is wall-bound by definition
        while time.monotonic() < t_end and not self.stop_requested:
            batch = self.assembler.next_batch(block=True, timeout_s=0.05)
            if batch:
                in_flight.append(self.dispatch_batch(batch))
            if in_flight and (len(in_flight) >= depth or not batch):
                self.complete_batch(in_flight.popleft())
            if self.feedback is not None \
                    and self.feedback.pending_trigger is not None:
                self.feedback.react()
        if self.stop_requested:
            # same drain discipline as run_until_drained: the polled tail
            # is scored + committed, not abandoned to replay
            tail = self.assembler.flush()
            while tail:
                in_flight.append(self.dispatch_batch(tail))
                tail = self.assembler.flush()
        while in_flight:
            self.complete_batch(in_flight.popleft())
        self.drain_labels()
        return self.counters["scored"] - start

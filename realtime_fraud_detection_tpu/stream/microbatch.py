"""Deadline-bounded microbatch assembly: the stream→device seam.

The reference configured (but never exercised) TF-Serving batching with
max_batch 128 / 100 ms timeout (ml-models-deployment.yaml:270-290) and
otherwise scored batch=1 per HTTP request (main.py:235-248). Here the
assembler is a first-class component: it drains a consumer/queue into
microbatches closed by whichever comes first —

- size: the batch reached ``max_batch`` (aligned with the compile-cached
  bucket set, core/batching.BATCH_BUCKETS), or
- deadline: ``max_delay_ms`` passed since the batch's FIRST record arrived
  (the p99-latency budget knob from BASELINE.json: assemble+transfer+compute
  must stay under 20 ms).

A C++ lock-free ring-buffer implementation of the same interface lives in
``native/`` (NativeMicrobatcher); this Python one is the reference
implementation and the fallback.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

from realtime_fraud_detection_tpu.stream.transport import Consumer, Record


class MicrobatchAssembler:
    """Pull-based assembler over a transport consumer."""

    def __init__(
        self,
        consumer: Consumer,
        max_batch: int = 256,
        max_delay_ms: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        idle_sleep_s: float = 0.0005,
        budget=None,
        budget_clock: Callable[[], float] = time.time,
        controller=None,
    ):
        self.consumer = consumer
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        self.clock = clock
        self.idle_sleep_s = idle_sleep_s
        # optional qos.LatencyBudget: a third close trigger — the OLDEST
        # pending record's remaining latency budget (from its ingest
        # timestamp) dropping under the assembly margin. ``budget_clock``
        # must share the record timestamps' time base (wall clock in
        # production, the virtual clock in the overload drill).
        self.budget = budget
        self.budget_clock = budget_clock
        # optional tuning.TuningPlane (or bare JitBatchController):
        # arrival-aware just-in-time closing REPLACES the fixed deadline —
        # arrivals feed its forecaster on every poll (this clock's base),
        # and the close decision weighs waiting for one more txn against
        # the bucket pad-waste curve and the live service-time model.
        # None (the default) keeps close decisions bit-identical to the
        # fixed-deadline path; the budget trigger above ALWAYS runs first,
        # so a controller can never outwait a QoS latency budget.
        self.controller = controller
        self._pending: List[Record] = []
        self._first_ts: Optional[float] = None
        self._oldest_event_ts: Optional[float] = None
        self.batches_emitted = 0
        self.records_emitted = 0
        # why the LAST batch closed (size | deadline | budget | timeout |
        # flush | jit) — tail-attribution metadata for the tracing plane: a
        # deadline-closed size-1 batch and a full size-256 batch have very
        # different per-txn cost profiles. ``close_reasons`` accumulates
        # the full histogram for the Prometheus mirror
        # (obs.metrics.MetricsCollector.sync_microbatch).
        self.last_close_reason: Optional[str] = None
        self.close_reasons: dict = {}

    def _deadline_passed(self) -> bool:
        return (
            self._first_ts is not None
            and (self.clock() - self._first_ts) * 1000.0 >= self.max_delay_ms
        )

    def _budget_low(self) -> bool:
        return (
            self.budget is not None
            and self._oldest_event_ts is not None
            and self.budget.should_close(self._oldest_event_ts,
                                         self.budget_clock())
        )

    def next_batch(self, block: bool = True,
                   timeout_s: Optional[float] = None) -> List[Record]:
        """Assemble the next microbatch.

        Non-blocking mode returns [] when neither the size nor the deadline
        condition holds yet. Blocking mode waits (bounded by ``timeout_s``)
        until a batch closes or the wait times out with whatever is pending.
        """
        wait_start = self.clock()
        while True:
            if len(self._pending) < self.max_batch:
                got = self.consumer.poll(self.max_batch - len(self._pending))
                if got and self._first_ts is None:
                    self._first_ts = self.clock()
                if got and self.budget is not None:
                    # explicit None check: t=0.0 is a legitimate ingest
                    # timestamp (the drill's virtual clock starts there)
                    ts = min((r.timestamp if r.timestamp is not None
                              else self.budget_clock()) for r in got)
                    self._oldest_event_ts = (
                        ts if self._oldest_event_ts is None
                        else min(self._oldest_event_ts, ts))
                if got and self.controller is not None:
                    self.controller.observe(self.clock(), len(got))
                self._pending.extend(got)

            if len(self._pending) >= self.max_batch:
                return self._emit("size")
            if self._pending and self._budget_low():
                return self._emit("budget")
            if self.controller is not None:
                if self._pending:
                    d = self.controller.should_close(
                        len(self._pending), self._first_ts, self.clock())
                    if d.close:
                        return self._emit(d.reason)
            elif self._pending and self._deadline_passed():
                return self._emit("deadline")

            if not block:
                return []
            if timeout_s is not None and self.clock() - wait_start >= timeout_s:
                return self._emit("timeout") if self._pending else []
            time.sleep(self.idle_sleep_s)

    def _emit(self, reason: str = "size") -> List[Record]:
        self.last_close_reason = reason
        self.close_reasons[reason] = self.close_reasons.get(reason, 0) + 1
        batch, self._pending = self._pending[: self.max_batch], self._pending[self.max_batch:]
        self._first_ts = self.clock() if self._pending else None
        if self.budget is not None and self._pending:
            self._oldest_event_ts = min(
                (r.timestamp if r.timestamp is not None
                 else self.budget_clock()) for r in self._pending)
        else:
            self._oldest_event_ts = None
        self.batches_emitted += 1
        self.records_emitted += len(batch)
        return batch

    def flush(self) -> List[Record]:
        """Close and return whatever is pending (drain-on-shutdown)."""
        return self._emit("flush") if self._pending else []


class DoubleBufferedScorer:
    """Overlap host assembly of batch N+1 with device compute of batch N.

    The host→device pipelining analog of the reference's operator pipeline
    (SURVEY.md §2.8: 'the PP analog is host→device pipelining'). The score
    function returns device arrays; blocking on them is deferred one
    iteration so assembly and compute overlap.
    """

    def __init__(self, score_fn: Callable[[List[Record]], Any]):
        self.score_fn = score_fn
        self._in_flight: Optional[tuple] = None

    def submit(self, batch: List[Record]) -> Optional[tuple]:
        """Submit a batch; returns the PREVIOUS (batch, result) now complete."""
        import jax

        done = None
        if self._in_flight is not None:
            prev_batch, prev_result = self._in_flight
            jax.block_until_ready(prev_result)
            done = (prev_batch, prev_result)
        self._in_flight = (batch, self.score_fn(batch)) if batch else None
        return done

    def drain(self) -> Optional[tuple]:
        return self.submit([])

"""The scoring service: §2.7 API surface over the microbatched TPU scorer.

Endpoint parity with the reference FastAPI app (main.py:127-343):

    POST /predict             one transaction  -> FraudPrediction
    POST /batch-predict       list             -> {results, count, ...}
    GET  /health              liveness + model inventory
    GET  /metrics             JSON summary (throughput/latency/decisions)
    GET  /model-info          ensemble weights/strategy/mesh
    POST /reload-models       hot swap (from checkpoint dir or fresh init)
    GET  /metrics/prometheus  text exposition

plus capabilities the reference only promised:

    GET  /drift               feature drift report (config.py:110-116)
    POST /experiments         create an A/B experiment (ab_testing.py analog)
    GET  /experiments?name=   arm metrics + significance

The difference from the reference is the execution model: every concurrent
/predict coalesces through RequestMicrobatcher into ONE fused XLA program
call, instead of 5 asyncio tasks per request at batch=1
(ensemble_predictor.py:166-182), and /batch-predict scores the whole list in
bucketed dense batches instead of a sequential loop (main.py:235-248).
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
from realtime_fraud_detection_tpu.obs import (
    DriftConfig,
    FeatureDriftMonitor,
    MetricsCollector,
)
from realtime_fraud_detection_tpu.scoring import init_scoring_models
from realtime_fraud_detection_tpu.scoring.scorer import FraudScorer
from realtime_fraud_detection_tpu.serving.batcher import RequestMicrobatcher
from realtime_fraud_detection_tpu.serving.httpd import HttpError, HttpServer
from realtime_fraud_detection_tpu.serving.validation import (
    validate_batch,
    validate_transaction,
)
from realtime_fraud_detection_tpu.testing import (
    ABTestManager,
    Variant,
    apply_weight_overrides,
)
from realtime_fraud_detection_tpu.utils.config import Config

__all__ = ["ServingApp"]


class ServingApp:
    """Wire scorer + batcher + obs + experiments behind the HTTP surface."""

    def __init__(self, config: Optional[Config] = None,
                 scorer: Optional[FraudScorer] = None,
                 host: Optional[str] = None, port: Optional[int] = None):
        self.config = config or Config()
        sc = self.config.serving
        self.scorer = scorer if scorer is not None else FraudScorer(self.config)
        self.metrics = MetricsCollector()
        self.drift = FeatureDriftMonitor(DriftConfig(
            num_features=self.scorer.sc.feature_dim))
        self.ab = ABTestManager()
        # deadline-aware QoS plane (qos/): always constructed so /qos can
        # enable it at runtime; admission/ladder only act when enabled.
        # Shares this app's MetricsCollector, so admitted/shed/ladder
        # series ride the existing Prometheus exposition.
        from realtime_fraud_detection_tpu.qos import QosPlane

        self.qos = QosPlane(self.config.qos, metrics=self.metrics)
        # continuous-learning plane (feedback/): always constructed so
        # /labels and /quality/live work out of the box; the join /
        # prequential / retrain machinery only runs when
        # config.feedback.enabled. Shares this app's drift monitor and
        # MetricsCollector; promotion goes through THIS app's score lock —
        # the same recipe /reload-models applies.
        from realtime_fraud_detection_tpu.feedback import FeedbackPlane
        from realtime_fraud_detection_tpu.feedback.plane import (
            promote_candidate,
        )

        self.feedback = FeedbackPlane(
            self.config.feedback, scorer=self.scorer, config=self.config,
            metrics=self.metrics, drift_monitor=self.drift,
            promote_fn=lambda cand: promote_candidate(
                self.scorer, self.config, cand, lock=self._score_lock))
        self._feedback_reacting = False
        # device-pool scoring (serving.device_pool): replicate the model
        # onto every addressable device; dispatches from the microbatcher
        # round-robin across per-device in-flight queues. Implies the
        # two-phase pipelined batcher (several batches must be in flight
        # for the replicas to see work) with its depth raised to the
        # pool's capacity.
        self.pool = getattr(self.scorer, "pool", None)
        if sc.device_pool and self.pool is None:
            from realtime_fraud_detection_tpu.scoring import DevicePool

            self.pool = DevicePool(self.scorer,
                                   inflight_depth=sc.inflight_depth)
        elif self.config.mesh.enabled and self.pool is None:
            # mesh-sharded branch execution (config.mesh / scoring/
            # mesh_executor.py): same dispatch/finalize seam as the pool
            # — the two-phase batcher, QoS masks and hot swap compose
            # unchanged — but each rotation slot is a data x model MESH
            # storing the configured branches sharded
            from realtime_fraud_detection_tpu.scoring import MeshExecutor

            mcfg = self.config.mesh
            self.pool = MeshExecutor(
                self.scorer, model_axis=mcfg.model,
                replicas=mcfg.replicas,
                inflight_depth=mcfg.inflight_depth,
                shard_branches=tuple(mcfg.shard_branches))
        # tracing plane (obs/tracing.py): per-transaction flight recorder
        # + /latency/breakdown + /slo. Constructed only when enabled —
        # the scoring path's no-op cost is one `is None` branch per batch.
        self.tracer = None
        if self.config.tracing.enabled:
            from realtime_fraud_detection_tpu.obs.tracing import Tracer

            self.tracer = Tracer(self.config.tracing)
        # fleet metrics aggregation (obs/fleetmetrics.py): per-worker
        # counter snapshots folded into one exposition at GET
        # /metrics/fleet — a ProcessFleet coordinator (or harness) feeds
        # worker snapshots in through this attribute; this process's own
        # tracer counters fold in at render time under its worker id
        from realtime_fraud_detection_tpu.obs.fleetmetrics import (
            FleetMetrics,
        )

        self.fleet_metrics = FleetMetrics()
        two_phase = sc.overlap_assembly or self.pool is not None
        # self-tuning host pipeline (serving.autotune / config.tuning):
        # the request microbatcher's close decisions move from the fixed
        # deadline to the arrival-aware just-in-time controller; the
        # online tuner reads the tracing plane's burn + the QoS ladder
        # through signals_fn so it freezes during emergencies
        self.tuning = None
        if sc.autotune or self.config.tuning.enabled:
            from realtime_fraud_detection_tpu.tuning import TuningPlane
            from realtime_fraud_detection_tpu.utils.config import (
                TuningSettings,
            )

            fields = {**dataclasses.asdict(self.config.tuning),
                      "enabled": True}
            if not two_phase or self.pool is not None:
                # pin the tuner's in-flight dimension where this path
                # cannot apply it: single-phase serving has no pipeline
                # depth, and with a device pool the depth IS the pool's
                # capacity — leaving the knob free would let the tuner
                # "trial" a no-op change and accept measurement noise as
                # an improvement
                depth = (self.pool.total_slots()
                         if self.pool is not None else 1)
                fields["inflight_min"] = fields["inflight_max"] = depth
            tset = TuningSettings(**fields)
            tset.validate(qos=self.config.qos)
            self.tuning = TuningPlane(tset)
            self.tuning.signals_fn = lambda: (
                (self.tracer.slo.burn_rate(
                    self.config.tracing.slo_fast_window_s)
                 if self.tracer is not None else 0.0),
                (self.qos.effective_level() if self.qos.enabled else 0))
        # consistent-hash shard router (cluster/hashring.py): with
        # config.cluster.enabled, /predict serves only users whose
        # partition the ring assigns to THIS worker_id; other keys get a
        # 421 naming the owning worker + address. Placement is a pure
        # function of (workers, n_partitions, virtual_nodes) — every
        # worker and every ingress computes the same answer with no
        # coordination traffic.
        # optional network-fault snapshot source (an object with
        # .snapshot(), e.g. chaos.netfaults.LinkFaultPlane): attached by
        # harnesses/drills that degrade this app's links; exposition
        # mirrors it through sync_netfaults so the serving plane renders
        # the same netfault_*/fenced_* series as a stream job would
        self.netfaults = None
        self.cluster_router = None
        cl = self.config.cluster
        if cl.enabled:
            from realtime_fraud_detection_tpu.cluster.hashring import (
                ShardRouter,
            )

            self.cluster_router = ShardRouter(
                cl.n_partitions, sorted(cl.workers),
                virtual_nodes=cl.virtual_nodes,
                addresses=dict(cl.workers))
        self.batcher = RequestMicrobatcher(
            self._score_batch_sync,
            max_batch=sc.microbatch_max_size,
            deadline_ms=sc.microbatch_deadline_ms,
            budget=self.qos.budget if self.config.qos.enabled else None,
            tracer=self.tracer,
            controller=self.tuning,
            # priority stamping mirrors the stream job: classes appear in
            # the queue-wait split only while the QoS plane is ENABLED
            # (it can be toggled at runtime via POST /qos) — without it,
            # traffic reports as "unclassified", never as classes that
            # no admission decision actually used
            classify_fn=lambda t: (self.qos.classify(t)
                                   if self.qos.enabled else ""),
            # two-phase pipelined scoring (serving.overlap_assembly): the
            # drain task dispatches batch N+1 (cache check + assembly +
            # device launch) while batch N still waits on the device in its
            # finalize task — per-waiter results keep arriving in order
            dispatch_fn=(self._dispatch_batch_sync if two_phase else None),
            finalize_fn=(self._finalize_batch_sync if two_phase else None),
            pipeline_depth=(self.pool.total_slots()
                            if self.pool is not None else 2),
        )
        self.http = HttpServer(host if host is not None else sc.host,
                               port if port is not None else sc.port)
        # dedicated Prometheus port (reference monitoring contract: metrics
        # on 8081 separate from the API; config.monitoring.enable_prometheus
        # + prometheus_port). 0 disables the extra listener — the main app
        # still serves /metrics/prometheus for annotation-based scraping.
        self.metrics_http: Optional[HttpServer] = None
        mon = self.config.monitoring
        if mon.enable_prometheus and mon.prometheus_port:
            self.metrics_http = HttpServer(
                host if host is not None else sc.host, mon.prometheus_port)
            self.metrics_http.route("GET", "/metrics",
                                    self._metrics_prometheus)
        self._reload_lock = asyncio.Lock()
        # prediction TTL cache (reference ensemble_predictor.py:437-471):
        # idempotent retries of a transaction_id serve the stored response
        from realtime_fraud_detection_tpu.serving.cache import PredictionCache

        self.prediction_cache = (
            PredictionCache(self.config.ensemble.cache_ttl_seconds,
                            self.config.ensemble.cache_max_entries)
            if sc.enable_prediction_cache else None
        )
        # FraudScorer and the drift monitor are single-writer; /predict's
        # microbatcher thread and /batch-predict's executor thread both call
        # _score_batch_sync, so serialize them (the device is serial anyway)
        self._score_lock = threading.Lock()
        # set by _predict (event loop) when the QoS served rung moved;
        # consumed by _dispatch_batch_sync (executor) under _score_lock.
        # Plain bool: single writer per side, torn reads impossible.
        self._qos_rung_dirty = False
        # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
        self._started = time.monotonic()
        # admission control (reference config.py:86 max_concurrent_
        # predictions, enforced): transactions admitted but not yet
        # answered. Beyond the cap, requests get an immediate 503 instead
        # of growing the microbatch queue without bound — load sheds at
        # the door, and the deadline batcher's latency contract holds for
        # everything admitted. Single event loop => plain counter.
        self._inflight_txns = 0
        self._register_routes()

    # --------------------------------------------------------------- scoring
    def _score_batch_sync(self, txns, trace=None) -> List[Dict[str, Any]]:
        """Runs in an executor thread: device call + obs write-back.

        The score lock is held for host-state mutation only (assembly at
        dispatch; write-back inside finalize) — NOT across the device wait,
        so a concurrent caller assembles its batch while this one's compute
        is in flight (the double-buffered serving path, VERDICT r1 item 6).
        """
        return self._finalize_batch_sync(self._dispatch_batch_sync(txns,
                                                                   trace))

    def _dispatch_batch_sync(self, txns, trace=None) -> tuple:
        """Pipeline stage 1 (executor thread): prediction-cache lookup +
        assemble + device launch, WITHOUT blocking on the result. The
        two-phase microbatcher (serving.overlap_assembly) calls this for
        batch N+1 while batch N's ``_finalize_batch_sync`` is still waiting
        on the device — host assembly overlaps device compute."""
        # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
        t0 = time.perf_counter()
        # serve idempotent retries from the prediction cache; only misses
        # go to the device (reference TTL-cache semantics)
        cache = self.prediction_cache
        cached: Dict[int, Dict[str, Any]] = {}
        to_score = txns
        if cache is not None:
            with self._score_lock:
                for i, txn in enumerate(txns):
                    hit = cache.get(str(txn.get("transaction_id", "")))
                    if hit is not None:
                        cached[i] = hit            # deep copy from the cache
            if cached:
                to_score = [t for i, t in enumerate(txns) if i not in cached]
        if trace is not None and cached:
            # cache hits never reach the device: close their traces with
            # the `cached` terminal and keep only the scored contexts on
            # the batch carrier (contexts align with txns by queue order)
            kept = []
            for i, c in enumerate(trace.contexts):
                if i in cached:
                    self.tracer.finish_terminal(c, "cached")
                else:
                    kept.append(c)
            trace.contexts = kept
        try:
            pending = None
            if to_score:
                with self._score_lock:
                    if self._qos_rung_dirty and self.qos.enabled:
                        # rung change flagged by _predict on the event
                        # loop; applied here under the lock this thread
                        # already holds for the dispatch
                        self._qos_rung_dirty = False
                        self.qos.apply_degradation(self.scorer)
                    pending = self.scorer.dispatch(to_score, trace=trace)
        except Exception:
            self.metrics.record_error("score")
            self._close_trace_error(trace)
            raise
        return (t0, txns, to_score, cached, pending, trace)

    def _close_trace_error(self, trace) -> None:
        """Close every open context on a failed batch with the `error`
        terminal — the waiters got the exception, but the flight
        recorder must still see the (worst-latency) failing
        transactions, exactly as the stream job records them. Never a
        silent gap."""
        if trace is None or self.tracer is None:
            return
        for c in trace.contexts:
            self.tracer.finish_terminal(c, "error")
        trace.contexts = []

    def _finalize_batch_sync(self, ctx: tuple) -> List[Dict[str, Any]]:
        """Pipeline stage 2 (executor thread): block on the device result,
        then run the obs/experiment/cache tail and reassemble request
        order."""
        t0, txns, to_score, cached, pending, trace = ctx
        cache = self.prediction_cache
        try:
            fresh = (self.scorer.finalize(pending, lock=self._score_lock)
                     if pending is not None else [])
        except Exception:
            self.metrics.record_error("score")
            self._close_trace_error(trace)
            raise
        # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
        dt = time.perf_counter() - t0
        # batch metrics count the same population as per-prediction metrics:
        # fresh results only — a cache hit costs ~0 and would deflate the
        # apparent batch latency per txn; an all-hit batch records nothing
        # (no device batch happened)
        if fresh:
            self.metrics.record_batch(len(fresh), dt)
        if self.config.monitoring.enable_drift_detection \
                and pending is not None \
                and not self.config.feedback.enabled:
            # with the feedback plane enabled, on_predictions below feeds
            # the same shared drift monitor — don't double-count the batch
            with self._score_lock:
                self.drift.update(pending.features)
        # experiments and per-prediction metrics run on FRESH results only:
        # a cache hit is a retry of an already-recorded transaction, and
        # re-recording it would feed correlated duplicate observations into
        # the A/B significance test and inflate decision metrics
        self._apply_experiments(to_score, fresh)
        if self.config.monitoring.enable_performance_tracking:
            per_txn = dt / max(len(fresh), 1)
            for r in fresh:
                self.metrics.record_prediction(
                    r["decision"], r["fraud_score"], per_txn,
                    r["model_predictions"])
        if cache is not None:
            # cache AFTER experiments: the stored response is exactly what
            # this request serves, so a retry is truly idempotent even when
            # a variant reweighted the score
            with self._score_lock:
                for r in fresh:
                    cache.put(r["transaction_id"], r)
        if self.config.feedback.enabled and fresh:
            # continuous-learning plane: register exactly what this batch
            # serves (post-experiment scores) with the label join + drift
            # monitor, then run the cheap trigger check; the expensive
            # retrain runs on a worker thread (_maybe_react)
            with self._score_lock:
                self.feedback.on_predictions(
                    to_score, fresh,
                    features=(pending.features if pending is not None
                              else None))
                self.feedback.check_trigger()
            self._maybe_react()
        if trace is not None and self.tracer is not None:
            # emit: the batch's waiters resolve right after this returns.
            # Closing here also feeds the SLO window; the burn gate is an
            # extra, hysteresis-guarded degradation signal on top of the
            # backlog ladder.
            self.tracer.finish_batch(trace)
            if self.qos.enabled:
                ts = self.config.tracing
                self.qos.observe_slo_burn(
                    self.tracer.slo.burn_rate(ts.slo_fast_window_s),
                    threshold=ts.slo_burn_threshold,
                    patience=ts.slo_gate_patience,
                    up_patience=ts.slo_gate_up_patience)
        # reassemble in request order
        if cached:
            results, it_fresh = [], iter(fresh)
            for i in range(len(txns)):
                results.append(cached[i] if i in cached else next(it_fresh))
        else:
            results = fresh
        return results

    def _apply_experiments(self, txns, results) -> None:
        """Route each txn through active experiments: treatment overrides
        re-weight the ensemble host-side (a weighted average over the 5
        returned model predictions — numerically identical to running the
        device combine with those weights), and every arm accumulates
        online metrics. Ground-truth labels, when the producer supplies
        them (simulator ``is_fraud``), feed the significance test."""
        alert_t = self.config.stream.alert_score_threshold
        base = self.config.normalized_weights()
        for txn, res in zip(txns, results):
            uid = str(txn.get("user_id", ""))
            for name in self.ab.active_experiments():
                variant = self.ab.assign(name, uid)
                if variant.overrides.get("weights"):
                    ens = self.config.ensemble
                    reweighted = apply_weight_overrides(
                        res["model_predictions"], base,
                        variant.overrides["weights"],
                        ens.confidence_threshold,
                        decline_threshold=ens.decline_threshold,
                        review_threshold=ens.review_threshold,
                        monitor_threshold=ens.monitor_threshold)
                    if reweighted is not None:
                        # decision + risk_level are recomputed with the new
                        # score so the served record stays consistent
                        res.update(reweighted)
                        res["fraud_score"] = reweighted["fraud_probability"]
                        res.setdefault("explanation", {})["experiment"] = {
                            "name": name, "variant": variant.name}
                actual = txn.get("is_fraud")
                self.ab.record_prediction(
                    name, variant.name, res["fraud_score"],
                    res["fraud_score"] > alert_t,
                    bool(actual) if actual is not None else None)

    def _maybe_react(self) -> None:
        """Kick the plane's retrain->gate->promote on a worker thread when
        a trigger is pending (never on the scoring path). One reaction in
        flight at a time; the promotion itself happens under the score
        lock inside promote_fn — the /reload-models recipe."""
        if self.feedback.pending_trigger is None or self._feedback_reacting:
            return
        self._feedback_reacting = True

        def _run() -> None:
            try:
                # O(n) shallow row snapshot under the ingest lock; the
                # expensive sort + stack and the training itself run
                # lock-free — the retrain must never block scoring
                with self._score_lock:
                    rows = self.feedback.buffer.snapshot_rows()
                arrays = self.feedback.buffer.arrays_from(
                    rows, self.feedback.buffer.store_history)
                self.feedback.react(arrays=arrays)
            finally:
                self._feedback_reacting = False

        threading.Thread(target=_run, name="feedback-retrain",
                         daemon=True).start()

    # ---------------------------------------------------------------- routes
    def _register_routes(self) -> None:
        r = self.http.route
        r("POST", "/predict", self._predict)
        r("POST", "/batch-predict", self._batch_predict)
        r("GET", "/health", self._health)
        r("GET", "/metrics", self._metrics)
        r("GET", "/model-info", self._model_info)
        r("POST", "/reload-models", self._reload_models)
        r("GET", "/metrics/prometheus", self._metrics_prometheus)
        r("GET", "/metrics/fleet", self._metrics_fleet)
        r("GET", "/drift", self._drift)
        r("POST", "/experiments", self._create_experiment)
        r("GET", "/experiments", self._experiment_results)
        r("GET", "/qos", self._qos_status)
        r("POST", "/qos", self._qos_configure)
        r("POST", "/labels", self._ingest_labels)
        r("GET", "/quality/live", self._quality_live)
        r("GET", "/latency/breakdown", self._latency_breakdown)
        r("GET", "/slo", self._slo_status)
        r("GET", "/autotune", self._autotune_status)
        r("GET", "/cluster", self._cluster_status)

    def _admit(self, n: int) -> None:
        limit = self.config.serving.max_concurrent_predictions
        if self._inflight_txns + n > limit:
            self.metrics.record_error("at_capacity")
            raise HttpError(
                503, f"at capacity ({self._inflight_txns} in flight, "
                     f"limit {limit})")
        self._inflight_txns += n

    def _release_on_done(self, fut: "asyncio.Future", n: int) -> None:
        """Free n admission slots when the batcher resolves ``fut`` — NOT
        when the HTTP waiter gives up. A timed-out request's transaction
        still sits in the microbatch queue and will be scored; releasing
        its slot early would let new admissions stack on top of abandoned
        work and grow the queue without bound."""
        def _done(f: "asyncio.Future") -> None:
            self._inflight_txns -= n
            if not f.cancelled():
                f.exception()        # consume, silencing "never retrieved"
        fut.add_done_callback(_done)

    async def _predict(self, body, query) -> Tuple[int, Any]:
        txn, errors = validate_transaction(body)
        if errors:
            raise HttpError(422, errors)
        if (self.cluster_router is not None
                and self.config.cluster.worker_id):
            # shard affinity ahead of admission: a wrong-shard request
            # must not burn this worker's QoS tokens or concurrency
            # slots. 421 Misdirected Request, with the owner's identity
            # and address so the caller (or the ingress) re-issues once.
            uid = str(txn.get("user_id", ""))
            owner = self.cluster_router.route(uid)
            if owner != self.config.cluster.worker_id:
                resp = {
                    "error": "wrong_shard",
                    "owner": owner,
                    "location": self.cluster_router.address_of(owner),
                    "partition": self.cluster_router.partition_of(uid),
                }
                carrier = txn.get("trace_carrier")
                if carrier is not None:
                    # redirect-aware carrier echo: bump the hop count so
                    # the eventual consumer books this bounce under the
                    # trace's redirect_hops stage; the caller copies the
                    # returned carrier onto the re-issued request
                    from realtime_fraud_detection_tpu.obs.tracing import (
                        make_carrier,
                        parse_carrier,
                    )

                    c = parse_carrier(carrier)
                    if c is not None:
                        resp["trace_carrier"] = make_carrier(
                            c["tid"], origin=c["org"],
                            produced_ts=c.get("ts"), priority=c["pr"],
                            fault=c["flt"], parent=c["sp"],
                            hops=int(c.get("rh", 0)) + 1,
                            redirect_s=float(c.get("rs", 0.0)))
                return 421, resp
        if self.qos.enabled:
            # QoS admission ahead of the concurrency gate: a shed is an
            # explicit score-with-reason (200, decision REVIEW, risk_level
            # SHED), so retriable overload is visible to the caller without
            # looking like record loss. The ladder observes the batcher
            # queue depth as its backlog signal.
            # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
            decision = self.qos.admit(txn, time.monotonic())
            if not decision.admitted:
                return 200, self.qos.shed_result(txn, decision)
            self.qos.observe_backlog(self.batcher.queue_depth)
            # A served-rung change is only FLAGGED here: the event loop
            # must never take _score_lock (an executor thread holds it
            # across multi-ms batch assembly — blocking here would freeze
            # every endpoint exactly when QoS is protecting latency). The
            # executor consumes the flag in _dispatch_batch_sync under
            # the lock it already holds, so set_degradation's mask +
            # rules_only writes can never race a dispatch into a torn
            # (mask from rung N, flag from rung N+1) pair — the
            # `rtfd lint` lock-order finding this path was rebuilt for.
            if self.qos.effective_level() != self.scorer.qos_level:
                self._qos_rung_dirty = True
        timeout = self.config.serving.prediction_timeout_seconds
        self._admit(1)
        try:
            fut = self.batcher.submit_nowait(txn)
        except (asyncio.QueueFull, RuntimeError):
            self._inflight_txns -= 1
            self.metrics.record_error("at_capacity")
            raise HttpError(503, "scoring queue full")
        self._release_on_done(fut, 1)
        # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
        t_enq = time.monotonic()
        try:
            # shield: the waiter's timeout must not cancel the scoring —
            # the batch containing this txn is already (or will be) on the
            # device; the slot frees via _release_on_done either way
            result = await asyncio.wait_for(asyncio.shield(fut),
                                            timeout=timeout)
        except asyncio.TimeoutError:
            self.metrics.record_error("timeout")
            raise HttpError(408, "prediction timed out")
        if self.qos.enabled:
            # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
            self.qos.record_completion(t_enq, time.monotonic())
        self.metrics.queue_depth.set(self.batcher.queue_depth)
        return 200, result

    async def _batch_predict(self, body, query) -> Tuple[int, Any]:
        txns, errors = validate_batch(
            body, self.config.serving.batch_size_limit)
        if errors:
            raise HttpError(422, errors)
        limit = self.config.serving.max_concurrent_predictions
        if len(txns) > limit:
            # oversize, not overload: no amount of retrying can ever fit
            # this batch under the concurrency cap, so reject it as
            # non-retryable instead of a transient 503
            raise HttpError(
                413, f"batch of {len(txns)} exceeds the concurrency "
                     f"capacity {limit}; split into smaller batches")
        # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
        t0 = time.perf_counter()
        self._admit(len(txns))
        try:
            loop = asyncio.get_running_loop()
            results = await loop.run_in_executor(
                None, self._score_batch_sync, txns)
        finally:
            self._inflight_txns -= len(txns)
        return 200, {
            "results": results,
            "count": len(results),
            # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
            "processing_time_ms": (time.perf_counter() - t0) * 1e3,
        }

    async def _health(self, body, query) -> Tuple[int, Any]:
        info = self.scorer.model_info()
        loaded = sum(1 for m in info["models"].values() if m["enabled"])
        payload = {
            "status": "healthy",
            "models_loaded": loaded,
            "num_models": info["num_models"],
            # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
            "uptime_seconds": time.monotonic() - self._started,
            "queue_depth": self.batcher.queue_depth,
        }
        if self.prediction_cache is not None:
            # lock-free by contract (cache.py): stats() reads only atomic
            # counters, and taking _score_lock here would stall the event
            # loop behind an executor thread's batch assembly
            payload["prediction_cache"] = self.prediction_cache.stats()
        return 200, payload

    async def _metrics(self, body, query) -> Tuple[int, Any]:
        payload = self.metrics.summary()
        payload["host_assembly"] = self.scorer.host_stats()
        if self.pool is not None:
            key = ("mesh" if hasattr(self.pool, "mesh_snapshot")
                   else "device_pool")
            payload[key] = self.pool.stats()
        return 200, payload

    async def _metrics_prometheus(self, body, query) -> Tuple[int, Any]:
        # mirror the scorer's host-assembly spans + cache counters and the
        # feedback plane's prequential/label/promotion series into the
        # registry at scrape time (cheap gauge sets + counter deltas)
        self.metrics.sync_host_stats(self.scorer.host_stats())
        self.metrics.sync_quant(self.scorer.quant_snapshot())
        self.metrics.sync_kernels(self.scorer.kernel_snapshot())
        self.metrics.sync_graph(self.scorer.graph_snapshot())
        self.metrics.sync_microbatch(self.batcher.close_reasons)
        if self.pool is not None:
            # a mesh executor mirrors through its own series (geometry,
            # placement, per-chip bytes); the replicated pool keeps the
            # device_pool_* family
            mesh_snap = getattr(self.pool, "mesh_snapshot", None)
            if mesh_snap is not None:
                self.metrics.sync_mesh(mesh_snap())
            else:
                self.metrics.sync_device_pool(self.pool.stats())
        if self.tracer is not None:
            self.metrics.sync_tracing(self.tracer.snapshot())
        if self.tuning is not None:
            self.metrics.sync_autotune(self.tuning.snapshot())
        if self.config.feedback.enabled:
            with self._score_lock:
                snap = self.feedback.snapshot()
            self.metrics.sync_feedback(snap)
        if self.cluster_router is not None:
            self.metrics.sync_cluster(self._cluster_snapshot())
        if self.netfaults is not None:
            self.metrics.sync_netfaults(self.netfaults.snapshot())
        return 200, self.metrics.render_prometheus()

    async def _metrics_fleet(self, body, query) -> Tuple[int, Any]:
        """Fleet-level Prometheus exposition: every worker's counters
        under a ``{worker=...}`` label plus honest unlabeled fleet sums,
        exactly one HELP/TYPE pair per family (obs/fleetmetrics.py).
        This process's own tracing counters fold in at render time under
        its cluster worker id, so a one-process deployment still renders
        an honest one-worker fleet."""
        from realtime_fraud_detection_tpu import __version__

        local_id = self.config.cluster.worker_id or "serving"
        if self.tracer is not None:
            self.fleet_metrics.ingest_cumulative(
                local_id,
                {f"trace_{k}": v
                 for k, v in self.tracer.counters.items()})
            self.fleet_metrics.set_worker_info(
                local_id, pid=os.getpid(), version=__version__)
        return 200, self.fleet_metrics.render(version=__version__)

    def _cluster_snapshot(self) -> Dict[str, Any]:
        """Serving-side cluster snapshot (router truth only — the stream
        fleet's snapshot additionally carries handoff/checkpoint ledgers;
        obs.metrics.sync_cluster accepts either shape)."""
        snap = self.cluster_router.snapshot()
        return {
            "workers_alive": len(snap["members"]),
            "workers": {
                m: {"partitions_owned": len(snap["assignment"].get(m, ()))}
                for m in snap["members"]
            },
            "router": snap,
        }

    async def _cluster_status(self, body, query) -> Tuple[int, Any]:
        """Shard-routing status: this worker's identity, the membership,
        the partition assignment, and the router's movement ledger."""
        if self.cluster_router is None:
            return 200, {"enabled": False}
        return 200, {
            "enabled": True,
            "worker_id": self.config.cluster.worker_id,
            **self.cluster_router.snapshot(),
        }

    async def _model_info(self, body, query) -> Tuple[int, Any]:
        return 200, self.scorer.model_info()

    async def _reload_models(self, body, query) -> Tuple[int, Any]:
        """Hot swap under a lock (reference main.py:291-305 +
        model_manager.py:348-380). Body options:
        {"checkpoint_dir": ..., "step": optional} — restore params (and host
        state if present) from a checkpoint; {"quality_artifact": path} —
        re-blend live from a quality-eval artifact (weights + validity are
        runtime tensors to the fused program, so a new measured blend
        deploys with ZERO recompiles; combinable with checkpoint_dir to
        swap params and blend together); {} — fresh re-init (dummy-model
        analog). The swap happens between batches: the scorer reads
        ``self.models`` once per score_batch call."""
        body = body or {}
        async with self._reload_lock:
            loop = asyncio.get_running_loop()
            source: Dict[str, Any] = {}
            blend_requested = "quality_artifact" in body
            if blend_requested:
                # VALIDATE the artifact up front (parse + schema + known
                # branch names) but apply it only AFTER the checkpoint
                # restore succeeds: a 404/409 restore must leave the live
                # blend untouched, and a half-applied update (new blend +
                # old params, or vice versa) must never serve.
                try:
                    weights = Config.load_selected_blend_weights(
                        str(body["quality_artifact"]))
                except FileNotFoundError as e:
                    raise HttpError(404, str(e))
                except (ValueError, OSError) as e:
                    raise HttpError(422, str(e))
                unknown = [n for n in weights
                           if n not in self.config.models]
                if unknown:
                    raise HttpError(
                        422, f"artifact names unknown model(s) {unknown}; "
                             f"configured: {sorted(self.config.models)}")
            if "checkpoint_dir" in body:
                step = body.get("step")
                if step is not None:
                    try:
                        step = int(step)
                    except (TypeError, ValueError):
                        raise HttpError(422, f"step must be an integer, "
                                             f"got {step!r}")
                if blend_requested:
                    # refuse to combine a checkpoint and a quality artifact
                    # that record DIFFERENT text-encoder architectures —
                    # the blend was measured with one model, the params are
                    # another; serving that pair silently mixes quality
                    # claims (VERDICT Weak #5). Checked BEFORE the restore
                    # so a refusal leaves the live deployment untouched;
                    # {"allow_arch_mismatch": true} overrides explicitly.
                    art_tm = Config.load_artifact_text_model(
                        str(body["quality_artifact"]))
                    try:
                        ck_meta = (CheckpointManager(body["checkpoint_dir"])
                                   .manifest(step).get("metadata") or {})
                    except FileNotFoundError as e:
                        raise HttpError(404, str(e))
                    ck_tm = ck_meta.get("text_model")
                    if (art_tm is not None and ck_tm is not None
                            and dict(art_tm) != dict(ck_tm)
                            and not body.get("allow_arch_mismatch")):
                        raise HttpError(
                            409, f"text-encoder architecture mismatch: "
                                 f"artifact records {art_tm}, checkpoint "
                                 f"records {ck_tm}; pass "
                                 f"allow_arch_mismatch to combine anyway")

                def _restore():
                    # one shared recipe (checkpoint.restore_into_scorer):
                    # step resolved once, shape-aware template from the
                    # manifest, swap under the score lock. The same
                    # allow_arch_mismatch override also waives the
                    # quantization-mode stamp check — an int8 checkpoint
                    # never silently restores into an f32 scorer (409).
                    mgr = CheckpointManager(body["checkpoint_dir"])
                    return mgr.restore_into_scorer(
                        self.scorer, step=step, lock=self._score_lock,
                        allow_arch_mismatch=bool(
                            body.get("allow_arch_mismatch")))
                try:
                    ck = await loop.run_in_executor(None, _restore)
                except FileNotFoundError as e:
                    raise HttpError(404, str(e))
                except ValueError as e:
                    raise HttpError(409, str(e))   # config/shape mismatch
                source.update(checkpoint=body["checkpoint_dir"],
                              step=ck.step)
            elif blend_requested:
                pass                               # blend-only reload
            else:
                import jax

                seed = int(body.get("seed", 0))

                def _reinit():
                    fresh = init_scoring_models(
                        jax.random.PRNGKey(seed),
                        bert_config=self.scorer.bert_config,
                        feature_dim=self.scorer.sc.feature_dim,
                        node_dim=self.scorer.sc.node_dim)
                    with self._score_lock:
                        self.scorer.set_models(fresh)
                await loop.run_in_executor(None, _reinit)
                source["reinit_seed"] = seed
            if blend_requested:
                # params are in place; deploy the (pre-validated) blend.
                # Belt and suspenders: if the apply still fails, roll the
                # model table back and refresh, so the served blend is
                # either fully the old one or fully the new one.
                snapshot = {n: (mc.enabled, mc.weight)
                            for n, mc in self.config.models.items()}
                try:
                    applied = self.config.apply_quality_artifact(
                        str(body["quality_artifact"]))
                    with self._score_lock:
                        self.scorer.refresh_blend_from_config()
                except Exception:
                    for name, (was_enabled, was_weight) in snapshot.items():
                        mc = self.config.models[name]
                        mc.enabled = was_enabled
                        mc.weight = was_weight
                    with self._score_lock:
                        self.scorer.refresh_blend_from_config()
                    raise
                source["quality_artifact"] = {
                    "path": str(body["quality_artifact"]),
                    "weights": applied,
                }
            if self.prediction_cache is not None:
                # cached responses describe the replaced models; clear()
                # keeps the monotonic hit/miss counters /health exposes
                with self._score_lock:
                    self.prediction_cache.clear()
        return 200, {"status": "reloaded", "source": source}

    async def _qos_status(self, body, query) -> Tuple[int, Any]:
        """QoS plane status: ladder level, admission state, counters."""
        snap = self.qos.snapshot()
        snap["queue_depth"] = self.batcher.queue_depth
        return 200, snap

    async def _qos_configure(self, body, query) -> Tuple[int, Any]:
        """Update QoS knobs at runtime (all runtime tensors/host state —
        zero recompiles). Body: any subset of utils.config.QosSettings
        fields, e.g. {"enabled": true, "admission_rate": 20000,
        "budget_ms": 20}."""
        body = body or {}
        try:
            applied = self.qos.configure(body)
        except (TypeError, ValueError) as e:
            raise HttpError(422, str(e))
        # the budget only binds the batcher while the plane is enabled
        self.batcher.budget = (self.qos.budget
                               if self.config.qos.enabled else None)
        if not self.config.qos.enabled:
            # dropping back to a disabled plane also lifts any degradation
            with self._score_lock:
                self.scorer.set_degradation(None)
        return 200, {"status": "configured", "applied": applied,
                     "qos": self.qos.snapshot()}

    async def _ingest_labels(self, body, query) -> Tuple[int, Any]:
        """Ingest delayed ground-truth label events (the labels-topic
        seam over HTTP). Body: one event dict or a list of them; each
        needs ``transaction_id``, ``is_fraud`` and (optionally)
        ``label_ts``. Labels are joined to emitted predictions, feed the
        prequential metrics + labeled buffer, and can trigger a
        retrain."""
        if not self.config.feedback.enabled:
            raise HttpError(409, "feedback plane disabled "
                                 "(config.feedback.enabled)")
        events = body if isinstance(body, list) else [body]
        cleaned = []
        for ev in events:
            if not isinstance(ev, dict) or not ev.get("transaction_id") \
                    or "is_fraud" not in ev:
                raise HttpError(
                    422, "each label event needs transaction_id + is_fraud")
            ev = dict(ev)
            # rtfd-lint: allow[wall-clock] HTTP serving plane is real-time (no virtual-clock mode)
            ev.setdefault("label_ts", time.time())
            cleaned.append(ev)
        with self._score_lock:
            matched = self.feedback.on_labels(cleaned)
            self.feedback.check_trigger()
        self._maybe_react()
        return 200, {"ingested": len(cleaned), "matched": matched,
                     "join": self.feedback.join.stats()}

    async def _quality_live(self, body, query) -> Tuple[int, Any]:
        """Live model quality under delayed ground truth: prequential
        sliding/fading AUC + precision/recall at the pinned operating
        point, calibration error, per-branch drop-one attribution,
        label-join health, buffer occupancy, and the retrain/gate/
        promotion audit tail. Snapshotted under the score lock — the
        executor thread mutates the plane's windows under the same lock."""
        with self._score_lock:
            return 200, self.feedback.snapshot()

    async def _latency_breakdown(self, body, query) -> Tuple[int, Any]:
        """Critical-path decomposition of the captured trace window:
        additive per-stage contributions to the p50/p95/p99 end-to-end
        latency with the dominant stage flagged, plus the slowest-N
        exemplar trace ids (obs/tracing.py breakdown)."""
        if self.tracer is None:
            return 200, {"enabled": False, "n": 0,
                         "hint": "start with --trace or "
                                 "config.tracing.enabled"}
        return 200, self.tracer.breakdown()

    async def _slo_status(self, body, query) -> Tuple[int, Any]:
        """SLO burn-rate status: objective, fast/slow-window violation
        fractions + burn rates, and the QoS gate the burn signal feeds."""
        if self.tracer is None:
            return 200, {"enabled": False}
        payload = self.tracer.slo.snapshot()
        payload["enabled"] = True
        payload["qos_gate"] = {
            "engaged": self.qos.slo_engaged,
            "threshold": self.config.tracing.slo_burn_threshold,
        }
        return 200, payload

    async def _autotune_status(self, body, query) -> Tuple[int, Any]:
        """Self-tuning plane state: the forecast, the JIT controller's
        decision mix + live knob values, and the tuner's trial/freeze
        counters (tuning/plane.py snapshot)."""
        if self.tuning is None:
            return 200, {"enabled": False,
                         "hint": "start with --autotune or "
                                 "config.tuning.enabled"}
        return 200, self.tuning.snapshot()

    async def _drift(self, body, query) -> Tuple[int, Any]:
        rep = self.drift.report()
        return 200, {
            "drifted": rep.drifted,
            "max_psi": rep.max_psi,
            "top_features": rep.top_features[:10],
            "psi": [float(x) for x in rep.psi],
            "rows_seen": rep.rows_seen,
            "baseline_frozen": rep.baseline_frozen,
        }

    async def _create_experiment(self, body, query) -> Tuple[int, Any]:
        body = body or {}
        try:
            name = body["name"]
            if "from_quality_artifact" in body:
                # canary a measured blend: control = production weights,
                # treatment = the artifact's selected blend at `traffic`.
                # Every artifact branch must be ENABLED in the live scorer:
                # host-side re-weighting can only use predictions the fused
                # program returned (a disabled branch's weight would be
                # silently renormalized away — a control-vs-wrong-thing
                # experiment). Enable first via /reload-models.
                from realtime_fraud_detection_tpu.scoring import MODEL_NAMES
                from realtime_fraud_detection_tpu.utils.config import (
                    Config,
                )

                art = str(body["from_quality_artifact"])
                weights = Config.load_selected_blend_weights(art)
                disabled = [
                    n for n in weights
                    if n in MODEL_NAMES
                    and not self.scorer.model_valid[MODEL_NAMES.index(n)]
                ]
                if disabled:
                    raise HttpError(
                        409, f"artifact blend uses branch(es) {disabled} "
                             f"that are disabled in the current "
                             f"deployment; enable them first (POST "
                             f"/reload-models with the artifact)")
                self.ab.experiment_from_artifact(
                    name, art,
                    traffic=float(body.get("traffic", 0.5)),
                    salt=body.get("salt", ""))
            else:
                variants = [Variant(v["name"], float(v["traffic"]),
                                    v.get("overrides", {}))
                            for v in body["variants"]]
                self.ab.create_experiment(name, variants,
                                          salt=body.get("salt", ""))
        except FileNotFoundError as e:
            raise HttpError(404, str(e))
        except (KeyError, TypeError) as e:
            raise HttpError(422, f"bad experiment spec: {e}")
        except ValueError as e:
            raise HttpError(422, str(e))
        return 200, {"status": "created", "experiment": name}

    async def _experiment_results(self, body, query) -> Tuple[int, Any]:
        name = query.get("name")
        if not name:
            raise HttpError(422, "query param 'name' required")
        try:
            return 200, self.ab.results(name)
        except KeyError:
            raise HttpError(404, f"no experiment {name!r}")

    # -------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        await self.batcher.start()
        await self.http.start()
        if self.metrics_http is not None:
            await self.metrics_http.start()

    async def stop(self) -> None:
        if self.metrics_http is not None:
            await self.metrics_http.stop()
        await self.http.stop()
        await self.batcher.stop()

    @property
    def port(self) -> int:
        return self.http.port

    def run_forever(self) -> None:               # pragma: no cover - CLI path
        """Serve until SIGTERM/SIGINT, then stop GRACEFULLY: the HTTP
        server closes first (no new admissions), then the microbatcher
        drains — every already-admitted transaction is scored and its
        waiter resolved before the process exits. A mid-batch SIGTERM
        loses nothing (the graceful-shutdown satellite, ISSUE 12); only
        SIGKILL abandons in-flight work, by definition."""
        import signal as _signal

        async def _main():
            await self.start()
            stopping = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                try:
                    loop.add_signal_handler(sig, stopping.set)
                except (NotImplementedError, RuntimeError, ValueError):
                    pass          # platform/thread without signal support
            try:
                await stopping.wait()
            finally:
                await self.stop()

        asyncio.run(_main())

"""Serving layer: the §2.7 HTTP API over the microbatched fused scorer."""

from realtime_fraud_detection_tpu.serving.app import ServingApp
from realtime_fraud_detection_tpu.serving.batcher import RequestMicrobatcher
from realtime_fraud_detection_tpu.serving.httpd import (
    HttpError,
    HttpServer,
)
from realtime_fraud_detection_tpu.serving.ingress_client import (
    NoShardAvailableError,
    ShardIngressClient,
)
from realtime_fraud_detection_tpu.serving.validation import (
    validate_batch,
    validate_transaction,
)

__all__ = [
    "HttpError",
    "HttpServer",
    "NoShardAvailableError",
    "RequestMicrobatcher",
    "ServingApp",
    "ShardIngressClient",
    "validate_batch",
    "validate_transaction",
]

"""Shard-following ingress client: the live side of the 421 contract.

PR 10 gave the serving plane wrong-shard refusal: a ``/predict`` for a
user whose partition another worker owns answers ``421 Misdirected
Request`` with the owner's identity and address, BEFORE admission (a
wrong-shard request must not burn QoS tokens). What was missing is the
client that actually closes the loop — the reference's ingress/load
balancer role (arXiv:2109.09541 §4: dumb clients + deterministic
routing). :class:`ShardIngressClient` is that client:

- **follows 421s**: a misdirected request is re-issued once to the
  ``location`` the owning worker advertised (bounded by
  ``max_redirects`` — two workers with momentarily divergent membership
  views can bounce a key, and the client must not ping-pong forever);
- **learns affinity**: the user→worker mapping from every success and
  every 421 lands in a bounded local cache, so steady-state traffic goes
  direct and the 421 path is only paid on membership changes — exactly
  the rebalance-cost model of the consistent-hash ring;
- **retries outages deterministically**: a connection-refused /
  dropped-socket worker (mid-rebalance restart, a kill) is retried with
  ``DeterministicBackoff`` while rotating to the next known worker —
  bounded, jittered, replayable through the injected sleep seam.

No new protocol: plain HTTP against ``serving/app.py``'s existing
surface; the client works against any subset of the fleet's base URLs.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Mapping, Optional, Sequence

__all__ = ["ShardIngressClient", "NoShardAvailableError"]


class NoShardAvailableError(ConnectionError):
    """Every known worker refused or was unreachable within the retry
    budget — the fleet (or the network to it) is down from this
    client's seat."""


class ShardIngressClient:
    """HTTP ``/predict`` client that follows wrong-shard redirects."""

    AFFINITY_CAP = 100_000        # bounded user->URL cache

    def __init__(self, workers: Mapping[str, str] | Sequence[str],
                 timeout_s: float = 10.0, max_redirects: int = 3,
                 retries: int = 4, retry_sleep=None):
        from realtime_fraud_detection_tpu.utils.backoff import (
            DeterministicBackoff,
            instance_seed,
        )

        if isinstance(workers, Mapping):
            self.urls = [u.rstrip("/") for u in workers.values()]
        else:
            self.urls = [str(u).rstrip("/") for u in workers]
        if not self.urls:
            raise ValueError("ShardIngressClient needs >= 1 worker URL")
        self.timeout_s = float(timeout_s)
        self.max_redirects = max(0, int(max_redirects))
        self.retries = max(0, int(retries))
        self.backoff = DeterministicBackoff(
            base_s=0.05, mult=2.0, max_s=1.0,
            seed=instance_seed(";".join(sorted(self.urls))),
            sleep=retry_sleep)
        self._rr = 0
        self._affinity: Dict[str, str] = {}
        self.requests = 0
        self.redirects_followed = 0
        self.retried = 0
        self.affinity_hits = 0

    # ---------------------------------------------------------------- http
    def _post(self, url: str, payload: Mapping[str, Any]) -> tuple:
        """(status, body) — 421 surfaces as a value, not an exception."""
        req = urllib.request.Request(
            url + "/predict", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            body: Any = {}
            try:
                body = json.loads(e.read() or b"{}")
            except (ValueError, OSError):
                pass
            return e.code, body

    def _next_url(self) -> str:
        url = self.urls[self._rr % len(self.urls)]
        self._rr += 1
        return url

    def _remember(self, user_id: str, url: str) -> None:
        if user_id and url:
            if len(self._affinity) >= self.AFFINITY_CAP:
                self._affinity.clear()        # rare, O(1) amortized
            self._affinity[user_id] = url

    # ------------------------------------------------------------- predict
    def predict(self, txn: Mapping[str, Any]) -> Dict[str, Any]:
        """Score one transaction on whichever worker owns its user.

        Tries the learned-affinity URL first (steady state: zero 421s),
        follows up to ``max_redirects`` wrong-shard redirects, and on
        connection failure backs off deterministically while rotating to
        the next known worker. Raises :class:`NoShardAvailableError`
        when the whole budget is exhausted; any non-421 HTTP status is
        returned to the caller inside the body (the serving plane's own
        error contract — sheds are 200s, validation failures 422s)."""
        uid = str(txn.get("user_id", ""))
        url = self._affinity.get(uid)
        if url is not None:
            self.affinity_hits += 1
        else:
            url = self._next_url()
        self.requests += 1
        attempt = 0
        redirects = 0
        last_err: Optional[Exception] = None
        while True:
            try:
                status, body = self._post(url, txn)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last_err = e
                self._affinity.pop(uid, None)
                if attempt >= self.retries:
                    raise NoShardAvailableError(
                        f"no worker reachable for user {uid!r} after "
                        f"{attempt} retries: {last_err}") from e
                self.backoff.sleep(attempt)
                attempt += 1
                self.retried += 1
                url = self._next_url()
                continue
            if status == 421:
                # a 421 is PROOF the asked worker does not own this user
                # — invalidate any learned affinity pointing there FIRST,
                # even when the redirect cannot be followed: mid-
                # rebalance, a previously-confirmed mapping is exactly
                # the entry most likely to be stale, and keeping it
                # would re-route every later request for this user into
                # the same refusal
                if self._affinity.get(uid) == url:
                    self._affinity.pop(uid, None)
                location = str((body or {}).get("location") or "")
                if not location or redirects >= self.max_redirects:
                    # bounded-redirect guard: two workers with divergent
                    # membership views can bounce a key back and forth —
                    # terminate with an explicit error, never a loop
                    raise NoShardAvailableError(
                        f"wrong shard for user {uid!r} and no followable "
                        f"location after {redirects} redirects "
                        f"(owner={body.get('owner')!r})")
                redirects += 1
                self.redirects_followed += 1
                url = location.rstrip("/")
                self._remember(uid, url)
                continue
            self._remember(uid, url)
            if isinstance(body, dict):
                body["_ingress"] = {"worker_url": url, "status": status,
                                    "redirects": redirects}
            return body

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, Any]:
        return {
            "workers": list(self.urls),
            "requests": self.requests,
            "redirects_followed": self.redirects_followed,
            "retried": self.retried,
            "affinity_hits": self.affinity_hits,
            "affinity_size": len(self._affinity),
        }

"""Request-side deadline microbatcher: concurrent /predict → one device call.

This is the piece the reference conspicuously lacks: its ``/predict`` scores
batch=1 per request and ``/batch-predict`` is a sequential Python loop
(main.py:235-248) — "no real batching anywhere in the serving path"
(SURVEY.md §2.7). Its k8s tree *configures* TF-Serving batching (max_batch
128, 100 ms timeout, ml-models-deployment.yaml:270-290) that nothing uses.

Here, concurrent requests land in an asyncio queue; a single drain task
collects up to ``max_batch`` or until ``deadline_ms`` after the first
request, then runs ONE fused scoring call in a worker thread (the event loop
never blocks on device work). Every waiter gets its own row's
FraudPrediction. Deadline defaults to 5 ms — the p99 < 20 ms budget allots
assemble ≈ 5, transfer+compute ≈ 10, return ≈ 5 (SURVEY.md §7.6).

With a QoS ``budget`` (qos/budget.py) attached, the batch close deadline is
additionally capped by the OLDEST waiter's remaining latency budget: a
request that already spent most of its budget queued closes its batch
early (possibly at size 1) instead of waiting out the full assembly window
on top — the deadline-aware assembly lever for p99 (arXiv:1904.07421).

The two-phase pipelined mode (``dispatch_fn``/``finalize_fn``) is also the
device-pool carrier (scoring/device_pool.py): with ``pipeline_depth``
raised to the pool's capacity (devices x per-replica depth,
serving/app.py), the drain task keeps dispatching batches while earlier
ones compute, so the scorer's round-robin pool actually sees enough
concurrent batches to fill every replica. Completion chaining below keeps
per-request FIFO regardless of which replica scored which batch.
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

__all__ = ["RequestMicrobatcher"]


class RequestMicrobatcher:
    """Coalesce concurrent scoring requests into deadline-bounded batches."""

    def __init__(
        self,
        score_fn: Callable[[Sequence[Mapping[str, Any]]], List[Dict[str, Any]]],
        max_batch: int = 256,
        deadline_ms: float = 5.0,
        max_queue: int = 10_000,
        budget=None,
        dispatch_fn: Optional[Callable[[Sequence[Mapping[str, Any]]], Any]] = None,
        finalize_fn: Optional[Callable[[Any], List[Dict[str, Any]]]] = None,
        pipeline_depth: int = 2,
        tracer=None,
        controller=None,
        classify_fn: Optional[Callable[[Mapping[str, Any]], str]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.score_fn = score_fn
        self.max_batch = max_batch
        self.deadline_s = deadline_ms / 1e3
        # injected time base (clock-discipline): every deadline/queue-wait
        # read below goes through this seam — time.monotonic in production,
        # a virtual clock in deterministic tests. Must match the time base
        # of the attached budget/tracer/controller.
        self._clock = clock
        # optional qos.LatencyBudget: per-request enqueue timestamps bound
        # the close deadline by the oldest waiter's remaining budget
        self.budget = budget
        # optional tuning.TuningPlane (serving.autotune): arrival-aware
        # just-in-time closing replaces the fixed assembly deadline —
        # every submit feeds its forecaster (time.monotonic, the same
        # base as the drain loop's clock), and the drain loop asks it
        # per wakeup whether waiting for one more request is expected to
        # lower admitted p99. The QoS budget bound ALWAYS still caps the
        # wait (close_by is passed through), so a controller can never
        # outwait a latency budget. None = bit-identical to today.
        self.controller = controller
        # optional priority classifier (qos.QosPlane.classify): stamps
        # each traced request's priority class so the tracing plane can
        # split queue-wait attribution by class (/latency/breakdown)
        self.classify_fn = classify_fn
        # close-reason histogram (size/deadline/budget/jit/flush) for the
        # Prometheus mirror (MetricsCollector.sync_microbatch) — the
        # serving twin of MicrobatchAssembler.close_reasons
        self.last_close_reason: Optional[str] = None
        self.close_reasons: Dict[str, int] = {}
        # optional obs.tracing.Tracer: each drained batch gets a
        # TraceBatch whose per-request admission time is the enqueue
        # timestamp (same time.monotonic base as the tracer's clock), so
        # the ``queue`` stage measures the real microbatch queue wait.
        # The trace is passed as a second argument to score_fn/dispatch_fn
        # ONLY when a tracer is attached — existing single-argument
        # callables are untouched.
        self.tracer = tracer
        # two-phase pipelined mode: with dispatch_fn + finalize_fn, the
        # drain task runs dispatch (assembly + device launch) inline and
        # hands the blocking finalize to its own ordered task, so batch
        # N+1's host assembly overlaps batch N's device wait. At most
        # ``pipeline_depth`` finalizes stay in flight (backpressure).
        if (dispatch_fn is None) != (finalize_fn is None):
            raise ValueError(
                "dispatch_fn and finalize_fn must be provided together")
        self.dispatch_fn = dispatch_fn
        self.finalize_fn = finalize_fn
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight: List[asyncio.Task] = []
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_queue)
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self.batches = 0
        self.requests = 0

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._task is None:
            self._closed = False
            self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        self._closed = True
        if self._task is not None:
            # a sentinel wakes the drain loop if it's blocked on get()
            await self._queue.put(None)
            await self._task
            self._task = None

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    # --------------------------------------------------------------- submit
    def submit_nowait(self, txn: Mapping[str, Any]) -> asyncio.Future:
        """Enqueue one transaction, returning its result future.

        For callers that manage the wait themselves (the serving app holds
        its admission slot until THIS future resolves — a waiter timing out
        must not free capacity while the transaction still sits in the
        queue). Raises asyncio.QueueFull if the queue is at max_queue.
        """
        if self._closed:
            raise RuntimeError("microbatcher is stopped")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        now = self._clock()
        if self.controller is not None:
            self.controller.observe(now)
        self._queue.put_nowait((txn, fut, now))
        return fut

    async def submit(self, txn: Mapping[str, Any]) -> Dict[str, Any]:
        """Enqueue one transaction; resolves to its FraudPrediction dict."""
        if self._closed:
            raise RuntimeError("microbatcher is stopped")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        now = self._clock()
        if self.controller is not None:
            self.controller.observe(now)
        await self._queue.put((txn, fut, now))
        return await fut

    # ---------------------------------------------------------------- drain
    def _close_at(self, first_item) -> Tuple[float, str]:
        """When must the batch containing ``first_item`` hand off, and why?
        The assembly window from now, capped by the oldest waiter's
        remaining latency budget (it is the oldest: the queue is FIFO).
        With a controller attached the fixed window drops out — only the
        budget bound remains (the controller owns the wait inside it)."""
        if self.controller is not None:
            deadline, kind = math.inf, "deadline"
        else:
            deadline, kind = self._clock() + self.deadline_s, "deadline"
        if self.budget is not None:
            by = self.budget.close_by(first_item[2])
            if by < deadline:
                deadline, kind = by, "budget"
        return deadline, kind

    def _note_close(self, reason: str) -> None:
        self.last_close_reason = reason
        self.close_reasons[reason] = self.close_reasons.get(reason, 0) + 1

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            if first is None:                    # stop sentinel
                await self._flush_remaining(loop)
                return
            batch = [first]
            if self.controller is not None:
                # drain everything ALREADY queued before asking the
                # controller: its headroom is measured from the first
                # waiter's enqueue instant, so after a backpressure stall
                # an aged first item would otherwise deadline-close at
                # n=1 while a full batch sits in the queue — the JIT path
                # must see the backlog the way the stream assembler does
                # (poll first, decide second)
                while len(batch) < self.max_batch:
                    try:
                        item = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if item is None:             # stop sentinel
                        self._note_close("flush")
                        await self._score(loop, batch)
                        await self._flush_remaining(loop)
                        return
                    batch.append(item)
            deadline, bound_kind = self._close_at(first)
            reason = "size"
            while len(batch) < self.max_batch:
                now = self._clock()
                remaining = deadline - now
                if remaining <= 0:
                    reason = bound_kind
                    break
                timeout = remaining
                if self.controller is not None:
                    d = self.controller.should_close(
                        len(batch), first[2], now,
                        close_by=(deadline if math.isfinite(deadline)
                                  else None))
                    if d.close:
                        reason = d.reason
                        break
                    timeout = min(timeout, d.recheck_s)
                try:
                    item = await asyncio.wait_for(
                        self._queue.get(), timeout=timeout)
                except asyncio.TimeoutError:
                    if self.controller is not None:
                        continue                 # re-decide on the new now
                    reason = bound_kind
                    break
                if item is None:
                    self._note_close("flush")
                    await self._score(loop, batch)
                    await self._flush_remaining(loop)
                    return
                batch.append(item)
            self._note_close(reason)
            await self._score(loop, batch)

    async def _flush_remaining(self, loop) -> None:
        """Score whatever raced in behind the stop sentinel — a submit()
        that passed the _closed check may enqueue after it, and its waiter
        must not hang forever."""
        leftovers = []
        while not self._queue.empty():
            item = self._queue.get_nowait()
            if item is not None:
                leftovers.append(item)
        for i in range(0, len(leftovers), self.max_batch):
            self._note_close("flush")
            await self._score(loop, leftovers[i:i + self.max_batch])
        await self._join_pipeline()

    async def _join_pipeline(self) -> None:
        """Wait out every in-flight finalize task (shutdown barrier)."""
        while self._inflight:
            task = self._inflight.pop(0)
            try:
                await task
            except Exception:  # noqa: BLE001 — waiters got the exception
                pass

    def _trace_for(self, batch):
        """Open a TraceBatch for a drained batch (None when untraced):
        admission = the request's enqueue instant, so queue wait is real.
        With a classifier attached, each context carries its QoS priority
        class so /latency/breakdown can split queue-wait by class."""
        if self.tracer is None or not self.tracer.enabled:
            return None
        cls = self.classify_fn
        return self.tracer.batch(
            [self.tracer.begin(str(t.get("transaction_id", "")),
                               t_admit=ts,
                               priority=(cls(t) if cls is not None else ""))
             for t, _, ts in batch],
            batch_size=len(batch),
            close_reason=self.last_close_reason)

    def _feed_tuning(self, n: int, t_dispatch: float, enq_ts) -> None:
        """Completed-batch observation into the tuning plane (no-op for a
        bare controller or with tuning off): service time = dispatch→now,
        per-request latency = enqueue→now — the queue wait the JIT
        decision caused is part of the objective it is judged on."""
        cb = getattr(self.controller, "on_batch_complete", None)
        if cb is None:
            return
        now = self._clock()
        cb(n, max(0.0, now - t_dispatch), now,
           latencies_ms=[(now - t) * 1e3 for t in enq_ts])

    async def _score(self, loop, batch) -> None:
        if self.dispatch_fn is not None:
            await self._score_pipelined(loop, batch)
            return
        txns = [t for t, _, _ in batch]
        futs = [f for _, f, _ in batch]
        trace = self._trace_for(batch)
        t_disp = self._clock()
        try:
            # device work off the event loop; one fused program per batch
            if trace is not None:
                results = await loop.run_in_executor(
                    None, self.score_fn, txns, trace)
            else:
                results = await loop.run_in_executor(
                    None, self.score_fn, txns)
        except Exception as e:                   # noqa: BLE001
            for f in futs:
                if not f.done():
                    f.set_exception(e)
            return
        self.batches += 1
        self.requests += len(batch)
        self._feed_tuning(len(batch), t_disp, [ts for _, _, ts in batch])
        for f, r in zip(futs, results):
            if not f.done():                     # waiter may have timed out
                f.set_result(r)

    # ------------------------------------------------------ pipelined mode
    async def _score_pipelined(self, loop, batch) -> None:
        """Dispatch this batch now; finalize in an ordered background task.

        The drain loop regains control right after dispatch returns, so it
        collects (and dispatches) the NEXT batch while this one's finalize
        blocks on the device in the executor — host assembly overlapped
        with device compute, completion order preserved by chaining each
        finalize behind its predecessor."""
        txns = [t for t, _, _ in batch]
        futs = [f for _, f, _ in batch]
        trace = self._trace_for(batch)
        t_disp = self._clock()
        try:
            if trace is not None:
                ctx = await loop.run_in_executor(
                    None, self.dispatch_fn, txns, trace)
            else:
                ctx = await loop.run_in_executor(
                    None, self.dispatch_fn, txns)
        except Exception as e:                   # noqa: BLE001
            for f in futs:
                if not f.done():
                    f.set_exception(e)
            return
        prev = self._inflight[-1] if self._inflight else None
        self._inflight.append(loop.create_task(
            self._finalize(loop, prev, ctx, futs, len(batch),
                           t_disp, [ts for _, _, ts in batch])))
        # with a tuning plane attached, the pipeline depth follows the
        # online tuner (re-read per batch, so a tuner move takes effect
        # one batch later); the serving app pins the tuner's range when
        # this path cannot apply it (single-phase / device pool)
        rec = getattr(self.controller, "recommended_inflight_depth", None)
        if rec is not None:
            self.pipeline_depth = max(1, int(rec()))
        # bound the pipeline: wait for the oldest finalize once depth
        # batches are in flight (device backpressure reaches the queue)
        while len(self._inflight) > self.pipeline_depth:
            task = self._inflight.pop(0)
            try:
                await task
            except Exception:  # noqa: BLE001 — waiters got the exception
                pass

    async def _finalize(self, loop, prev: Optional[asyncio.Task], ctx,
                        futs, n: int, t_disp: float = 0.0,
                        enq_ts=()) -> None:
        if prev is not None:
            try:
                await prev                       # completion stays in order
            except Exception:  # noqa: BLE001
                pass
        try:
            results = await loop.run_in_executor(None, self.finalize_fn, ctx)
        except Exception as e:                   # noqa: BLE001
            for f in futs:
                if not f.done():
                    f.set_exception(e)
            return
        self.batches += 1
        self.requests += n
        self._feed_tuning(n, t_disp, enq_ts)
        for f, r in zip(futs, results):
            if not f.done():
                f.set_result(r)

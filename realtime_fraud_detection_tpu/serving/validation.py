"""Request validation for the scoring API.

Mirrors the reference's Pydantic request models (main.py:67-106):
``TransactionFeatures{transaction_id, user_id, merchant_id, amount,
currency, payment_method, features{}, timestamp}`` — required identity/amount
fields, typed optionals, and a free-form ``features`` dict that flows into
the 64-feature contract. Plain functions instead of Pydantic: validation sits
on the request hot path and a dict pass costs ~1 µs vs model construction.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Mapping, Tuple

__all__ = ["validate_transaction", "validate_batch", "sanitize_for_stream"]

_REQUIRED = ("transaction_id", "user_id", "merchant_id", "amount")
_STRING_FIELDS = ("transaction_id", "user_id", "merchant_id", "currency",
                  "payment_method", "timestamp")

# stream-ingest coercion tables (the encode path's typed accessors);
# calendar fields carry their valid ranges — an out-of-range value (found
# by fuzzing: 2**31 passes int() but overflows the int32 batch column) is
# dropped so the encoder's neutral default applies
_STREAM_INT_FIELDS = (("hour_of_day", 0, 23), ("day_of_week", 1, 7),
                      ("day_of_month", 1, 31))
_STREAM_FLOAT_FIELDS = ("fraud_score",)
_STREAM_GEO_FIELDS = ("geolocation", "merchant_location")
_STREAM_STR_FIELDS = ("payment_method", "transaction_type", "card_type",
                      "user_agent", "ip_address", "device_fingerprint",
                      "description")


def sanitize_for_stream(body: Any) -> Tuple[Dict[str, Any], List[str]]:
    """Per-record ingest sanitizer for the stream path.

    The reference degrades per TRANSACTION, not per batch
    (TransactionProcessor.java:83-91 wraps each processElement); a poisoned
    field in one record must not push its 255 batch-mates onto the error
    path. Strict on identity + amount (reject), lenient on everything else
    (coerce or drop the field so the encoder's defaults apply). Returns
    (sanitized_record, errors); non-empty errors == divert this record to
    the per-record error result."""
    txn, errors = validate_transaction(body)
    if errors:
        return txn, errors
    for f, lo, hi in _STREAM_INT_FIELDS:
        if f in txn:
            try:
                v = int(txn[f])
            except (TypeError, ValueError, OverflowError):
                # OverflowError: int(float('inf')) — found by the ingest
                # fuzz test; an infinite hour/day field drops like any
                # other uncoercible value
                del txn[f]
                continue
            if lo <= v <= hi:
                txn[f] = v
            else:
                del txn[f]
    for f in _STREAM_FLOAT_FIELDS:
        if f in txn:
            try:
                v = float(txn[f])
                txn[f] = v if math.isfinite(v) else 0.0
            except (TypeError, ValueError):
                del txn[f]
    for f in _STREAM_GEO_FIELDS:
        geo = txn.get(f)
        if geo is not None:
            try:
                txn[f] = {"lat": float(geo["lat"]), "lon": float(geo["lon"])}
            except (TypeError, ValueError, KeyError):
                del txn[f]
    for f in _STREAM_STR_FIELDS:
        if f in txn and txn[f] is not None and not isinstance(txn[f], str):
            txn[f] = str(txn[f])
    return txn, []


def validate_transaction(body: Any) -> Tuple[Dict[str, Any], List[str]]:
    """Returns (normalized_txn, errors). Empty errors == valid."""
    errors: List[str] = []
    if not isinstance(body, Mapping):
        return {}, ["body must be a JSON object"]
    txn: Dict[str, Any] = dict(body)
    for f in _REQUIRED:
        if f not in txn or txn[f] in (None, ""):
            errors.append(f"missing required field: {f}")
    if "amount" in txn and txn.get("amount") not in (None, ""):
        try:
            amount = float(txn["amount"])
            if not math.isfinite(amount) or amount < 0:
                errors.append("amount must be a finite non-negative number")
            else:
                txn["amount"] = amount
        except (TypeError, ValueError):
            errors.append("amount must be a number")
    for f in _STRING_FIELDS:
        if f in txn and txn[f] is not None and not isinstance(txn[f], str):
            txn[f] = str(txn[f])
    feats = txn.get("features")
    if feats is not None and not isinstance(feats, Mapping):
        errors.append("features must be an object of name -> value")
    return txn, errors


def validate_batch(body: Any, limit: int) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Validate a /batch-predict payload: {"transactions": [...]} or a bare
    list (the reference accepts a list of TransactionFeatures,
    main.py:218-233)."""
    if isinstance(body, Mapping) and "transactions" in body:
        body = body["transactions"]
    if not isinstance(body, list):
        return [], ["body must be a list of transactions or "
                    "{'transactions': [...]}"]
    if len(body) == 0:
        return [], ["empty batch"]
    if len(body) > limit:
        return [], [f"batch size {len(body)} exceeds limit {limit}"]
    txns: List[Dict[str, Any]] = []
    errors: List[str] = []
    for i, item in enumerate(body):
        txn, errs = validate_transaction(item)
        if errs:
            errors.extend(f"[{i}] {e}" for e in errs)
        txns.append(txn)
    return txns, errors

"""Minimal asyncio HTTP/1.1 server for the scoring API.

Stdlib-only (asyncio streams): FastAPI/uvicorn are not part of this
framework's dependency surface, and the endpoint set (SURVEY.md §2.7 — seven
routes, JSON in/out) doesn't need them. Supports keep-alive, content-length
bodies, JSON errors, and per-connection tasks; TLS/chunked encoding are out
of scope (the reference terminates TLS at the ALB/ingress, not in-process —
fraud-detection-additional-resources.yaml ALB listener).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple
from urllib.parse import unquote_plus

__all__ = ["HttpServer", "JsonResponse", "HttpError"]

log = logging.getLogger(__name__)

_MAX_BODY = 32 * 1024 * 1024
_MAX_HEADER = 64 * 1024

# handler(body_json, query) -> (status, payload)
Handler = Callable[[Any, Dict[str, str]], Awaitable[Tuple[int, Any]]]

_REASONS = {200: "OK", 202: "Accepted", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 408: "Request Timeout",
            409: "Conflict",
            413: "Payload Too Large", 421: "Misdirected Request",
            422: "Unprocessable Entity",
            500: "Internal Server Error", 501: "Not Implemented",
            503: "Service Unavailable"}


class HttpError(Exception):
    def __init__(self, status: int, detail: Any):
        super().__init__(detail)
        self.status = status
        self.detail = detail


class JsonResponse:
    @staticmethod
    def encode(status: int, payload: Any, keep_alive: bool,
               content_type: str = "application/json") -> bytes:
        if content_type == "application/json":
            body = json.dumps(payload).encode()
        else:
            body = str(payload).encode()
        reason = _REASONS.get(status, "Unknown")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n"
            "\r\n"
        )
        return head.encode() + body


class HttpServer:
    """Route table + asyncio server. Routes are (METHOD, path) exact-match."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 drain_grace_s: float = 5.0):
        self.host = host
        self.port = port
        self.drain_grace_s = drain_grace_s
        self._routes: Dict[Tuple[str, str], Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        # task -> True while parked waiting for the next request (idle)
        self._conns: Dict[Any, bool] = {}
        self._closing = False

    def route(self, method: str, path: str, handler: Handler) -> None:
        self._routes[(method.upper(), path)] = handler

    async def start(self) -> None:
        # limit > _MAX_HEADER so readuntil can see an oversized header block
        # and we answer 413 instead of tripping the reader's own limit
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=2 * _MAX_HEADER)
        # resolve the ephemeral port
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Cancel only IDLE keep-alive handlers (parked waiting for the
            # next request): on py3.12 wait_closed() waits for every
            # connection handler, so a parked client would otherwise hang
            # shutdown forever. Handlers mid-request finish their response
            # first and then exit via the _closing flag.
            self._closing = True
            for task, idle in list(self._conns.items()):
                if idle:
                    task.cancel()
            try:
                await asyncio.wait_for(self._server.wait_closed(),
                                       timeout=self.drain_grace_s)
            except asyncio.TimeoutError:
                # grace expired: a handler is stuck mid-request (e.g. a
                # slow-loris body that never arrives) — cancel everything
                for task in list(self._conns):
                    task.cancel()
                await self._server.wait_closed()
            self._server = None
            self._closing = False

    # ------------------------------------------------------------- protocol
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conns[task] = True
        try:
            while True:
                keep_alive = await self._handle_one(reader, writer, task)
                if not keep_alive or self._closing:
                    break
                if task is not None:
                    self._conns[task] = True     # parked until next request
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        except Exception:                        # noqa: BLE001
            log.exception("connection handler error")
        finally:
            if task is not None:
                self._conns.pop(task, None)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:                    # noqa: BLE001
                pass

    async def _handle_one(self, reader, writer, task=None) -> bool:
        try:
            header_blob = await reader.readuntil(b"\r\n\r\n")
        except asyncio.LimitOverrunError:
            await self._respond(writer, 413, {"detail": "headers too large"},
                                False)
            return False
        if task is not None:
            self._conns[task] = False            # busy: request in flight
        if len(header_blob) > _MAX_HEADER:
            await self._respond(writer, 413, {"detail": "headers too large"},
                                False)
            return False
        head_lines = header_blob.decode("latin-1").split("\r\n")
        try:
            method, target, _version = head_lines[0].split(" ", 2)
        except ValueError:
            await self._respond(writer, 400, {"detail": "bad request line"},
                                False)
            return False
        headers = {}
        for line in head_lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()

        keep_alive = headers.get("connection", "keep-alive").lower() != "close"
        if "transfer-encoding" in headers:
            # chunked bodies are out of scope; reject rather than misparse
            # the chunk stream as the next request on this connection
            await self._respond(
                writer, 501, {"detail": "transfer-encoding not supported"},
                False)
            return False
        try:
            length = int(headers.get("content-length", "0") or 0)
        except ValueError:
            await self._respond(writer, 400,
                                {"detail": "bad content-length"}, False)
            return False
        if length < 0 or length > _MAX_BODY:
            status, msg = ((413, "body too large") if length > 0
                           else (400, "bad content-length"))
            await self._respond(writer, status, {"detail": msg}, False)
            return False
        raw = await reader.readexactly(length) if length else b""

        path, _, query_str = target.partition("?")
        query: Dict[str, str] = {}
        for pair in query_str.split("&"):
            if "=" in pair:
                k, _, v = pair.partition("=")
                query[unquote_plus(k)] = unquote_plus(v)

        handler = self._routes.get((method.upper(), path))
        if handler is None:
            known_paths = {p for _, p in self._routes}
            status = 405 if path in known_paths else 404
            await self._respond(
                writer, status, {"detail": f"no route {method} {path}"},
                keep_alive)
            return keep_alive

        body: Any = None
        if raw:
            try:
                body = json.loads(raw)
            except json.JSONDecodeError:
                await self._respond(
                    writer, 400, {"detail": "invalid JSON body"}, keep_alive)
                return keep_alive
        try:
            status, payload = await handler(body, query)
        except HttpError as e:
            status, payload = e.status, {"detail": e.detail}
        except Exception:                        # noqa: BLE001
            log.exception("handler error for %s %s", method, path)
            status, payload = 500, {"detail": "internal error"}
        content_type = "application/json"
        if isinstance(payload, str):
            content_type = "text/plain; version=0.0.4"  # Prometheus text
        await self._respond(writer, status, payload, keep_alive, content_type)
        return keep_alive

    @staticmethod
    async def _respond(writer, status, payload, keep_alive,
                       content_type="application/json") -> None:
        writer.write(JsonResponse.encode(status, payload, keep_alive,
                                         content_type))
        await writer.drain()

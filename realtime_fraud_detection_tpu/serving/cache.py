"""Serving-side prediction cache: TTL + bounded size, evict-oldest.

Mirror of the reference's ensemble prediction cache
(ensemble_predictor.py:437-471 — 300 s TTL, max 1000 entries, LRU-by-oldest),
keyed by transaction_id: a retried /predict or /batch-predict for the same
transaction serves the stored §2.7 response without another device round
trip. Scoring is stateful (velocity/history move on), so the cache exists
for idempotent retries, not memoization — the TTL bounds how stale a
served-again response can be.

Single-writer like the rest of the serving host state: MUTATING calls
(get/put/clear) happen under the serving score lock. ``stats()`` is the
one exception — it only reads int counters and len(), each an atomic read
under the GIL, so /health may call it lock-free from the event loop (a
momentarily torn hits/entries pair is fine for a monitoring endpoint;
blocking the event loop on the score lock, held across batch assembly,
would not be).
"""

from __future__ import annotations

import copy
import time
from collections import OrderedDict
from typing import Any, Dict, Optional


class PredictionCache:
    def __init__(self, ttl_seconds: float = 300.0, max_entries: int = 1000):
        self.ttl = ttl_seconds
        self.max_entries = max_entries
        self._data: "OrderedDict[str, tuple[float, Dict[str, Any]]]" = (
            OrderedDict())
        self.hits = 0
        self.misses = 0

    def get(self, key: str, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Deep copy out: a caller mutating the served response (experiment
        annotation, downstream enrichment) must not corrupt the entry."""
        # rtfd-lint: allow[wall-clock] default time base; callers pass now explicitly
        now = now if now is not None else time.monotonic()
        entry = self._data.get(key)
        if entry is None or now - entry[0] > self.ttl:
            if entry is not None:
                del self._data[key]    # expired
            self.misses += 1
            return None
        self.hits += 1
        return copy.deepcopy(entry[1])

    def put(self, key: str, result: Dict[str, Any],
            now: Optional[float] = None) -> None:
        """Deep copy in: the stored response is frozen at serve time."""
        if not key:
            return
        # rtfd-lint: allow[wall-clock] default time base; callers pass now explicitly
        now = now if now is not None else time.monotonic()
        self._data[key] = (now, copy.deepcopy(result))
        self._data.move_to_end(key)
        while len(self._data) > self.max_entries:
            self._data.popitem(last=False)         # evict oldest insertion

    def clear(self) -> None:
        """Drop entries, keep hit/miss counters (they are monotonic counters
        on /health — a model reload must not reset a scraped series)."""
        self._data.clear()

    def stats(self) -> Dict[str, Any]:
        return {"entries": len(self._data), "hits": self.hits,
                "misses": self.misses, "ttl_seconds": self.ttl,
                "max_entries": self.max_entries}

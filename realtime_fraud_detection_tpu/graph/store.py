"""Typed incremental entity graph: user↔device↔merchant↔IP adjacency.

``state.history.EntityGraphStore`` holds only the user↔merchant bipartite
edges, so the shared device fingerprints and egress IPs that define a
coordinated :class:`~realtime_fraud_detection_tpu.sim.fraud_patterns.
FraudRing` (``n_devices``/``n_ips``) never reach the GNN. This store is
the heterogeneous replacement: four node types, six directed edge types,
each source node keeping a bounded RECENCY RING of distinct neighbors
(most-recent-last, oldest evicted at the fanout cap — the same dense
fixed-fanout discipline the bipartite store uses, minus the duplicate
entries that would let one hot counterparty flood a small ring).

Identity is the STRING entity id, not a dense per-store index: adjacency
lists must merge across partition-scoped stores (``graph.fetch``) and a
dense index is only meaningful inside one store. The sampler resolves
ids → feature rows at gather time (``models.gnn.typed_entity_features``
for device/IP nodes, the scorer's entity tables for users/merchants).

Concurrency: mutation and reads take one internal lock — a worker's
:class:`~realtime_fraud_detection_tpu.graph.fetch.GraphFetchServer`
thread reads the live store while the worker's scoring thread ingests
at finalize time. The lock is never held across any blocking call.

Determinism: pure function of the ingest order (no clocks, no RNG) —
``cluster`` drills replay digest-identically with the graph riding
``PartitionState``.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

__all__ = ["NODE_TYPES", "EDGE_TYPES", "TypedEntityGraph"]

NODE_TYPES = ("user", "device", "merchant", "ip")

# directed edge types; each transaction ingests the user's three
# counterparty links in both directions
EDGE_TYPES = (
    "user->device", "device->user",
    "user->merchant", "merchant->user",
    "user->ip", "ip->user",
)

_REVERSE = {
    "user->device": "device->user",
    "user->merchant": "merchant->user",
    "user->ip": "ip->user",
}


class TypedEntityGraph:
    """Heterogeneous bounded-recency adjacency over string entity ids."""

    def __init__(self, fanout: int = 16):
        if fanout < 1:
            raise ValueError(f"fanout must be >= 1, got {fanout}")
        self.fanout = int(fanout)
        self._adj: Dict[str, Dict[str, List[str]]] = {
            et: {} for et in EDGE_TYPES}
        # bumped on every mutating ingest — an observability stamp
        # (stats()/graph_snapshot); sampler-cache COHERENCE runs on
        # drain_dirty (exact per-id eviction) + the owner's
        # ownership_epoch (wholesale on handoff), not on this counter
        self.generation = 0
        self.edges_added = 0
        # ids whose adjacency changed since the last drain_dirty(): the
        # sampler evicts exactly the cache entries depending on them
        self._dirty: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- pickling
    def __getstate__(self) -> Dict[str, Any]:
        state = dict(self.__dict__)
        del state["_lock"]           # locks don't pickle; snapshot is a copy
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self._lock = threading.Lock()

    # -------------------------------------------------------------- ingest
    @staticmethod
    def _ring_add(adj: Dict[str, List[str]], src: str, dst: str,
                  fanout: int) -> bool:
        """Recency-ring insert: distinct neighbors, most-recent-last,
        oldest evicted at the cap. Returns True when the ring changed."""
        ring = adj.get(src)
        if ring is None:
            adj[src] = [dst]
            return True
        if ring and ring[-1] == dst:
            return False                  # already the most recent
        try:
            ring.remove(dst)              # move-to-end on re-observation
        except ValueError:
            pass
        ring.append(dst)
        del ring[:-fanout]
        return True

    def add_transaction(self, user_id: str, merchant_id: str,
                        device_id: str, ip: str) -> None:
        self.add_batch([user_id], [merchant_id], [device_id], [ip])

    def add_batch(self, user_ids: Sequence[str],
                  merchant_ids: Sequence[str],
                  device_ids: Sequence[str],
                  ips: Sequence[str]) -> None:
        """Ingest one finalized microbatch's entity links (both edge
        directions per link; empty counterparty ids are skipped — a txn
        without a device fingerprint simply contributes no device edge)."""
        with self._lock:
            changed = False
            for uid, mid, did, ip in zip(user_ids, merchant_ids,
                                         device_ids, ips):
                uid = str(uid)
                if not uid:
                    continue
                for fwd, dst in (("user->device", str(did)),
                                 ("user->merchant", str(mid)),
                                 ("user->ip", str(ip))):
                    if not dst or dst == "None":
                        continue
                    rev = _REVERSE[fwd]
                    if self._ring_add(self._adj[fwd], uid, dst,
                                      self.fanout):
                        changed = True
                        self._dirty.add(uid)
                    if self._ring_add(self._adj[rev], dst, uid,
                                      self.fanout):
                        changed = True
                        self._dirty.add(dst)
                    self.edges_added += 1
            if changed:
                self.generation += 1

    # ------------------------------------------------------------- queries
    def neighbors(self, edge_type: str, ids: Sequence[str],
                  fanout: Optional[int] = None) -> List[List[str]]:
        """Per-source recency lists (oldest-first, ≤ fanout each). Unknown
        sources yield empty lists — a cold node has no neighborhood, not
        an error."""
        if edge_type not in EDGE_TYPES:
            raise ValueError(f"unknown edge type {edge_type!r}; expected "
                             f"one of {EDGE_TYPES}")
        k = self.fanout if fanout is None else max(1, int(fanout))
        adj = self._adj[edge_type]
        with self._lock:
            return [list(adj.get(str(i), ())[-k:]) for i in ids]

    def neighbor_map(self, edge_type: str, ids: Iterable[str],
                     fanout: Optional[int] = None) -> Dict[str, List[str]]:
        """{id: neighbors} for the fetch server's wire format; sources
        with no adjacency are omitted (the response stays proportional to
        what this store actually knows)."""
        ids = [str(i) for i in ids]
        out: Dict[str, List[str]] = {}
        for i, ring in zip(ids, self.neighbors(edge_type, ids, fanout)):
            if ring:
                out[i] = ring
        return out

    def degree(self, edge_type: str, ids: Sequence[str]) -> List[int]:
        """Current ring occupancy per source (the typed node featurizer's
        degree signal — capped at fanout by construction)."""
        if edge_type not in EDGE_TYPES:
            raise ValueError(f"unknown edge type {edge_type!r}")
        adj = self._adj[edge_type]
        with self._lock:
            return [len(adj.get(str(i), ())) for i in ids]

    # ---------------------------------------------------- sampler coherence
    def drain_dirty(self) -> List[str]:
        """Ids whose adjacency changed since the last drain (cleared).
        The sampler cache evicts entries depending on exactly these."""
        with self._lock:
            dirty = sorted(self._dirty)
            self._dirty.clear()
            return dirty

    # ------------------------------------------------------------- summary
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            nodes = {
                "user": len(set(self._adj["user->device"])
                            | set(self._adj["user->merchant"])
                            | set(self._adj["user->ip"])),
                "device": len(self._adj["device->user"]),
                "merchant": len(self._adj["merchant->user"]),
                "ip": len(self._adj["ip->user"]),
            }
            edges = {et: sum(len(r) for r in self._adj[et].values())
                     for et in EDGE_TYPES}
        return {"fanout": self.fanout, "generation": self.generation,
                "edges_added": self.edges_added, "nodes": nodes,
                "edges": edges}

    def digest(self) -> str:
        """Deterministic content hash over the full typed adjacency —
        feeds ``PartitionState.digest`` so handoff snapshot/restore and
        the drills' replay checks cover the graph bundle."""
        with self._lock:
            payload = {
                et: sorted((src, tuple(ring))
                           for src, ring in self._adj[et].items())
                for et in EDGE_TYPES
            }
        h = hashlib.sha256()
        h.update(json.dumps(payload, sort_keys=True,
                            default=list).encode())
        return h.hexdigest()

    def __len__(self) -> int:
        with self._lock:
            return sum(len(adj) for adj in self._adj.values())


def merge_neighbor_lists(local: Mapping[str, List[str]],
                         remotes: Sequence[Mapping[str, List[str]]],
                         ids: Sequence[str], fanout: int,
                         ) -> Dict[str, List[str]]:
    """Deterministic cross-store neighborhood merge.

    Edge data is partitioned by the TRANSACTION's user key (writes are
    always partition-local), so one device's user ring is spread across
    stores. The merged view concatenates local-first then each remote in
    caller order (the fetch client queries peers in sorted id order),
    dedups preserving first occurrence, and keeps the LAST ``fanout``
    entries — recency within each source is preserved; cross-source
    order is positional, deterministic, and documented as best-effort
    (the graph is an enrichment signal, not handed-off truth)."""
    out: Dict[str, List[str]] = {}
    for i in ids:
        i = str(i)
        seen: Dict[str, None] = {}
        for src in (local, *remotes):
            for n in src.get(i, ()):
                seen.setdefault(str(n))
        merged = list(seen)
        out[i] = merged[-max(1, int(fanout)):]
    return out

"""Real-time entity-graph plane: the GNN branch's serve-time substrate.

The chaos drill proved a coordinated :class:`~realtime_fraud_detection_tpu.
sim.fraud_patterns.FraudRing` is near-invisible per-feature (incumbent
ledger AUC 0.9255 → 0.6578 in the ring phase) — the learnable signal IS
the shared-entity linkage (many users funneling through a handful of
devices/IPs/merchants), which is exactly what the GraphSAGE branch
(arXiv:1706.02216) exists for. This package gives that branch a real
substrate:

- :mod:`graph.store` — :class:`TypedEntityGraph`: a heterogeneous
  user↔device↔merchant↔IP adjacency store with per-edge-type bounded
  recency rings, maintained incrementally from the transaction flow at
  finalize time and living inside ``cluster/partition.py``'s
  ``PartitionState`` bundle (snapshot/restore/digest — handoff, SIGKILL
  replay and the shard/elastic/partition drills carry it for free);
- :mod:`graph.sampler` — :class:`NeighborSampler`: a deterministic
  fixed-fanout two-hop sampler that walks ACROSS edge types
  (user→device→user, user→IP→user, merchant→user→merchant) and emits the
  padded ``[B,K]`` / ``[B,K,K]`` feature+mask tensors ``models/gnn.py``
  already consumes — host-prepared gathers only, generation-stamped
  cache (the serve-time feature-fetch problem of arXiv:2501.10546);
- :mod:`graph.fetch` — :class:`GraphFetchClient`/:class:`GraphFetchServer`:
  cross-partition neighbor resolution over the netbroker framing (rings
  deliberately straddle shards), with per-batch budgets, absolute
  deadlines and an explicit degrade-to-local-subgraph path — a
  partitioned link yields fewer neighbors, never a wedged worker;
- :mod:`graph.drill` — ``rtfd graph-drill``: the eleventh lockwatch
  drill, pinning ring-phase AUC lift of graph-on vs the trees-only
  incumbent end-to-end across ≥2 partition workers.
"""

from realtime_fraud_detection_tpu.graph.store import (  # noqa: F401
    EDGE_TYPES,
    NODE_TYPES,
    TypedEntityGraph,
)
from realtime_fraud_detection_tpu.graph.sampler import (  # noqa: F401
    NeighborSampler,
)
from realtime_fraud_detection_tpu.graph.fetch import (  # noqa: F401
    GraphFetchClient,
    GraphFetchServer,
    StaleGraphGenerationError,
)

__all__ = [
    "EDGE_TYPES",
    "NODE_TYPES",
    "TypedEntityGraph",
    "NeighborSampler",
    "GraphFetchClient",
    "GraphFetchServer",
    "StaleGraphGenerationError",
]

"""Cross-partition neighbor fetch: resolve non-owned graph nodes over TCP.

Graph edge data is partitioned by the TRANSACTION's user key (writes are
always local to the owning worker — the property that lets the graph
bundle ride handoff snapshots), so one shared entity's adjacency — a ring
device fingerprint serving users across several partitions — is SPREAD
over the fleet. Rings deliberately straddle shards; a partition-scoped
worker sampling a two-hop neighborhood must therefore resolve the remote
shares of its frontier nodes, and that resolution sits INSIDE the
score path's assemble stage, where the latency budget lives.

The protocol follows ``cluster/handoff.py``'s framing discipline
(netbroker length-prefixed JSON frames over one TCP connection per peer)
with the score path's own rules layered on top:

- **absolute per-batch deadline** — one wall-clock budget covers ALL
  remote resolution for a microbatch; a slow or partitioned peer eats
  the residual, never more (``_recv_frame(deadline=...)``, the PR 13
  whole-frame read bound);
- **bounded per-batch node budget** — remote lookups are capped per
  microbatch, so a pathological frontier cannot turn one assemble into
  a fan-out storm;
- **degrade-to-local, never stall** — any failure (deadline, budget,
  refused connection, netfault window, fenced generation) yields a
  PARTIAL result and a ``degraded`` flag: the sampler falls back to the
  local subgraph and the batch scores with fewer neighbors. A
  partitioned link means a sparser neighborhood, not a wedged worker.
- **backoff-gated reconnects** — a dead peer is retried on a
  ``DeterministicBackoff`` schedule measured on the injected clock (no
  sleeping in the score path: attempts before the next-allowed instant
  are skipped as degraded);
- **generation fencing awareness** — every request carries the client's
  assignment generation; a coordinator can fence a server at a new
  generation on rebalance, and a stale client's requests are refused
  with a typed :class:`StaleGraphGenerationError` marker (counted,
  degraded — the worker's own rebalance adoption refreshes the stamp;
  the handoff-plane idiom, not a crash).
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from realtime_fraud_detection_tpu.stream.netbroker import (
    _recv_frame,
    _send_frame,
)

__all__ = ["GraphFetchServer", "GraphFetchClient",
           "StaleGraphGenerationError"]


class StaleGraphGenerationError(RuntimeError):
    """A fetch carried an assignment generation older than the server's
    fence — the requester's view of partition ownership is stale (a
    rebalance it has not adopted yet). Refused loudly server-side;
    client-side it is a counted degrade, never a crash."""


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        server: GraphFetchServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server._conns.add(sock)
        try:
            while True:
                try:
                    req = _recv_frame(sock)
                except (ConnectionError, ValueError, OSError):
                    return
                if req is None:
                    return
                try:
                    resp = server.dispatch(req)
                except Exception as e:  # noqa: BLE001 - per-request isolation
                    resp = {"error": f"{type(e).__name__}: {e}"}
                try:
                    _send_frame(sock, resp)
                except (ConnectionError, OSError):
                    return
        finally:
            server._conns.discard(sock)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class GraphFetchServer:
    """Serve one worker's LOCAL typed-graph view to its peers.

    ``graph_source`` is a zero-arg callable returning the object to read
    (a ``TypedEntityGraph`` or a ``PartitionedStore.graph`` facade — any
    ``neighbor_map(edge_type, ids, fanout)`` provider); a callable so a
    handoff that swaps the worker's store swaps the served view with it.
    The server never fetches recursively: it answers with exactly what
    this worker's owned partitions know.
    """

    def __init__(self, graph_source: Callable[[], Any],
                 worker_id: str = "", host: str = "127.0.0.1",
                 port: int = 0, max_ids_per_request: int = 512):
        self._graph_source = graph_source
        self.worker_id = str(worker_id)
        self.max_ids_per_request = int(max_ids_per_request)
        self._fence_generation = 0
        self._lock = threading.Lock()
        self._conns: set = set()
        self.requests_total = 0
        self.fenced_requests_total = 0
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever,
            name=f"graph-fetch-{self.worker_id or 'server'}", daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "GraphFetchServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        for sock in list(self._conns):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ------------------------------------------------------------- fencing
    def fence(self, generation: int) -> None:
        """Coordinator seam: refuse requests stamped below ``generation``
        from here on (monotonic, like the handoff fence)."""
        with self._lock:
            self._fence_generation = max(self._fence_generation,
                                         int(generation))

    # ------------------------------------------------------------- dispatch
    def dispatch(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "neighbors":
            with self._lock:
                self.requests_total += 1
                fence = self._fence_generation
            gen = int(req.get("generation", 0))
            if gen < fence:
                with self._lock:
                    self.fenced_requests_total += 1
                raise StaleGraphGenerationError(
                    f"graph fetch fenced at generation {fence}; stale "
                    f"requester at generation {gen} refused")
            ids = [str(i) for i in (req.get("ids") or ())]
            ids = ids[: self.max_ids_per_request]
            graph = self._graph_source()
            k = req.get("k")
            # server-side child-span timing: the handling duration rides
            # the reply frame so the CLIENT's remote_fetch span can report
            # its server share (wire time = client span - srv_ms)
            t0 = time.perf_counter()  # rtfd-lint: allow[wall-clock] real RPC handling time reported to the caller
            neighbors = graph.neighbor_map(
                str(req.get("edge")), ids,
                int(k) if k is not None else None)
            return {
                "worker": self.worker_id,
                "neighbors": neighbors,
                "srv_ms": round((time.perf_counter() - t0) * 1e3, 4),  # rtfd-lint: allow[wall-clock] real RPC handling time reported to the caller
            }
        if op == "ping":
            return {"pong": True, "worker": self.worker_id}
        if op == "stats":
            with self._lock:
                return {"requests_total": self.requests_total,
                        "fenced_requests_total": self.fenced_requests_total,
                        "fence_generation": self._fence_generation}
        raise ValueError(f"unknown op {op!r}")


class GraphFetchClient:
    """Score-path client resolving remote neighbor shares from peers.

    One instance per worker, used from the worker's single assembly
    thread (the scorer's own concurrency contract). Peers are
    ``{peer_id: (host, port)}``; connections open lazily and reopen on a
    :class:`~realtime_fraud_detection_tpu.utils.backoff.
    DeterministicBackoff` schedule measured against the injected clock —
    the score path NEVER sleeps for the network.
    """

    def __init__(self, peers: Mapping[str, Tuple[str, int]],
                 deadline_ms: float = 25.0, node_budget: int = 64,
                 connect_timeout_s: float = 1.0,
                 clock: Optional[Callable[[], float]] = None,
                 backoff=None, link=None):
        from realtime_fraud_detection_tpu.utils.backoff import (
            DeterministicBackoff,
            instance_seed,
        )

        self.peers: Dict[str, Tuple[str, int]] = {
            str(p): (str(h), int(port))
            for p, (h, port) in sorted(peers.items())}
        self.deadline_ms = float(deadline_ms)
        self.node_budget = int(node_budget)
        self.connect_timeout_s = float(connect_timeout_s)
        self._clock = clock if clock is not None else time.monotonic
        self.backoff = backoff if backoff is not None else \
            DeterministicBackoff(base_s=0.05, mult=2.0, max_s=2.0,
                                 seed=instance_seed("graph-fetch"),
                                 sleep=lambda _s: None)
        # optional in-path chaos link (chaos/netfaults.py) — None in
        # production; the graph drill partitions this seam exactly like
        # the broker/handoff links
        self._link = link
        self.generation = 0
        self._socks: Dict[str, socket.socket] = {}
        # peer -> (consecutive failures, next retry instant on the clock)
        self._down: Dict[str, Tuple[int, float]] = {}
        # per-batch state (begin_batch resets)
        self._batch_deadline = float("inf")
        self._budget_left = self.node_budget
        self._batch_degraded = False
        self._batch_deadline_hit = False
        # cumulative counters (sync_graph mirrors as deltas)
        self.remote_fetch_total = 0        # peer requests attempted
        self.fetched_nodes_total = 0       # node adjacency entries received
        self.fetch_deadline_total = 0      # batches that hit the deadline
        self.fetch_error_total = 0         # refused/failed peer calls
        self.budget_exhausted_total = 0    # batches that hit the node budget
        self.stale_generation_total = 0    # fenced-generation refusals
        self.degraded_batches_total = 0    # batches with ANY degrade cause
        # distributed-tracing seam: the active batch's TraceBatch (set by
        # begin_batch(trace=...)); every peer call records a remote_fetch
        # child span on it, with the server's own srv_ms from the reply
        self._trace: Optional[Any] = None

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        for sock in self._socks.values():
            try:
                sock.close()
            except OSError:
                pass
        self._socks.clear()

    def set_generation(self, generation: int) -> None:
        """Adopt the fleet assignment generation (stamped on requests)."""
        self.generation = int(generation)

    # ------------------------------------------------------------ batch API
    def begin_batch(self, trace: Optional[Any] = None) -> None:
        """Open one microbatch's remote-resolution window: a fresh node
        budget and ONE absolute deadline shared by every fetch in the
        batch. ``trace`` (a ``TraceBatch``) attaches the tracing plane:
        each peer call then records a ``remote_fetch`` child span carved
        out of the enclosing stage, carrying the server-side ``srv_ms``
        returned in the reply frame."""
        self._batch_deadline = self._clock() + self.deadline_ms / 1e3
        self._budget_left = self.node_budget
        self._batch_degraded = False
        self._batch_deadline_hit = False
        self._trace = trace

    def end_batch(self) -> bool:
        """Close the window; True (and counted) when any fetch degraded.
        The deadline counter increments here, once per MICROBATCH — the
        sampler issues several fetch() calls per window, and each would
        observe the same expired deadline."""
        if self._batch_deadline_hit:
            self.fetch_deadline_total += 1
        if self._batch_degraded:
            self.degraded_batches_total += 1
        self._trace = None
        return self._batch_degraded

    # -------------------------------------------------------------- fetch
    def fetch(self, edge_type: str, ids: Sequence[str],
              fanout: Optional[int] = None,
              ) -> Tuple[List[Dict[str, List[str]]], bool]:
        """Resolve ``ids``' remote adjacency shares from every reachable
        peer. Returns (per-peer neighbor maps in sorted-peer order,
        degraded) — partial on ANY failure; the caller merges with its
        local view (graph.store.merge_neighbor_lists) and proceeds."""
        ids = [str(i) for i in ids]
        degraded = False
        if not ids or not self.peers:
            return [], False
        if self._budget_left <= 0:
            self.budget_exhausted_total += 1
            self._batch_degraded = True
            return [], True
        if len(ids) > self._budget_left:
            ids = ids[: self._budget_left]
            self.budget_exhausted_total += 1
            degraded = True
        self._budget_left -= len(ids)
        out: List[Dict[str, List[str]]] = []
        req = {"op": "neighbors", "edge": str(edge_type), "ids": ids,
               "generation": int(self.generation)}
        if fanout is not None:
            req["k"] = int(fanout)
        for peer in self.peers:
            now = self._clock()
            if now >= self._batch_deadline:
                self._batch_deadline_hit = True
                degraded = True
                break
            resp = self._call_peer(peer, req)
            if self._trace is not None:
                # client span (wall of the whole RPC) + the server-side
                # child duration from the reply frame: the stitched trace
                # shows both the worker's wait and the peer's handling
                self._trace.child_span(
                    "remote_fetch", (self._clock() - now) * 1e3,
                    peer=peer,
                    server=(resp or {}).get("worker", ""),
                    srv_ms=float((resp or {}).get("srv_ms", 0.0) or 0.0),
                    error=resp is None)
            if resp is None:
                degraded = True
                continue
            neigh = resp.get("neighbors") or {}
            out.append({str(i): [str(n) for n in ring]
                        for i, ring in neigh.items()})
            self.fetched_nodes_total += len(neigh)
        if degraded:
            self._batch_degraded = True
        return out, degraded

    # ---------------------------------------------------------- peer calls
    def _call_peer(self, peer: str,
                   req: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """One request/response against one peer inside the batch
        deadline. Any failure marks the peer down (backoff-gated retry on
        a LATER batch) and returns None — the caller degrades."""
        down = self._down.get(peer)
        now = self._clock()
        if down is not None and now < down[1]:
            self.fetch_error_total += 1
            return None
        sock = self._socks.get(peer)
        try:
            if sock is None:
                budget = min(self.connect_timeout_s,
                             max(self._batch_deadline - now, 1e-3))
                sock = socket.create_connection(self.peers[peer],
                                                timeout=budget)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._socks[peer] = sock
            if self._link is not None:
                self._link.before_send(req, 0)
            self.remote_fetch_total += 1
            _send_frame(sock, req)
            resp = _recv_frame(sock, deadline=self._batch_deadline)
            if resp is None:
                raise ConnectionError("graph fetch peer closed connection")
            if self._link is not None:
                self._link.after_recv(req)
        except (ConnectionError, OSError, ValueError):
            self._mark_down(peer)
            self.fetch_error_total += 1
            return None
        err = resp.get("error")
        if err is not None:
            if str(err).startswith("StaleGraphGenerationError"):
                # fenced: our assignment view is stale — degrade and let
                # the worker's rebalance adoption refresh the stamp
                self.stale_generation_total += 1
            else:
                self.fetch_error_total += 1
            return None
        self._down.pop(peer, None)
        return resp

    def _mark_down(self, peer: str) -> None:
        sock = self._socks.pop(peer, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        attempt = self._down.get(peer, (0, 0.0))[0]
        # next allowed attempt: pure-function backoff delay on the clock,
        # never a sleep — the score path stays non-blocking
        self._down[peer] = (attempt + 1,
                            self._clock() + self.backoff.delay(attempt))

    # ------------------------------------------------------------- summary
    def stats(self) -> Dict[str, Any]:
        return {
            "peers": len(self.peers),
            "peers_down": len(self._down),
            "generation": self.generation,
            "remote_fetch_total": self.remote_fetch_total,
            "fetched_nodes_total": self.fetched_nodes_total,
            "fetch_deadline_total": self.fetch_deadline_total,
            "fetch_error_total": self.fetch_error_total,
            "budget_exhausted_total": self.budget_exhausted_total,
            "stale_generation_total": self.stale_generation_total,
            "degraded_batches_total": self.degraded_batches_total,
        }

"""Graph drill: prove the entity-graph plane earns the GNN its place.

``rtfd graph-drill`` is the graph plane's acceptance artifact and the
ELEVENTH lockwatch drill. The chaos drill measured what a coordinated
:class:`~realtime_fraud_detection_tpu.sim.fraud_patterns.FraudRing` does
to a per-feature model (ledger AUC 0.9255 → 0.6578 — near-random,
because ring traffic is deliberately in-distribution per feature); this
drill pins the other half of that story: with the typed entity graph
maintained from the transaction flow, serve-time two-hop neighborhood
sampling feeding the GNN branch through the columnar assemble path, and
cross-partition neighbor fetch over the cluster plane, the GRAPH-ON
blend ranks the ring while the trees-only incumbent cannot.

One seeded virtual-clock timeline drives a healthy phase then a
ring phase end-to-end through ≥2 REAL partition-scoped workers
(``cluster.fleet.WorkerFleet`` over one shared broker log) whose
scorers are REAL ``FraudScorer`` instances in typed graph mode —
trained GBDT trees + a typed GNN trained on a DIFFERENT seeded
cohort's ring (the feedback-plane retrain premise: the model knows the
ring SHAPE, not these members' ids). Checks, all enforced fast and
full:

- **ring-phase AUC lift** — served (trees+GNN blend) AUC materially
  above the trees-only incumbent (the xgboost branch's own predictions
  from the same run's ledger) on the drill's truth ledger, ring phase;
  healthy-phase AUC must NOT regress;
- **cross-partition fetch exercised** — the ring straddles shards by
  construction (members hash across workers), and the workers' fetch
  clients demonstrably resolve remote neighbor shares (counts > 0);
- **graceful degrade** — a seeded netfault window fully partitions the
  graph-fetch links mid-ring-phase: degraded batches are counted INSIDE
  the window, none before it, and zero transactions are lost or errored
  (a partitioned link yields fewer neighbors, never a wedged worker);
- **columnar == serial** — with graph sampling enabled, ``assemble``
  and ``assemble_serial`` produce bit-identical tensors and scores;
- **bit-identical replay** — a second fully fresh run (fresh broker,
  fresh fleet, fresh TCP fetch servers) reproduces the same sha256
  digest over preds/offsets/state (wall-clock facts excluded).

Convention matches the ten sibling drills: full summary JSON, then a
compact (<2 KB) verdict as the FINAL stdout line.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.stream import topics as T

__all__ = ["GraphDrillConfig", "run_graph_drill", "compact_graph_summary"]


@dataclasses.dataclass
class GraphDrillConfig:
    """Drill sizes. Defaults = the full drill; ``fast()`` = the tier-1
    smoke — same phases, same netfault window, smaller stream."""

    seed: int = 7
    n_workers: int = 3
    n_partitions: int = 12          # the transactions topic's contract
    num_users: int = 4_000
    num_merchants: int = 120
    # phases (transactions)
    healthy_txns: int = 2_048
    ring_txns: int = 4_096
    # training segments (separate seeded generators)
    trees_train_txns: int = 4_096
    gnn_train_txns: int = 8_000
    n_trees: int = 32
    tree_depth: int = 6
    # stream shape
    batch: int = 64
    max_delay_ms: float = 25.0
    inflight_depth: int = 2
    tps: float = 2_000.0
    # deterministic service-cost model (virtual ms per dispatched batch)
    base_ms: float = 4.0
    per_txn_ms: float = 0.16
    # graph shape
    fanout: int = 8
    fanout2: int = 8
    node_dim: int = 16
    # the ring (serving phase; the training generator draws its own)
    ring_rate: float = 0.2
    ring_members: int = 24
    ring_devices: int = 4
    ring_ips: int = 3
    # cross-partition fetch. The fetch deadline is WALL-bound (socket
    # reads cannot run on the virtual clock), so the drill sets it far
    # past any plausible localhost stall: a deadline firing would change
    # sampled content and flake the replay digest on a loaded CI host.
    # The degrade path is exercised by the (virtual-clock-deterministic)
    # netfault partition window; the deadline path is unit-tested.
    fetch_deadline_ms: float = 30_000.0
    fetch_budget: int = 4_096
    # netfault window, as fractions of the ring phase
    netfault_start_frac: float = 0.35
    netfault_len_frac: float = 0.25
    # acceptance bars
    min_auc_lift: float = 0.05
    healthy_regression_slack: float = 0.05
    # second, fully fresh run compared digest-for-digest with the first
    replay_check: bool = True

    @classmethod
    def fast(cls) -> "GraphDrillConfig":
        """Tier-1 smoke: every phase (ring, remote fetch, netfault
        degrade, replay) still runs; the stream and training shrink."""
        return cls(n_workers=2, num_users=1_500, num_merchants=60,
                   healthy_txns=768, ring_txns=1_536,
                   trees_train_txns=2_048, gnn_train_txns=4_000,
                   n_trees=24)

    def cost_s(self, n: int) -> float:
        return (self.base_ms + n * self.per_txn_ms) / 1e3

    def phase_edges(self) -> Tuple[float, float, float, float]:
        """(t_ring, t_nf_start, t_nf_end, t_end) on the virtual clock."""
        t_ring = self.healthy_txns / self.tps
        ring_len = self.ring_txns / self.tps
        t0 = t_ring + self.netfault_start_frac * ring_len
        return (t_ring, t0, t0 + self.netfault_len_frac * ring_len,
                t_ring + ring_len)


# ----------------------------------------------------------------- helpers


def _auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Tie-averaged Mann-Whitney AUC — the feedback plane's pinned
    implementation (== sklearn.roc_auc_score), not a fifth copy."""
    from realtime_fraud_detection_tpu.feedback.prequential import (
        sliding_auc,
    )

    return sliding_auc(np.asarray(labels, np.float64),
                       np.asarray(scores, np.float64))


def _drill_bert_config():
    from realtime_fraud_detection_tpu.models.bert import BertConfig

    # minimal text branch: it is DISABLED in the blend and exists only so
    # the fused program keeps its production shape
    return BertConfig(vocab_size=2_048, hidden_size=32, num_layers=1,
                      num_heads=2, intermediate_size=64,
                      max_position_embeddings=64)


def _scorer_config(cfg: GraphDrillConfig):
    from realtime_fraud_detection_tpu.scoring import ScorerConfig

    return ScorerConfig(graph_mode="typed", fanout=cfg.fanout,
                        graph_fanout2=cfg.fanout2,
                        node_dim=cfg.node_dim, text_len=16,
                        token_cache_entries=4_096)


def _train_models(cfg: GraphDrillConfig):
    """Trained ScoringModels: GBDT trees on a seeded basic-mix stream
    through the production assemble path (the quant-drill recipe) + the
    typed GNN on a DIFFERENT seeded cohort's ring
    (training.neural.train_typed_gnn) — the drill's serving ring shares
    no member/device/IP ids with the training one, so any lift is the
    STRUCTURE generalizing, not id memorization."""
    import jax

    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.scoring.pipeline import (
        init_scoring_models,
    )
    from realtime_fraud_detection_tpu.sim.fraud_patterns import (
        FraudRingConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.training import GBDTTrainer
    from realtime_fraud_detection_tpu.training.neural import train_typed_gnn

    bert_config = _drill_bert_config()
    # -- trees: the per-feature incumbent
    gen_t = TransactionGenerator(num_users=cfg.num_users,
                                 num_merchants=cfg.num_merchants,
                                 seed=cfg.seed + 1_000)
    scorer = FraudScorer(scorer_config=_scorer_config(cfg),
                         bert_config=bert_config, seed=cfg.seed)
    scorer.seed_profiles(gen_t.users.profiles(), gen_t.merchants.profiles())
    xs, ys = [], []
    done, ts = 0, 0.0
    while done < cfg.trees_train_txns:
        n = min(cfg.batch, cfg.trees_train_txns - done)
        recs = gen_t.generate_batch(n)
        batch = scorer.assemble(recs, now=ts)
        xs.append(np.asarray(batch.features))
        ys.append(np.asarray([bool(r.get("is_fraud")) for r in recs],
                             np.float32))
        for r in recs:     # serving's write-back: later segments see state
            scorer.velocity.update(str(r.get("user_id", "")),
                                   float(r.get("amount", 0.0)), ts)
        done += n
        ts += n / 200.0
    trees = GBDTTrainer(n_estimators=cfg.n_trees, max_depth=cfg.tree_depth,
                        seed=cfg.seed).fit(np.concatenate(xs),
                                           np.concatenate(ys))
    # -- typed GNN: a different cohort's ring
    gen_g = TransactionGenerator(num_users=cfg.num_users,
                                 num_merchants=cfg.num_merchants,
                                 seed=cfg.seed + 2_000)
    gen_g.inject_fraud_ring(FraudRingConfig(
        rate=cfg.ring_rate, n_members=cfg.ring_members,
        n_devices=cfg.ring_devices, n_ips=cfg.ring_ips))
    gnn = train_typed_gnn(gen_g, n_transactions=cfg.gnn_train_txns,
                          fanout=cfg.fanout, fanout2=cfg.fanout2,
                          node_dim=cfg.node_dim, seed=cfg.seed)
    models = init_scoring_models(
        jax.random.PRNGKey(cfg.seed), bert_config=bert_config,
        node_dim=cfg.node_dim, n_trees=cfg.n_trees,
        tree_depth=cfg.tree_depth, gnn_typed=True)
    return models.replace(trees=trees, gnn=gnn), bert_config


def _build_schedule(cfg: GraphDrillConfig):
    """The seeded two-phase arrival timeline. Returns (sched, truth,
    ring_member_ids, profiles) where truth maps txn id →
    (phase, is_fraud, is_ring)."""
    from realtime_fraud_detection_tpu.sim.fraud_patterns import (
        FraudRingConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed, tps=cfg.tps)
    sched: List[Tuple[float, Dict[str, Any]]] = []
    truth: Dict[str, Tuple[str, bool, bool]] = {}
    t = 0.0

    def emit(txns, phase):
        nonlocal t
        for txn in txns:
            txn["event_ts"] = round(t, 9)
            sched.append((t, txn))
            truth[str(txn["transaction_id"])] = (
                phase, bool(txn.get("is_fraud")),
                txn.get("fraud_type") == "fraud_ring")
            t += 1.0 / cfg.tps

    done = 0
    while done < cfg.healthy_txns:
        n = min(1_024, cfg.healthy_txns - done)
        emit(gen.generate_batch(n), "healthy")
        done += n
    ring = gen.inject_fraud_ring(FraudRingConfig(
        rate=cfg.ring_rate, n_members=cfg.ring_members,
        n_devices=cfg.ring_devices, n_ips=cfg.ring_ips))
    done = 0
    while done < cfg.ring_txns:
        n = min(1_024, cfg.ring_txns - done)
        emit(gen.generate_batch(n), "ring")
        done += n
    return (sched, truth, [str(u) for u in ring.member_ids],
            (gen.users.profiles(), gen.merchants.profiles()))


# ------------------------------------------------------------------- fleet


def _run_fleet(cfg: GraphDrillConfig, sched, profiles, models,
               bert_config) -> Dict[str, Any]:
    """Drive one fleet of REAL typed-graph FraudScorers over the schedule
    on a fresh broker, with per-worker TCP graph-fetch servers and a
    seeded netfault window partitioning the fetch links mid-ring-phase."""
    from realtime_fraud_detection_tpu.chaos.faults import (
        ChaosPlan,
        FaultWindow,
    )
    from realtime_fraud_detection_tpu.chaos.netfaults import (
        LinkState,
        NetworkPartition,
    )
    from realtime_fraud_detection_tpu.cluster.fleet import WorkerFleet
    from realtime_fraud_detection_tpu.cluster.hashring import (
        partition_for_key,
    )
    from realtime_fraud_detection_tpu.graph.fetch import (
        GraphFetchClient,
        GraphFetchServer,
    )
    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker
    from realtime_fraud_detection_tpu.utils.backoff import (
        DeterministicBackoff,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    uprofs, mprofs = profiles
    broker = InMemoryBroker()
    clock = [0.0]
    vclock = lambda: clock[0]                                  # noqa: E731

    def factory(worker_id: str, store) -> FraudScorer:
        config = Config()
        for name in ("lstm_sequential", "bert_text", "isolation_forest"):
            config.disable_model(name)
        config.update_model_weight("xgboost_primary", 0.5)
        config.update_model_weight("graph_neural", 0.5)
        return FraudScorer(config=config, models=models,
                           scorer_config=_scorer_config(cfg),
                           bert_config=bert_config, stores=store)

    fleet = WorkerFleet(
        broker, cfg.n_workers, cfg.n_partitions, factory,
        topic=T.TRANSACTIONS, clock=vclock, max_batch=cfg.batch,
        max_delay_ms=cfg.max_delay_ms,
        store_kwargs={"graph_fanout": cfg.fanout})

    # profiles: each worker seeds its OWNED users (the facade refuses
    # non-owned keys by contract) + the replicated merchant set
    for w in fleet.workers.values():
        owned = set(w.store.owned())
        w.scorer.seed_profiles(
            {u: p for u, p in uprofs.items()
             if partition_for_key(u, cfg.n_partitions) in owned},
            mprofs)

    # graph-fetch plane: one TCP server per worker serving its owned
    # partitions' local graph view; each worker's client targets the
    # other workers, with a chaos link in the request path
    servers = {
        wid: GraphFetchServer(
            graph_source=(lambda w=w: w.store.graph),
            worker_id=wid).start()
        for wid, w in fleet.workers.items()}
    links: Dict[str, LinkState] = {}
    clients: Dict[str, GraphFetchClient] = {}
    for wid, w in fleet.workers.items():
        link = LinkState(f"graphfetch-{wid}", "peers",
                         sleep=lambda _s: None, seed=cfg.seed)
        client = GraphFetchClient(
            {pid: ("127.0.0.1", srv.port)
             for pid, srv in servers.items() if pid != wid},
            deadline_ms=cfg.fetch_deadline_ms,
            node_budget=cfg.fetch_budget,
            # retry a down peer on the very next batch: the drill's
            # failures come ONLY from the seeded link windows, so the
            # heal instant is the window edge, not a wall-clock backoff
            backoff=DeterministicBackoff(base_s=1e-6, mult=1.0,
                                         max_s=1e-6, jitter_frac=0.0,
                                         sleep=lambda _s: None),
            link=link)
        w.scorer.attach_graph_fetch(client)
        links[wid] = link
        clients[wid] = client

    t_ring, t_nf0, t_nf1, _t_end = cfg.phase_edges()
    plan = ChaosPlan([FaultWindow("graph_partition", "net", t_nf0, t_nf1)])
    plan.bind("graph_partition",
              NetworkPartition(list(links.values()), mode="full"))

    next_i = 0
    n = len(sched)
    degraded_pre_window: Optional[int] = None
    window_open = False

    def degraded_total() -> int:
        return sum(c.degraded_batches_total for c in clients.values())

    while True:
        now = clock[0]
        if not window_open and now >= t_nf0:
            degraded_pre_window = degraded_total()
            window_open = True
        plan.poll(now)
        while next_i < n and sched[next_i][0] <= now:
            ts, txn = sched[next_i]
            next_i += 1
            broker.produce(T.TRANSACTIONS, txn,
                           key=str(txn["user_id"]), timestamp=ts)
        progressed = False
        for w in fleet.alive_workers():
            while w.in_flight and w.in_flight[0][1] <= now:
                ctx, tdone = w.in_flight.popleft()
                if ctx is not None:
                    w.job.complete_batch(ctx, now=tdone)
                w.on_batch_complete()
                progressed = True
            if len(w.in_flight) < cfg.inflight_depth:
                batch = w.assembler.next_batch(block=False)
                if not batch and next_i >= n:
                    batch = w.assembler.flush()
                if batch:
                    ctx = w.job.dispatch_batch(batch, now=now)
                    start = max(now, w.busy_until)
                    done = start + cfg.cost_s(len(batch))
                    w.busy_until = done
                    w.in_flight.append((ctx, done))
                    progressed = True
        if progressed:
            continue
        alive = fleet.alive_workers()
        if (next_i >= n and fleet.lag() == 0
                and not any(w.in_flight for w in alive)
                and not any(w.assembler._pending for w in alive)):
            break
        targets: List[float] = []
        if next_i < n:
            targets.append(sched[next_i][0])
        for w in alive:
            if w.in_flight:
                targets.append(w.in_flight[0][1])
            if w.assembler._first_ts is not None:
                targets.append(w.assembler._first_ts
                               + cfg.max_delay_ms / 1e3)
        for fw in plan.windows:
            for edge in (fw.t_start, fw.t_end):
                if edge > now:
                    targets.append(edge)
        clock[0] = max(now + 1e-9,
                       min(targets) if targets else now + 0.01)

    makespan = clock[0]
    degraded_in_window = (degraded_total() - (degraded_pre_window or 0))

    # ---- ledger: the predictions topic, with per-branch predictions
    preds: List[Tuple[str, float, float, float, str]] = []
    for p in range(broker.partitions(T.PREDICTIONS)):
        off = 0
        while True:
            recs = broker.read(T.PREDICTIONS, p, off, 4096)
            if not recs:
                break
            off = recs[-1].offset + 1
            for r in recs:
                v = r.value if isinstance(r.value, dict) else {}
                ex = v.get("explanation") or {}
                kind = ("shed" if ex.get("shed")
                        else "replayed" if ex.get("replayed_from_cache")
                        else "error" if ex.get("error")
                        else "scored")
                mp = v.get("model_predictions") or {}
                preds.append((str(v.get("transaction_id", "")),
                              round(float(v.get("fraud_score", -1.0)), 6),
                              round(float(mp.get("xgboost_primary", -1.0)),
                                    6),
                              round(float(mp.get("graph_neural", -1.0)), 6),
                              kind))

    tx_ends = broker.end_offsets(T.TRANSACTIONS)
    committed = [broker.committed(fleet.group_id, T.TRANSACTIONS, p)
                 for p in range(len(tx_ends))]
    digests: Dict[int, str] = {}
    for w in fleet.alive_workers():
        for p, d in w.store.digests(now=makespan).items():
            digests[p] = d

    fetch_stats = {wid: c.stats() for wid, c in sorted(clients.items())}
    server_stats = {wid: {"requests_total": s.requests_total,
                          "fenced_requests_total": s.fenced_requests_total}
                    for wid, s in sorted(servers.items())}
    link_stats = {wid: lk.snapshot_entry()
                  for wid, lk in sorted(links.items())}
    graph_stats = {wid: w.scorer.graph_snapshot()
                   for wid, w in sorted(fleet.workers.items())}
    for srv in servers.values():
        srv.stop()
    for c in clients.values():
        c.close()

    # content digest: ledger + offsets + per-partition state (the graph
    # bundle rides PartitionState.digest) + assignment. Fetch/link
    # counters are NOT digested: the partition window's refusal COUNT can
    # vary with batch timing while the CONTENT (which neighborhoods were
    # resolvable) is pinned by the virtual-clock schedule.
    digest = hashlib.sha256(json.dumps({
        "preds": sorted(preds),
        "committed": committed,
        "assignment": fleet.assignment(),
        "state": sorted(digests.items()),
    }, sort_keys=True).encode()).hexdigest()

    return {
        "makespan_s": round(makespan, 4),
        "preds": preds,
        "committed": committed,
        "tx_ends": tx_ends,
        "digests": digests,
        "counters": fleet.counters(),
        "assignment": fleet.assignment(),
        "fetch": fetch_stats,
        "servers": server_stats,
        "links": link_stats,
        "graph": graph_stats,
        "degraded_pre_window": degraded_pre_window,
        "degraded_in_window": degraded_in_window,
        "digest": digest,
    }


# ---------------------------------------------------------- serial check


def _columnar_serial_check(cfg: GraphDrillConfig, models,
                           bert_config) -> Dict[str, Any]:
    """Bit-exactness of assemble vs assemble_serial WITH typed graph
    sampling enabled (fresh scorers, same trained models, ring traffic)."""
    import jax

    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.sim.fraud_patterns import (
        FraudRingConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    gen = TransactionGenerator(num_users=400, num_merchants=40,
                               seed=cfg.seed + 3_000)
    gen.inject_fraud_ring(FraudRingConfig(rate=cfg.ring_rate))
    pair = []
    for _ in range(2):
        s = FraudScorer(models=models, scorer_config=_scorer_config(cfg),
                        bert_config=bert_config, seed=cfg.seed)
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        pair.append(s)
    col, ser = pair
    leaves_equal = True
    score_mismatches = 0
    checked = 0
    for i in range(4):
        recs = gen.generate_batch(24)
        ts = float(i)
        b_col = col.assemble(recs, now=ts)
        b_ser = ser.assemble_serial(recs, now=ts)
        la, ta = jax.tree_util.tree_flatten(b_col)
        lb, tb = jax.tree_util.tree_flatten(b_ser)
        if ta != tb:
            leaves_equal = False
            break
        for x, y in zip(la, lb):
            if not np.array_equal(np.asarray(x), np.asarray(y)):
                leaves_equal = False
        r_col = col.finalize(col.dispatch_assembled(b_col, recs), now=ts)
        r_ser = ser.finalize(ser.dispatch_assembled(b_ser, recs), now=ts)
        for a, b in zip(r_col, r_ser):
            checked += 1
            if a["fraud_score"] != b["fraud_score"]:
                score_mismatches += 1
    return {"leaves_equal": leaves_equal,
            "score_mismatches": score_mismatches,
            "scores_checked": checked}


# ------------------------------------------------------------------ drill


def run_graph_drill(config: Optional[GraphDrillConfig] = None,
                    fast: bool = False) -> Dict[str, Any]:
    from realtime_fraud_detection_tpu.cluster.hashring import (
        partition_for_key,
    )

    cfg = config or (GraphDrillConfig.fast() if fast
                     else GraphDrillConfig())
    models, bert_config = _train_models(cfg)
    sched, truth, ring_members, profiles = _build_schedule(cfg)
    out = _run_fleet(cfg, sched, profiles, models, bert_config)

    # ---- truth-ledger AUCs per phase: served blend vs the trees-only
    # incumbent read from the SAME run's per-branch predictions
    phase_rows: Dict[str, Dict[str, List[float]]] = {
        "healthy": {"y": [], "served": [], "trees": [], "gnn": [],
                    "ring": []},
        "ring": {"y": [], "served": [], "trees": [], "gnn": [],
                 "ring": []},
    }
    by_id: Dict[str, int] = {}
    for tid, served, trees_p, gnn_p, kind in out["preds"]:
        if kind != "scored":
            continue
        by_id[tid] = by_id.get(tid, 0) + 1
        t = truth.get(tid)
        if t is None:
            continue
        phase, is_fraud, is_ring = t
        rows = phase_rows[phase]
        rows["y"].append(float(is_fraud))
        rows["served"].append(served)
        rows["trees"].append(trees_p)
        rows["gnn"].append(gnn_p)
        rows["ring"].append(float(is_ring))

    def aucs(phase: str) -> Dict[str, float]:
        rows = phase_rows[phase]
        y = np.asarray(rows["y"], bool)
        ring_mask = np.asarray(rows["ring"], bool)
        served = np.asarray(rows["served"])
        trees_p = np.asarray(rows["trees"])
        gnn_p = np.asarray(rows["gnn"])
        res = {
            "graph_on": round(_auc(y, served), 4),
            "incumbent_trees": round(_auc(y, trees_p), 4),
            "gnn_branch": round(_auc(y, gnn_p), 4),
        }
        keep = ring_mask | ~y          # ring fraud vs benign
        if ring_mask.any():
            res["ring_vs_benign_graph_on"] = round(
                _auc(ring_mask[keep], served[keep]), 4)
            res["ring_vs_benign_incumbent"] = round(
                _auc(ring_mask[keep], trees_p[keep]), 4)
        return res

    auc_healthy = aucs("healthy")
    auc_ring = aucs("ring")
    lift = round(auc_ring["graph_on"] - auc_ring["incumbent_trees"], 4)

    # ---- coverage / fetch / degrade facts
    produced = list(truth)
    lost = len(set(produced) - set(by_id))
    double = sum(1 for c in by_id.values() if c > 1)
    remote_fetches = sum(s["remote_fetch_total"]
                         for s in out["fetch"].values())
    remote_nodes = sum(s["fetched_nodes_total"]
                       for s in out["fetch"].values())
    partition_refusals = sum(lk["partitioned_sends_total"]
                             for lk in out["links"].values())
    # ring straddle: the cohort's partitions span >= 2 workers
    owner_of = {p: wid for wid, parts in out["assignment"].items()
                for p in parts}
    ring_workers = sorted({owner_of.get(
        partition_for_key(u, cfg.n_partitions), "?")
        for u in ring_members})

    serial = _columnar_serial_check(cfg, models, bert_config)

    replay_identical = None
    if cfg.replay_check:
        sched2, _truth2, _rm2, profiles2 = _build_schedule(cfg)
        second = _run_fleet(cfg, sched2, profiles2, models, bert_config)
        replay_identical = second["digest"] == out["digest"]

    checks = {
        "workers_enough": cfg.n_workers >= 2,
        "ring_straddles_shards": len(ring_workers) >= 2,
        "zero_lost": lost == 0,
        "every_txn_scored_once": (double == 0
                                  and len(by_id) == len(produced)),
        "zero_errors": out["counters"]["errors"] == 0,
        "offsets_gap_free": out["committed"] == out["tx_ends"],
        "remote_fetch_exercised": (remote_fetches > 0
                                   and remote_nodes > 0),
        "degrade_exercised_in_window": out["degraded_in_window"] > 0,
        "no_degrade_before_window": (out["degraded_pre_window"] or 0) == 0,
        "partition_refusals_counted": partition_refusals > 0,
        "ring_auc_lift": lift >= cfg.min_auc_lift,
        "healthy_not_regressed": (
            auc_healthy["graph_on"]
            >= auc_healthy["incumbent_trees"]
            - cfg.healthy_regression_slack),
        "columnar_serial_bitexact": (serial["leaves_equal"]
                                     and serial["score_mismatches"] == 0),
    }
    if replay_identical is not None:
        checks["replay_bit_identical"] = bool(replay_identical)

    summary: Dict[str, Any] = {
        "metric": "graph_drill",
        "passed": all(bool(v) for v in checks.values()),
        "checks": checks,
        "n_workers": cfg.n_workers,
        "n_partitions": cfg.n_partitions,
        "num_users": cfg.num_users,
        "produced": len(produced),
        "scored": out["counters"]["scored"],
        "lost": lost,
        "double_scored": double,
        "auc": {"healthy": auc_healthy, "ring": auc_ring,
                "ring_phase_lift": lift},
        "ring_workers": ring_workers,
        "ring_members": len(ring_members),
        "remote_fetches": remote_fetches,
        "remote_nodes": remote_nodes,
        "partition_refusals": partition_refusals,
        "degraded_in_window": out["degraded_in_window"],
        "degraded_pre_window": out["degraded_pre_window"],
        "fetch": out["fetch"],
        "graph": out["graph"],
        "columnar_serial": serial,
        "makespan_s": out["makespan_s"],
        "replay_identical": replay_identical,
        "digest": out["digest"],
    }
    return summary


def compact_graph_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line digest (bench.py convention: full
    result on the preceding line, compact parseable verdict last)."""
    auc = summary.get("auc") or {}
    compact = {
        "metric": "graph_drill",
        "passed": summary.get("passed"),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "n_workers": summary.get("n_workers"),
        "produced": summary.get("produced"),
        "scored": summary.get("scored"),
        "lost": summary.get("lost"),
        "ring_phase_lift": auc.get("ring_phase_lift"),
        "ring_auc": auc.get("ring"),
        "remote_fetches": summary.get("remote_fetches"),
        "degraded_in_window": summary.get("degraded_in_window"),
        "ring_workers": summary.get("ring_workers"),
        "digest": (summary.get("digest") or "")[:16],
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:
        for victim in ("ring_auc", "checks", "ring_workers", "digest",
                       "summary_of"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "graph_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact


# ------------------------------------------------------------- bench hook


def run_graph_sampling_bench(seed: int = 7) -> Dict[str, Any]:
    """The ``bench.py graph_sampling`` micro half: per-txn sampler cost
    cold vs cached on a seeded synthetic graph, and remote-fetch
    amortization (per-node one-at-a-time vs one batched request) against
    a live local fetch server. Pure host work — safe on any backend."""
    import time

    from realtime_fraud_detection_tpu.graph.fetch import (
        GraphFetchClient,
        GraphFetchServer,
    )
    from realtime_fraud_detection_tpu.graph.sampler import NeighborSampler
    from realtime_fraud_detection_tpu.graph.store import TypedEntityGraph

    rng = np.random.default_rng(seed)
    node_dim, fanout = 16, 8
    n_users, n_devices, n_merchants = 4_096, 1_024, 256
    graph = TypedEntityGraph(fanout=fanout)
    users = [f"u{i}" for i in range(n_users)]
    for start in range(0, n_users, 512):
        chunk = users[start:start + 512]
        graph.add_batch(
            chunk,
            [f"m{int(i)}" for i in rng.integers(0, n_merchants,
                                                len(chunk))],
            [f"d{int(i)}" for i in rng.integers(0, n_devices, len(chunk))],
            [f"ip{int(i)}" for i in rng.integers(0, 2_048, len(chunk))])

    zeros = lambda ids: np.zeros((len(ids), node_dim), np.float32)  # noqa: E731
    sampler = NeighborSampler(graph, node_dim, fanout, fanout,
                              user_rows=zeros, merchant_rows=zeros)
    batch_u = [f"u{int(i)}" for i in rng.integers(0, n_users, 256)]
    batch_m = [f"m{int(i)}" for i in rng.integers(0, n_merchants, 256)]
    t0 = time.perf_counter()  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
    sampler.sample(batch_u, batch_m)
    cold_us = (time.perf_counter() - t0) / len(batch_u) * 1e6  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
    t0 = time.perf_counter()  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
    sampler.sample(batch_u, batch_m)
    cached_us = (time.perf_counter() - t0) / len(batch_u) * 1e6  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds

    server = GraphFetchServer(lambda: graph, worker_id="bench").start()
    try:
        client = GraphFetchClient({"peer": ("127.0.0.1", server.port)},
                                  deadline_ms=5_000.0, node_budget=10_000)
        dev_ids = [f"d{int(i)}" for i in rng.integers(0, n_devices, 128)]
        client.begin_batch()
        t0 = time.perf_counter()  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
        for d in dev_ids:
            client.fetch("device->user", [d], fanout)
        per_node_us = (time.perf_counter() - t0) / len(dev_ids) * 1e6  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
        client.end_batch()
        client.begin_batch()
        t0 = time.perf_counter()  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
        client.fetch("device->user", dev_ids, fanout)
        batched_us = (time.perf_counter() - t0) / len(dev_ids) * 1e6  # rtfd-lint: allow[wall-clock] bench timing: real host microseconds
        client.end_batch()
        client.close()
    finally:
        server.stop()
    return {
        "graph_nodes": graph.stats()["nodes"],
        "sampler_cold_us_per_txn": round(cold_us, 2),
        "sampler_cached_us_per_txn": round(cached_us, 2),
        "cache_speedup": round(cold_us / max(cached_us, 1e-9), 2),
        "remote_per_node_us": round(per_node_us, 1),
        "remote_batched_us_per_node": round(batched_us, 1),
        "remote_batch_amortization": round(
            per_node_us / max(batched_us, 1e-9), 2),
        "sampler": sampler.stats(),
    }

"""Serve-time neighborhood sampling: typed graph → padded GNN tensors.

The columnar assemble path needs, per microbatch, the dense fixed-shape
neighbor tensors ``models/gnn.py`` consumes — ``[B, K, D]`` frontier
features + masks and ``[B, K, K2, D]`` two-hop context. This sampler
walks the typed graph ACROSS edge types:

- **user centers**: 1-hop frontier = the user's recent devices, IPs and
  merchants interleaved most-recent-first (``user→device`` /
  ``user→ip`` / ``user→merchant``); 2-hop = each frontier entity's USER
  ring (``device→user`` etc.) with the center excluded — for a benign
  device that ring is empty after exclusion, for a ring device it holds
  the cohort: the mask density IS the fraud-ring signature;
- **merchant centers**: 1-hop = the merchant's recent users
  (``merchant→user``), 2-hop = those users' merchant rings.

Everything is host-prepared gathers over small python rings — the device
sees only dense tensors. Entity-keyed 2-hop rings (``device→user``,
``ip→user``, ``merchant→user``) are the rings a fraud ring SPREADS across
partitions, so those (and only those) are resolved cross-partition
through an attached :class:`~realtime_fraud_detection_tpu.graph.fetch.
GraphFetchClient` — budgeted, deadlined, degrade-to-local.

**Cache.** Sampling is ~O(K·K2) python work per center; centers repeat
heavily (hot users, hot merchants), so samples are cached per center id,
generation-stamped like ``features/schema.EntityRowCache`` — but where
profile writes are rare, graph ingest happens EVERY batch, so wholesale
invalidation would never hit. Instead the graph reports which ids'
adjacency changed (``drain_dirty``) and the cache evicts exactly the
entries DEPENDING on them (center id ∪ frontier ids); entries also age
out after ``max_entry_age`` syncs (bounds staleness of remote-derived
neighborhoods the local dirty set cannot see), and an ownership-epoch
change (partition handoff swap) clears wholesale.

Determinism: a pure function of (graph state, fetch responses); the
drills replay bit-identically because both are functions of the seeded
schedule.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from realtime_fraud_detection_tpu.graph.store import merge_neighbor_lists
from realtime_fraud_detection_tpu.models.gnn import (
    MERCHANT_TAG_SLOT,
    typed_entity_features,
)

__all__ = ["NeighborSampler"]

# the three entity-keyed rings resolved cross-partition (a ring's shared
# devices/IPs/merchants accumulate user edges in every partition its
# members hash to); user-keyed rings are partition-local by ownership
REMOTE_EDGE_TYPES = ("device->user", "ip->user", "merchant->user")

_KIND_TO_USER_EDGE = {"device": "device->user", "ip": "ip->user",
                      "merchant": "merchant->user"}


class _Entry:
    """One cached center sample + its adjacency dependencies. ``born`` is
    the sampler's sync counter at build time — age is evaluated LAZILY at
    probe time (``_fresh``), so the post-ingest sync never scans the
    whole cache."""

    __slots__ = ("feat", "mask", "feat2", "mask2", "deps", "born")

    def __init__(self, feat, mask, feat2, mask2, deps, born):
        self.feat = feat
        self.mask = mask
        self.feat2 = feat2
        self.mask2 = mask2
        self.deps = deps
        self.born = born


class NeighborSampler:
    """Deterministic fixed-fanout two-hop sampler with a dependency-
    evicting cache.

    ``user_rows`` / ``merchant_rows`` resolve KNOWN center-table feature
    rows for user/merchant ids without creating entries (the scorer's
    ``_EntityIndex.peek_rows``); unknown ids resolve to zero rows — for
    2-hop users that is exactly right (the mask carries the signal, and a
    remote cohort member's profile is not this worker's to know).
    """

    def __init__(self, graph: Any, node_dim: int, fanout: int,
                 fanout2: int,
                 user_rows: Callable[[Sequence[str]], np.ndarray],
                 merchant_rows: Callable[[Sequence[str]], np.ndarray],
                 fetch: Optional[Any] = None,
                 max_entries: int = 65_536, max_entry_age: int = 64):
        self.graph = graph
        self.node_dim = int(node_dim)
        self.fanout = int(fanout)
        self.fanout2 = int(fanout2)
        self._user_rows = user_rows
        self._merchant_rows = merchant_rows
        self.fetch = fetch
        self.max_entries = max(1, int(max_entries))
        self.max_entry_age = max(1, int(max_entry_age))
        self._cache: Dict[str, _Entry] = {}
        self._deps: Dict[str, set] = {}      # entity id -> dependent keys
        self._epoch_seen = getattr(graph, "ownership_epoch", 0)
        self._syncs = 0                      # the lazy age-out clock
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------ coherence
    def attach_fetch(self, client: Any) -> None:
        self.fetch = client

    def sync(self) -> None:
        """Post-ingest coherence pass (the scorer calls this right after
        the finalize-time graph write-back): evict cache entries whose
        adjacency dependencies changed, advance the lazy age-out clock
        (entries past ``max_entry_age`` syncs are treated as misses at
        probe time — never a full-cache scan here, this is the hot
        write-back path), and clear wholesale on an ownership-epoch
        change (partition handoff)."""
        self._syncs += 1
        epoch = getattr(self.graph, "ownership_epoch", 0)
        if epoch != self._epoch_seen:
            self._epoch_seen = epoch
            self.evictions += len(self._cache)
            self._cache.clear()
            self._deps.clear()
            self.graph.drain_dirty()
            return
        for eid in self.graph.drain_dirty():
            for key in self._deps.pop(eid, ()):
                if self._cache.pop(key, None) is not None:
                    self.evictions += 1

    def _fresh(self, key: str) -> bool:
        """Probe: is there a live, un-aged entry for ``key``? An aged
        entry (built more than ``max_entry_age`` syncs ago — the bound on
        remote-derived staleness the local dirty set cannot see) is
        evicted here and reported as a miss."""
        entry = self._cache.get(key)
        if entry is None:
            return False
        if self._syncs - entry.born >= self.max_entry_age:
            self._evict(key)
            return False
        return True

    def _evict(self, key: str) -> None:
        entry = self._cache.pop(key, None)
        if entry is None:
            return
        self.evictions += 1
        for dep in entry.deps:
            keys = self._deps.get(dep)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._deps[dep]

    def _store(self, key: str, entry: _Entry) -> None:
        self._cache[key] = entry
        for dep in entry.deps:
            self._deps.setdefault(dep, set()).add(key)

    # ------------------------------------------------------------- sampling
    def sample(self, user_ids: Sequence[str], merchant_ids: Sequence[str],
               ) -> Dict[str, np.ndarray]:
        """Sample one microbatch's neighbor tensors (ScoreBatch fields).

        One remote-resolution window (budget + deadline) covers the whole
        batch; every remote ring needed by any cache-miss center is
        batched into at most one fetch per entity-keyed edge type."""
        b = len(user_ids)
        k, k2, d = self.fanout, self.fanout2, self.node_dim
        out = {
            "user_neigh_feat": np.zeros((b, k, d), np.float32),
            "user_neigh_mask": np.zeros((b, k), bool),
            "user_neigh2_feat": np.zeros((b, k, k2, d), np.float32),
            "user_neigh2_mask": np.zeros((b, k, k2), bool),
            "merch_neigh_feat": np.zeros((b, k, d), np.float32),
            "merch_neigh_mask": np.zeros((b, k), bool),
            "merch_neigh2_feat": np.zeros((b, k, k2, d), np.float32),
            "merch_neigh2_mask": np.zeros((b, k, k2), bool),
        }
        if b == 0:
            return out
        if self.fetch is not None:
            self.fetch.begin_batch()
        if len(self._cache) >= self.max_entries:
            # wholesale at the cap (the EntityRowCache discipline), taken
            # BEFORE the probes: within one sample() call entries only
            # grow, so pass 4 can rely on every probed-or-built center
            # being resident (a mid-batch clear would wipe probe hits)
            self.evictions += len(self._cache)
            self._cache.clear()
            self._deps.clear()

        # ---- pass 1: cache probe + frontier discovery for the misses
        u_missing: Dict[str, List[Tuple[str, str]]] = {}
        m_missing: Dict[str, None] = {}      # ordered id set
        for uid in dict.fromkeys(str(u) for u in user_ids):
            if self._fresh(f"u:{uid}"):
                self.hits += 1
                continue
            devs, mers, ips = (
                self.graph.neighbors(et, [uid], k)[0]
                for et in ("user->device", "user->merchant", "user->ip"))
            u_missing[uid] = self._interleave(devs, ips, mers)
        for mid in dict.fromkeys(str(m) for m in merchant_ids):
            if self._fresh(f"m:{mid}"):
                self.hits += 1
                continue
            m_missing[mid] = None

        # ---- pass 2: one batched remote resolution per entity-keyed edge
        remote: Dict[str, List[Dict[str, List[str]]]] = {
            et: [] for et in REMOTE_EDGE_TYPES}
        if self.fetch is not None and (u_missing or m_missing):
            need: Dict[str, List[str]] = {et: [] for et in REMOTE_EDGE_TYPES}
            for frontier in u_missing.values():
                for kind, eid in frontier:
                    need[_KIND_TO_USER_EDGE[kind]].append(eid)
            need["merchant->user"].extend(m_missing)
            for et in REMOTE_EDGE_TYPES:
                ids = sorted(dict.fromkeys(need[et]))
                if ids:
                    maps, _degraded = self.fetch.fetch(et, ids, k)
                    remote[et] = maps

        # ---- pass 3: build the missing entries
        for uid, frontier in u_missing.items():
            self._store(f"u:{uid}", self._build_user(uid, frontier, remote))
            self.misses += 1
        for mid in m_missing:
            self._store(f"m:{mid}", self._build_merchant(mid, remote))
            self.misses += 1

        # ---- pass 4: scatter the (now fully cached) rows
        for i, uid in enumerate(str(u) for u in user_ids):
            e = self._cache[f"u:{uid}"]
            out["user_neigh_feat"][i] = e.feat
            out["user_neigh_mask"][i] = e.mask
            out["user_neigh2_feat"][i] = e.feat2
            out["user_neigh2_mask"][i] = e.mask2
        for i, mid in enumerate(str(m) for m in merchant_ids):
            e = self._cache[f"m:{mid}"]
            out["merch_neigh_feat"][i] = e.feat
            out["merch_neigh_mask"][i] = e.mask
            out["merch_neigh2_feat"][i] = e.feat2
            out["merch_neigh2_mask"][i] = e.mask2
        if self.fetch is not None:
            self.fetch.end_batch()
        return out

    # ----------------------------------------------------------- internals
    def _interleave(self, devs: List[str], ips: List[str],
                    mers: List[str]) -> List[Tuple[str, str]]:
        """Typed frontier slots: devices, IPs and merchants interleaved
        most-recent-first (rings are oldest-first), ≤ fanout total —
        entity links (the ring signal) outrank a deep merchant tail."""
        streams = (("device", list(reversed(devs))),
                   ("ip", list(reversed(ips))),
                   ("merchant", list(reversed(mers))))
        frontier: List[Tuple[str, str]] = []
        i = 0
        while len(frontier) < self.fanout:
            added = False
            for kind, ring in streams:
                if i < len(ring):
                    frontier.append((kind, ring[i]))
                    added = True
                    if len(frontier) >= self.fanout:
                        break
            if not added:
                break
            i += 1
        return frontier

    def _merged_users(self, kind: str, eid: str,
                      remote: Dict[str, List[Dict[str, List[str]]]],
                      ) -> List[str]:
        et = _KIND_TO_USER_EDGE[kind]
        local = {eid: self.graph.neighbors(et, [eid], self.fanout)[0]}
        merged = merge_neighbor_lists(local, remote.get(et, ()), [eid],
                                      self.fanout)
        return merged[eid]

    def _build_user(self, uid: str, frontier: List[Tuple[str, str]],
                    remote: Dict[str, List[Dict[str, List[str]]]],
                    ) -> _Entry:
        k, k2, d = self.fanout, self.fanout2, self.node_dim
        feat = np.zeros((k, d), np.float32)
        mask = np.zeros((k,), bool)
        feat2 = np.zeros((k, k2, d), np.float32)
        mask2 = np.zeros((k, k2), bool)
        deps = {uid}
        for j, (kind, eid) in enumerate(frontier):
            deps.add(eid)
            users = [u for u in self._merged_users(kind, eid, remote)
                     if u != uid][-k2:]
            if kind == "merchant":
                feat[j] = self._merchant_row(eid)
            else:
                feat[j] = typed_entity_features(
                    kind, np.asarray([len(users) + 1], np.float32), d,
                    k2)[0]
            mask[j] = True
            if users:
                feat2[j, : len(users)] = self._user_rows(users)
                mask2[j, : len(users)] = True
        return _Entry(feat, mask, feat2, mask2, deps, self._syncs)

    def _build_merchant(self, mid: str,
                        remote: Dict[str, List[Dict[str, List[str]]]],
                        ) -> _Entry:
        k, k2, d = self.fanout, self.fanout2, self.node_dim
        feat = np.zeros((k, d), np.float32)
        mask = np.zeros((k,), bool)
        feat2 = np.zeros((k, k2, d), np.float32)
        mask2 = np.zeros((k, k2), bool)
        users = self._merged_users("merchant", mid, remote)[-k:]
        deps = {mid, *users}
        if users:
            feat[: len(users)] = self._user_rows(users)
            mask[: len(users)] = True
            # 2-hop: each frontier user's merchant ring (local by
            # ownership; non-owned users contribute empty rows — the
            # mask carries exactly what this worker can know)
            rings = self.graph.neighbors("user->merchant", users, k2)
            for j, ring in enumerate(rings):
                ring = [m for m in ring if m != mid][-k2:]
                if ring:
                    rows = np.stack([self._merchant_row(m) for m in ring])
                    feat2[j, : len(ring)] = rows
                    mask2[j, : len(ring)] = True
        return _Entry(feat, mask, feat2, mask2, deps, self._syncs)

    def _merchant_row(self, mid: str) -> np.ndarray:
        row = np.asarray(self._merchant_rows([mid])[0], np.float32).copy()
        # a cold merchant (no profile row yet) still carries its type tag
        row[MERCHANT_TAG_SLOT] = 1.0
        return row

    # ------------------------------------------------------------- summary
    def stats(self) -> Dict[str, Any]:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "entries": len(self._cache),
                "fanout": self.fanout, "fanout2": self.fanout2}

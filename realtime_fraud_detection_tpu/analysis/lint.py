"""``rtfd lint``: AST-level checker for this repo's own invariants.

Generic linters cannot see that ``time.monotonic()`` inside ``qos/`` breaks
``rtfd qos-drill``'s bit-identical virtual-clock replay, or that one
``np.asarray`` on a device array inside a pre-pull-safe bench module flips
a tunneled TPU into ~85 ms synchronous dispatch (utils/timing.py rule 2).
These rules encode exactly those contracts:

``wall-clock``
    No bare ``time.time()/monotonic()/perf_counter()`` (or
    ``datetime.now()``) in the virtual-clock-capable subsystems
    (CLOCK_SUBSYSTEMS). Wall clock must arrive through an injected
    ``clock``/``now`` seam; the genuinely wall-clock sites carry
    ``# rtfd-lint: allow[wall-clock] <why>``.

``d2h``
    No ``np.asarray`` / ``jax.device_get`` / ``.item()`` /
    ``float(<non-literal>)`` in the dispatch-path and pre-pull-safe bench
    scopes (D2H_MODULES / D2H_FUNCTIONS) — only ``block_until_ready`` is
    safe inside timed sections. Host-array conversions that can never see
    a device array are annotated, which doubles as documentation of WHY
    they are safe.

``metrics``
    Prometheus hygiene for the shared exposition: counters end in
    ``_total`` and are snake_case (gauges/histograms must NOT claim
    ``_total``), every MetricsCollector counter has exactly one writing
    plane outside obs/metrics.py (or lives behind a ``sync_*``/``record_*``
    mirror inside it), no counter ever ``.inc(<variable>)``s a raw
    cumulative total from outside the collector (that is what the
    counter-delta ``sync_*`` mirrors are for), and no dead series.

``lock-order``
    Param / degradation-mask mutation (MUTATORS) must be reached under the
    score lock — a call-graph walk: a mutation site is fine if it is
    lexically under a ``with <...lock...>``, receives ``lock=``, or if
    every package caller chain that reaches it holds one; the single-
    threaded entry points (drills, the stream job's run loop) are
    annotated where they are single-writer by construction. Also: no
    blocking queue op / ``time.sleep`` / thread join while lexically
    inside a ``with``-lock body.

``determinism``
    No global-RNG ``random.*`` / ``np.random.*`` draws in ``sim/``, any
    ``*drill*`` module, the quantization calibrators
    (``DETERMINISM_MODULES``), or the partition-parallel worker plane
    (``DETERMINISM_SUBSYSTEMS``: all of ``cluster/`` — ring placement
    and handoff must replay bit-identically) — seeded generator instances
    (``np.random.default_rng(seed)``, ``random.Random(seed)``,
    ``jax.random.PRNGKey``) only, so every drill replays bit-identically
    and the same weights always calibrate to the same int8 blobs.

``pragma-hygiene``
    Every ``# rtfd-lint: allow[rule]`` must name a known rule and still
    suppress a real finding — a pragma that stops matching (the code
    under it was fixed or moved) is itself an error, so stale waivers
    cannot accumulate.

Pragmas apply to their own line, or — as a comment-only line — to the
next code line. ``allow[a,b]`` names several rules at once. See
docs/analysis.md for the catalog and ``rtfd lint --help`` for the CLI.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "RULES",
    "format_findings",
    "lint_paths",
    "lint_source",
    "run_lint",
]

PACKAGE_NAME = "realtime_fraud_detection_tpu"

# Subsystems that can run under the drills' virtual clock: a bare wall-
# clock read here silently diverges a replay. (chaos/ joined with
# ISSUE 13: the ChaosPlan/link-fault layer never reads time by contract
# — clocks and sleep seams are injected; partition_drill.py's real-
# process pacing carries justified pragmas like elastic_drill.)
CLOCK_SUBSYSTEMS = frozenset(
    {"qos", "tuning", "feedback", "obs", "stream", "serving", "scoring",
     "sim", "cluster", "chaos", "graph"})

# Whole modules under the pre-pull-safe / dispatch-path d2h contract
# (utils/timing.py rule 2: only block_until_ready inside timed sections).
D2H_MODULES = frozenset({
    "utils/timing.py",
    "scoring/device_pool.py",
    "scoring/host_pipeline.py",
    "scoring/pool_drill.py",
    # quantized scoring plane (ISSUE 9): calibration is host-side work at
    # model-swap time by contract — every np.asarray there must be a
    # justified pragma, and anything unexplained is a dispatch-path leak.
    # (scoring/quant_drill.py is deliberately NOT here: like the other
    # drills it is an oracle-comparison harness whose whole job is
    # pulling both programs' scores host-side; determinism scope still
    # applies via the *drill* name convention.)
    "models/quant.py",
    # mesh-sharded serving plane (ISSUE 11): the executor's dispatch path
    # is under the same pre-pull contract as the pool's — wait() is the
    # designated pull, complete_no_fetch drains via block_until_ready
    # (scoring/mesh_drill.py rides the *drill* determinism convention and
    # is an oracle harness like pool_drill, which is already here).
    "scoring/mesh_executor.py",
    "scoring/mesh_drill.py",
    # Pallas kernel plane (ISSUE 17): kernel wrappers sit directly inside
    # the fused dispatch program — any host pull there would stall every
    # launch, so all three modules carry the full-module d2h contract.
    # (scoring/kernel_drill.py rides the *drill* determinism convention
    # and is an oracle harness like quant_drill, deliberately NOT here.)
    "ops/attention.py",
    "ops/dequant_matmul.py",
    "ops/epilogue.py",
    # persistent megakernel (ISSUE 19): the whole-batch program IS the
    # dispatch — a host pull anywhere in it would serialize every launch
    "ops/megakernel.py",
})
# Function-scoped d2h contract: the scorer's dispatch half must stay
# pull-free (finalize is the designated pull point).
D2H_FUNCTIONS: Dict[str, frozenset] = {
    "scoring/scorer.py": frozenset({"dispatch", "dispatch_assembled"}),
}

# Modules under the determinism contract beyond the sim/ + *drill*
# name conventions: int8 calibration must be a pure function of the
# weights (hot-swap on N replicas and checkpoint round-trips both assume
# the same f32 pytree always quantizes to the same blobs).
DETERMINISM_MODULES = frozenset({
    "models/quant.py",
    # link-fault layer (ISSUE 13): fault schedules ride worker specs
    # across the process boundary and must replay bit-identically inside
    # a fresh interpreter — seeded rng instances only, no global RNG
    "chaos/netfaults.py",
    # fleet observability plane (ISSUE 20): the coordinator's metric
    # fold and trace stitching feed obs-drill's digest — the aggregation
    # must be a pure function of the ingested events/rings
    "obs/fleetmetrics.py",
})
# Whole subsystems under the determinism contract: every cluster/ module
# is replay-critical — ring placement, partition routing, handoff
# snapshots, and the shard drill must all be pure functions of their
# seeds/inputs, or `rtfd shard-drill`'s bit-identical second run lies.
DETERMINISM_SUBSYSTEMS = frozenset({
    "cluster",
    # entity-graph plane (ISSUE 14): the typed store rides PartitionState
    # handoff blobs and the sampler/fetch results feed score content —
    # graph-drill's digest-identical fresh second run requires every
    # module to be a pure function of its inputs (seeded rng only)
    "graph",
    # Pallas kernel plane (ISSUE 17): kernels must be pure functions of
    # their operands or kernel-drill's parity digest lies — no hidden RNG
    # (tie-breaking, dropout-style noise) may ever enter a kernel wrapper
    "ops",
})

# Param / degradation-mask mutators: reachable only under the score lock
# (or from a single-writer thread, annotated at the entry point).
MUTATORS = frozenset({
    "set_degradation",
    "set_models",
    "refresh_blend_from_config",
    "promote_candidate",
    "restore_into_scorer",
})

_WALL_FNS = frozenset({
    "time", "monotonic", "perf_counter", "process_time",
    "time_ns", "monotonic_ns", "perf_counter_ns", "process_time_ns",
})
_DATETIME_FNS = frozenset({"now", "utcnow", "today"})
_NP_RANDOM_OK = frozenset({
    "default_rng", "RandomState", "Generator", "SeedSequence", "PCG64",
    "Philox", "bit_generator",
})
# stdlib `random` module-level draws that use the hidden global RNG
_RANDOM_GLOBAL_FNS = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "expovariate",
    "betavariate", "gammavariate", "lognormvariate", "vonmisesvariate",
    "paretovariate", "weibullvariate", "triangular", "getrandbits",
    "randbytes", "seed",
})

_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_PRAGMA_RE = re.compile(
    r"#\s*rtfd-lint:\s*allow\[([A-Za-z0-9_\-\s,]*)\](.*)$")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}")


@dataclass
class Pragma:
    path: str
    line: int            # line the pragma comment sits on
    target: int          # code line it covers
    rules: Tuple[str, ...]
    hits: int = 0


@dataclass
class Module:
    relpath: str         # package-relative, '/'-separated (e.g. "qos/plane.py")
    path: str            # display / reporting path
    source: str
    tree: ast.Module
    lines: List[str]
    # import alias sets, resolved per file
    time_names: Set[str] = field(default_factory=set)
    datetime_mod: Set[str] = field(default_factory=set)
    datetime_cls: Set[str] = field(default_factory=set)
    numpy_names: Set[str] = field(default_factory=set)
    jax_names: Set[str] = field(default_factory=set)
    random_names: Set[str] = field(default_factory=set)
    from_imports: Dict[str, str] = field(default_factory=dict)  # name -> mod

    @property
    def subsystem(self) -> Optional[str]:
        if "/" in self.relpath:
            return self.relpath.split("/", 1)[0]
        return None


def _resolve_aliases(mod: Module) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if alias.name == "time":
                    mod.time_names.add(bound)
                elif alias.name == "datetime":
                    mod.datetime_mod.add(bound)
                elif alias.name in ("numpy", "numpy.random"):
                    mod.numpy_names.add(bound)
                elif alias.name == "jax" or alias.name.startswith("jax."):
                    mod.jax_names.add(bound)
                elif alias.name == "random":
                    mod.random_names.add(bound)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                bound = alias.asname or alias.name
                mod.from_imports[bound] = f"{node.module}.{alias.name}"
                if node.module == "datetime" and alias.name == "datetime":
                    mod.datetime_cls.add(bound)


def _parse_pragmas(mod: Module) -> List[Pragma]:
    """Pragmas from REAL comment tokens only (a pragma-shaped substring
    inside a string literal — e.g. this linter's own messages — is not a
    pragma)."""
    pragmas: List[Pragma] = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(mod.source).readline))
    except (tokenize.TokenError, IndentationError):
        return pragmas
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        target = i
        if mod.lines[i - 1].strip().startswith("#"):
            # comment-only pragma line: covers the next code line
            for j in range(i + 1, len(mod.lines) + 1):
                nxt = mod.lines[j - 1].strip()
                if nxt and not nxt.startswith("#"):
                    target = j
                    break
        pragmas.append(Pragma(mod.path, i, target, rules))
    return pragmas


def _load_module(path: str, relpath: str,
                 source: Optional[str] = None) -> Optional[Module]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    mod = Module(relpath=relpath.replace(os.sep, "/"), path=path,
                 source=source, tree=tree, lines=source.splitlines())
    _resolve_aliases(mod)
    return mod


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted-name rendering of an expression ('a.b.c')."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(_dotted(node.func) + "()")
    return ".".join(reversed(parts))


def _is_lockish(expr: ast.AST) -> bool:
    if isinstance(expr, ast.IfExp):
        # `with (lock if lock is not None else nullcontext())`
        return _is_lockish(expr.body) or _is_lockish(expr.orelse)
    if isinstance(expr, ast.BoolOp):
        return any(_is_lockish(v) for v in expr.values)
    name = _dotted(expr).lower()
    leaf = name.rsplit(".", 1)[-1]
    return ("lock" in leaf or leaf in ("_cv", "cv")
            or "cond" in leaf)


# --------------------------------------------------------------------- rules

def _rule_wall_clock(ctx: "Context") -> List[Finding]:
    out: List[Finding] = []
    for mod in ctx.modules:
        if mod.subsystem not in CLOCK_SUBSYSTEMS:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            bad = None
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                base = f.value.id
                if base in mod.time_names and f.attr in _WALL_FNS:
                    bad = f"time.{f.attr}()"
                elif base in mod.datetime_cls and f.attr in _DATETIME_FNS:
                    bad = f"datetime.{f.attr}()"
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Attribute)
                  and isinstance(f.value.value, ast.Name)
                  and f.value.value.id in mod.datetime_mod
                  and f.value.attr == "datetime"
                  and f.attr in _DATETIME_FNS):
                bad = f"datetime.datetime.{f.attr}()"
            elif isinstance(f, ast.Name):
                target = mod.from_imports.get(f.id, "")
                if target.startswith("time.") \
                        and target.split(".", 1)[1] in _WALL_FNS:
                    bad = f"{target}()"
            if bad:
                out.append(Finding(
                    "wall-clock", mod.path, node.lineno, node.col_offset,
                    f"bare {bad} in virtual-clock-capable subsystem "
                    f"'{mod.subsystem}/' — route through the injected "
                    f"clock/now seam, or annotate the genuinely wall-clock "
                    f"site with `# rtfd-lint: allow[wall-clock] <why>`"))
    return out


def _d2h_scopes(mod: Module) -> List[Tuple[ast.AST, str]]:
    """(scope node, label) pairs the d2h rule checks in this module."""
    if mod.relpath in D2H_MODULES or mod.relpath == "bench.py":
        return [(mod.tree, mod.relpath)]
    wanted = D2H_FUNCTIONS.get(mod.relpath)
    if not wanted:
        return []
    scopes = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in wanted:
            scopes.append((node, node.name))
    return scopes


def _rule_d2h(ctx: "Context") -> List[Finding]:
    out: List[Finding] = []
    for mod in ctx.modules:
        for scope, label in _d2h_scopes(mod):
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                msg = None
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in mod.numpy_names and f.attr in (
                            "asarray", "array", "ascontiguousarray"):
                    msg = f"np.{f.attr}() in pre-pull-safe scope '{label}'"
                elif isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in mod.jax_names \
                        and f.attr == "device_get":
                    msg = f"jax.device_get() in pre-pull-safe scope " \
                          f"'{label}'"
                elif isinstance(f, ast.Attribute) and f.attr == "item" \
                        and not node.args and not node.keywords:
                    msg = f".item() in pre-pull-safe scope '{label}'"
                elif isinstance(f, ast.Name) and f.id == "float" \
                        and node.args \
                        and not isinstance(node.args[0], ast.Constant) \
                        and mod.relpath != "bench.py":
                    # bench.py builds large host-float report dicts; the
                    # float() heuristic would drown the real signal there
                    # (its asarray/device_get sites stay checked)
                    msg = (f"float() on a non-literal in pre-pull-safe "
                           f"scope '{label}'")
                if msg:
                    out.append(Finding(
                        "d2h", mod.path, node.lineno, node.col_offset,
                        f"{msg}: a device->host pull here breaks the "
                        f"timing discipline (utils/timing.py rule 2 — "
                        f"only block_until_ready is safe); move the pull "
                        f"past the timed/dispatch section or annotate a "
                        f"provably-host value with "
                        f"`# rtfd-lint: allow[d2h] <why>`"))
    return out


def _metric_registrations(mod: Module) -> List[Tuple[str, str, int, int]]:
    """(kind, name, line, col) for every metric constructor in a module."""
    regs = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        kind = None
        if isinstance(f, ast.Attribute) and f.attr in (
                "counter", "gauge", "histogram"):
            kind = f.attr
        elif isinstance(f, ast.Name) and f.id in (
                "Counter", "Gauge", "Histogram"):
            kind = f.id.lower()
        if kind is None:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            regs.append((kind, first.value, node.lineno, node.col_offset))
        elif isinstance(first, ast.JoinedStr):
            # f-string metric names (cli.py validation textfile): check the
            # static prefix for snake_case only
            continue
    return regs


def _collector_counter_attrs(metrics_mod: Module) -> Dict[str, int]:
    """MetricsCollector counter attributes -> definition line."""
    attrs: Dict[str, int] = {}
    for node in ast.walk(metrics_mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "counter":
            attrs[t.attr] = node.lineno
    return attrs


def _rule_metrics(ctx: "Context") -> List[Finding]:
    out: List[Finding] = []
    metrics_mod = None
    for mod in ctx.modules:
        if mod.relpath == "obs/metrics.py":
            metrics_mod = mod
        for kind, name, line, col in _metric_registrations(mod):
            if not _SNAKE_RE.match(name):
                out.append(Finding(
                    "metrics", mod.path, line, col,
                    f"metric name {name!r} is not snake_case"))
            if kind == "counter" and not name.endswith("_total"):
                out.append(Finding(
                    "metrics", mod.path, line, col,
                    f"counter {name!r} must end in '_total' (Prometheus "
                    f"counter convention; rate()/increase() consumers key "
                    f"on it)"))
            if kind in ("gauge", "histogram") and name.endswith("_total"):
                out.append(Finding(
                    "metrics", mod.path, line, col,
                    f"{kind} {name!r} must not claim the '_total' counter "
                    f"suffix"))
    if metrics_mod is None:
        return out
    counter_attrs = _collector_counter_attrs(metrics_mod)

    # internal writers: any Load of self.<attr> beyond the registration
    # assignment counts (the sync_* mirrors iterate (key, counter) tuples,
    # so the .inc receiver is often a local alias of the attribute)
    internal_writers: Set[str] = set()
    reg_lines = set(counter_attrs.values())
    for node in ast.walk(metrics_mod.tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in counter_attrs \
                and node.lineno not in reg_lines:
            internal_writers.add(node.attr)

    # .inc sites on collector counter attributes, per module
    writers: Dict[str, Dict[str, List[Tuple[int, int]]]] = {}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "inc"
                    and isinstance(node.func.value, ast.Attribute)):
                continue
            attr = node.func.value.attr
            if attr not in counter_attrs:
                continue
            if mod is metrics_mod:
                continue
            writers.setdefault(attr, {}).setdefault(
                mod.relpath, []).append((node.lineno, node.col_offset))
            # honest-counter check: a non-literal positional amount from
            # outside the collector smells like a raw cumulative total
            if node.args and not isinstance(node.args[0], ast.Constant):
                out.append(Finding(
                    "metrics", mod.path, node.lineno, node.col_offset,
                    f"counter '{attr}' incremented by a non-literal amount "
                    f"({_dotted(node.args[0]) or 'expression'}) outside "
                    f"obs/metrics.py — cumulative totals must mirror "
                    f"through a sync_* counter-delta method so the series "
                    f"stays an honest counter"))
    for attr, by_mod in sorted(writers.items()):
        if len(by_mod) > 1:
            planes = sorted(by_mod)
            for rel in planes[1:]:
                line, col = by_mod[rel][0]
                path = next(m.path for m in ctx.modules if m.relpath == rel)
                out.append(Finding(
                    "metrics", path, line, col,
                    f"counter '{attr}' is written from two planes "
                    f"({', '.join(planes)}) — one series, one writer; the "
                    f"second plane must mirror via its own sync_* seam"))
    for attr, line in sorted(counter_attrs.items()):
        if attr not in internal_writers and attr not in writers:
            out.append(Finding(
                "metrics", metrics_mod.path, line, 8,
                f"counter '{attr}' has no writer anywhere (neither a "
                f"sync_*/record_* mirror nor a plane) — dead series"))
    return out


class _LockVisitor(ast.NodeVisitor):
    """Annotates every Call with whether a lexical with-lock encloses it,
    and records blocking-op-under-lock findings."""

    def __init__(self, mod: Module, out: List[Finding]):
        self.mod = mod
        self.out = out
        self.lock_depth = 0
        self.lock_exprs: List[str] = []
        self.calls_under_lock: Set[int] = set()   # id(call node)

    def visit_With(self, node: ast.With) -> None:
        lockish = any(_is_lockish(item.context_expr) for item in node.items)
        if lockish:
            self.lock_depth += 1
            self.lock_exprs.append(
                _dotted(node.items[0].context_expr))
        self.generic_visit(node)
        if lockish:
            self.lock_depth -= 1
            self.lock_exprs.pop()

    def visit_Call(self, node: ast.Call) -> None:
        if self.lock_depth:
            self.calls_under_lock.add(id(node))
            self._check_blocking(node)
        self.generic_visit(node)

    def _check_blocking(self, node: ast.Call) -> None:
        f = node.func
        held = self.lock_exprs[-1]
        msg = None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in self.mod.time_names and f.attr == "sleep":
            msg = "time.sleep() while holding a lock"
        elif isinstance(f, ast.Attribute) and f.attr in ("get", "put"):
            recv = _dotted(f.value).lower()
            leaf = recv.rsplit(".", 1)[-1]
            if ("queue" in leaf or leaf in ("q", "_q")) \
                    and not self._nonblocking(node):
                msg = (f"blocking queue .{f.attr}() on '{_dotted(f.value)}' "
                       f"while holding a lock")
        elif isinstance(f, ast.Attribute) and f.attr == "join":
            recv = _dotted(f.value).lower()
            if "thread" in recv:
                msg = f"thread join on '{_dotted(f.value)}' under a lock"
        if msg:
            self.out.append(Finding(
                "lock-order", self.mod.path, node.lineno, node.col_offset,
                f"{msg} (holding '{held}') — a blocked producer/consumer "
                f"on the other side of that lock deadlocks; release first "
                f"or use the _nowait form, or annotate with "
                f"`# rtfd-lint: allow[lock-order] <why>`"))

    @staticmethod
    def _nonblocking(node: ast.Call) -> bool:
        for kw in node.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
            if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value == 0:
                return True
        if node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is False:
            return True
        return False


@dataclass
class _FuncInfo:
    mod: Module
    qualname: str
    node: ast.AST
    visitor: _LockVisitor


def _index_functions(ctx: "Context") -> Dict[str, List[_FuncInfo]]:
    """simple name -> defs across the package, with lock annotations."""
    index: Dict[str, List[_FuncInfo]] = {}
    for mod in ctx.modules:
        visitor = _LockVisitor(mod, ctx.lock_findings)
        visitor.visit(mod.tree)
        ctx.lock_visitors[mod.relpath] = visitor

        class _FnCollector(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: List[str] = []

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()

            def _fn(self, node) -> None:
                qual = ".".join(self.stack + [node.name])
                index.setdefault(node.name, []).append(
                    _FuncInfo(mod, qual, node, visitor))
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _fn
            visit_AsyncFunctionDef = _fn

        _FnCollector().visit(mod.tree)
    return index


def _enclosing_function(mod: Module, line: int,
                        index: Dict[str, List[_FuncInfo]]
                        ) -> Optional[_FuncInfo]:
    best: Optional[_FuncInfo] = None
    for infos in index.values():
        for info in infos:
            if info.mod is not mod:
                continue
            node = info.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                if best is None or node.lineno > best.node.lineno:
                    best = info
    return best


def _call_sites(name: str, ctx: "Context"
                ) -> List[Tuple[Module, ast.Call]]:
    sites = []
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == name) or (
                    isinstance(f, ast.Name) and f.id == name):
                sites.append((mod, node))
    return sites


def _has_lock_kwarg(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "lock" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None):
            return True
    return False


def _rule_lock_order(ctx: "Context") -> List[Finding]:
    out: List[Finding] = list(ctx.lock_findings)   # blocking-op findings
    index = ctx.func_index

    def unlocked_entries(name: str, depth: int,
                         seen: Set[str]) -> List[Tuple[Module, ast.Call, str]]:
        """Package entry call sites that reach `name` without a lock.

        Returns (module, call node, path-string) triples at the TOP of
        each unlocked chain — that is where the pragma or the fix goes."""
        if depth <= 0 or name in seen:
            return []
        seen = seen | {name}
        entries: List[Tuple[Module, ast.Call, str]] = []
        for mod, call in _call_sites(name, ctx):
            visitor = ctx.lock_visitors.get(mod.relpath)
            if visitor is not None and id(call) in visitor.calls_under_lock:
                continue                      # held lexically: fine
            if _has_lock_kwarg(call):
                continue                      # lock threaded through
            if ctx.consume_pragma(mod.path, call.lineno, "lock-order"):
                # a mid-chain single-writer waiver collapses every chain
                # that flows through this call site
                continue
            caller = _enclosing_function(mod, call.lineno, index)
            if caller is None:
                entries.append((mod, call, name))
                continue
            ups = unlocked_entries(caller.node.name, depth - 1, seen)
            if ups:
                entries.extend(
                    (m, c, f"{p} -> {name}") for m, c, p in ups)
            elif not _call_sites(caller.node.name, ctx):
                # no package caller at all (external/thread entry): the
                # chain surfaces here
                entries.append((mod, call, f"{caller.qualname} -> {name}"))
            # else: every caller chain held a lock — fine
        return entries

    reported: Set[Tuple[str, int, str]] = set()
    for mutator in sorted(MUTATORS):
        # no definition-present gate: the mutators are a fixed contract
        # (FraudScorer/checkpoint surface) and partial lint contexts — a
        # single file, the corpus tests — must still see their call sites
        for mod, call, path in unlocked_entries(mutator, 6, set()):
            key = (mod.path, call.lineno, mutator)
            if key in reported:
                continue
            reported.add(key)
            out.append(Finding(
                "lock-order", mod.path, call.lineno, call.col_offset,
                f"param/degradation mutation '{mutator}' is reachable "
                f"here without the score lock (chain: {path}) — hold the "
                f"score lock around the mutation, pass lock=, or annotate "
                f"a single-writer entry point with "
                f"`# rtfd-lint: allow[lock-order] <why>`"))
    return out


def _rule_determinism(ctx: "Context") -> List[Finding]:
    out: List[Finding] = []
    for mod in ctx.modules:
        base = os.path.basename(mod.relpath)
        if not (mod.relpath.startswith("sim/") or "drill" in base
                or mod.relpath in DETERMINISM_MODULES
                or mod.subsystem in DETERMINISM_SUBSYSTEMS):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            msg = None
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                if f.value.id in mod.random_names \
                        and f.attr in _RANDOM_GLOBAL_FNS:
                    msg = f"global-RNG random.{f.attr}()"
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Attribute) \
                    and isinstance(f.value.value, ast.Name) \
                    and f.value.value.id in mod.numpy_names \
                    and f.value.attr == "random" \
                    and f.attr not in _NP_RANDOM_OK:
                msg = f"global-RNG np.random.{f.attr}()"
            if msg:
                out.append(Finding(
                    "determinism", mod.path, node.lineno, node.col_offset,
                    f"{msg} in a deterministic module — drills and the "
                    f"simulator must replay bit-identically; draw from a "
                    f"seeded np.random.default_rng(seed) / "
                    f"random.Random(seed) instance instead"))
    return out


RULES: Dict[str, Any] = {
    "wall-clock": _rule_wall_clock,
    "d2h": _rule_d2h,
    "metrics": _rule_metrics,
    "lock-order": _rule_lock_order,
    "determinism": _rule_determinism,
    # pragma-hygiene runs structurally in run_lint (it needs the
    # suppression outcome of every other rule)
}
KNOWN_RULES = frozenset(RULES) | {"pragma-hygiene"}


@dataclass
class Context:
    modules: List[Module]
    pragmas: List[Pragma] = field(default_factory=list)
    lock_findings: List[Finding] = field(default_factory=list)
    lock_visitors: Dict[str, _LockVisitor] = field(default_factory=dict)
    func_index: Dict[str, List[_FuncInfo]] = field(default_factory=dict)
    pragma_index: Dict[Tuple[str, int], List[Pragma]] = field(
        default_factory=dict)

    def consume_pragma(self, path: str, line: int, rule: str) -> bool:
        hit = False
        for p in self.pragma_index.get((path, line), ()):
            if rule in p.rules:
                p.hits += 1
                hit = True
        return hit


def _run(ctx: Context) -> List[Finding]:
    for mod in ctx.modules:
        ctx.pragmas.extend(_parse_pragmas(mod))
    for p in ctx.pragmas:
        ctx.pragma_index.setdefault((p.path, p.target), []).append(p)
        if p.line != p.target:
            ctx.pragma_index.setdefault((p.path, p.line), []).append(p)
    ctx.func_index = _index_functions(ctx)

    raw: List[Finding] = []
    for fn in RULES.values():
        raw.extend(fn(ctx))

    kept: List[Finding] = []
    for f in raw:
        if not ctx.consume_pragma(f.path, f.line, f.rule):
            kept.append(f)

    seen_pragmas: Set[int] = set()
    for p in ctx.pragmas:
        if id(p) in seen_pragmas:
            continue
        seen_pragmas.add(id(p))
        unknown = [r for r in p.rules if r not in KNOWN_RULES]
        if not p.rules or unknown:
            kept.append(Finding(
                "pragma-hygiene", p.path, p.line, 0,
                f"pragma names unknown rule(s) "
                f"{unknown or ['<empty>']} — known: "
                f"{', '.join(sorted(KNOWN_RULES - {'pragma-hygiene'}))}"))
        elif p.hits == 0:
            kept.append(Finding(
                "pragma-hygiene", p.path, p.line, 0,
                f"stale pragma allow[{','.join(p.rules)}]: it no longer "
                f"suppresses any finding — the code it waived was fixed "
                f"or moved; delete the pragma"))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


# ----------------------------------------------------------------- frontends

def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _iter_package_files(root: str) -> Iterable[Tuple[str, str]]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__",)]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                yield full, os.path.relpath(full, root)


def lint_paths(paths: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint the package tree; ``paths`` filters the REPORT, not the scope.

    The cross-module rules (metrics one-writer, the lock-order call-graph)
    and the subsystem scoping are only correct with the whole package in
    context, so the full tree (+ repo-root bench.py) is always loaded and
    analyzed; explicit files/directories merely restrict which findings
    are returned. A path outside the package tree (other than bench.py)
    contributes nothing — in-memory corpus linting goes through
    :func:`lint_source` instead.
    """
    root = _package_root()
    modules: List[Module] = []
    for full, rel in _iter_package_files(root):
        m = _load_module(full, rel)
        if m is not None:
            modules.append(m)
    # the repo-root pre-pull-safe bench module rides along when present
    bench = os.path.join(os.path.dirname(root), "bench.py")
    if os.path.exists(bench):
        m = _load_module(bench, "bench.py")
        if m is not None:
            modules.append(m)
    findings = _run(Context(modules=modules))
    if not paths:
        return findings
    targets: Set[str] = set()
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in filenames:
                    if fn.endswith(".py"):
                        targets.add(os.path.abspath(
                            os.path.join(dirpath, fn)))
        else:
            targets.add(os.path.abspath(p))
    return [f for f in findings if os.path.abspath(f.path) in targets]


def lint_source(source: str, relpath: str,
                extra: Optional[Dict[str, str]] = None) -> List[Finding]:
    """Lint in-memory source as if it lived at ``relpath`` inside the
    package — the seeded-violation corpus tests use this so no bad code
    ever has to exist on disk. ``extra`` maps more relpaths to sources
    (for cross-module rules)."""
    modules = []
    m = _load_module(relpath, relpath, source=source)
    if m is not None:
        modules.append(m)
    for rel, src in (extra or {}).items():
        em = _load_module(rel, rel, source=src)
        if em is not None:
            modules.append(em)
    return _run(Context(modules=modules))


def format_findings(findings: Sequence[Finding], fmt: str = "text") -> str:
    if fmt == "json":
        return json.dumps({
            "findings": [f.as_dict() for f in findings],
            "count": len(findings),
            "rules": sorted(KNOWN_RULES),
            "clean": not findings,
        }, indent=2)
    if not findings:
        return "rtfd lint: clean (0 findings)"
    lines = [str(f) for f in findings]
    lines.append(f"rtfd lint: {len(findings)} finding(s)")
    return "\n".join(lines)


def run_lint(paths: Optional[Sequence[str]] = None,
             fmt: str = "text") -> Tuple[int, str]:
    """(exit_code, rendered output) — the CLI seam."""
    findings = lint_paths(paths)
    return (1 if findings else 0), format_findings(findings, fmt)

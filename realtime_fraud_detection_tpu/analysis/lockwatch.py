"""Dynamic lock-order watcher: record real acquisition graphs under drills.

The static ``lock-order`` rule (analysis/lint.py) sees the lexical
structure; this module watches what the threads actually do. While any of
the deterministic drills run (``rtfd lint --lockwatch`` drives pool-drill,
trace-drill, autotune-drill, feedback-drill, qos-drill, chaos-drill,
shard-drill, mesh-drill, elastic-drill, partition-drill and
graph-drill), every
``threading.Lock`` / ``RLock`` / ``Condition`` created from package code
is replaced by an instrumented wrapper that records, per thread:

- the acquisition DAG (edge A->B = "acquired B while holding A", keyed by
  lock *creation site*, so every instance of a class shares one node and
  the order analysis generalizes across objects);
- max hold time and max acquire-wait time per lock site;
- **violations**: a device-result wait (``jax.device_get`` /
  ``jax.block_until_ready``) entered while ANY watched lock is held — the
  serving plane's documented contract is the opposite (the score lock
  covers host-state mutation, never the device wait), and a lock held
  across a multi-ms device block is exactly how a 20 ms p99 budget dies;
- **warnings**: a condition wait while holding a *different* watched lock
  (the classic nested-wait deadlock shape — reported for triage, not
  failed, because a timeout-guarded wait can be a legitimate design).

A cycle anywhere in the merged acquisition graph, or any violation, fails
the run. The wrappers cost one dict update per acquisition — micro-
benchmark noise next to the drills' own work — and are installed only
inside :func:`watch_locks`; production code paths never see them.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = ["LockWatcher", "WatchedLock", "WatchedCondition", "watch_locks",
           "run_drill_watched", "LOCKWATCH_DRILLS"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

PACKAGE_MARKER = "realtime_fraud_detection_tpu"

# the thirteen deterministic drills the watcher is validated against
LOCKWATCH_DRILLS = ("qos-drill", "trace-drill", "autotune-drill",
                    "feedback-drill", "pool-drill", "chaos-drill",
                    "shard-drill", "mesh-drill", "elastic-drill",
                    "partition-drill", "graph-drill", "kernel-drill",
                    "obs-drill")


class LockWatcher:
    """Acquisition-graph recorder shared by every instrumented lock."""

    def __init__(self) -> None:
        self._meta = _REAL_LOCK()
        self._tls = threading.local()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.max_hold_ms: Dict[str, float] = {}
        self.max_wait_ms: Dict[str, float] = {}
        self.acquisitions = 0
        self.violations: List[Dict[str, Any]] = []
        self.warnings: List[Dict[str, Any]] = []
        self.armed = True

    # ------------------------------------------------------------- test API
    def lock(self, name: str) -> "WatchedLock":
        """A named instrumented lock (the corpus tests build inverted
        acquisition orders with these; package code gets wrapped
        automatically by watch_locks)."""
        return WatchedLock(self, _REAL_LOCK(), name)

    def condition(self, name: str) -> "WatchedCondition":
        return WatchedCondition(self, _REAL_CONDITION(), name)

    # ------------------------------------------------------------ recording
    def _held(self) -> List[List[Any]]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def held_sites(self) -> List[str]:
        return [s for s, _ in self._held()]

    def _acquired(self, site: str, waited_s: float) -> None:
        held = self._held()
        with self._meta:
            self.acquisitions += 1
            w = waited_s * 1e3
            if w > self.max_wait_ms.get(site, 0.0):
                self.max_wait_ms[site] = w
            for h, _t in held:
                if h != site:
                    self.edges[(h, site)] = self.edges.get((h, site), 0) + 1
        held.append([site, time.perf_counter()])

    def _released(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == site:
                _, t0 = held.pop(i)
                ms = (time.perf_counter() - t0) * 1e3
                with self._meta:
                    if ms > self.max_hold_ms.get(site, 0.0):
                        self.max_hold_ms[site] = ms
                return

    def note_device_wait(self, what: str) -> None:
        """Called (via the jax patches) when a thread is about to block on
        a device result; holding any watched lock here is a violation."""
        if not self.armed:
            return
        held = self.held_sites()
        if held:
            with self._meta:
                self.violations.append({
                    "kind": "device-wait-under-lock",
                    "blocking_on": what,
                    "held": list(held),
                    "thread": threading.current_thread().name,
                })

    def note_cond_wait(self, site: str) -> None:
        if not self.armed:
            return
        others = [s for s in self.held_sites() if s != site]
        if others:
            with self._meta:
                self.warnings.append({
                    "kind": "cond-wait-holding-other-lock",
                    "cond": site,
                    "held": others,
                    "thread": threading.current_thread().name,
                })

    # ------------------------------------------------------------- analysis
    def cycles(self, limit: int = 8) -> List[List[str]]:
        """Distinct cycles in the merged acquisition graph (site names)."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        found: List[Tuple[str, ...]] = []

        def dfs(node: str, path: List[str], on_path: set) -> None:
            if len(found) >= limit:
                return
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    # canonicalize by rotating the smallest element first
                    body = cyc[:-1]
                    k = body.index(min(body))
                    canon = tuple(body[k:] + body[:k])
                    if canon not in {tuple(c[:-1]) for c in found}:
                        found.append(tuple(cyc))
                elif nxt not in visited:
                    visited.add(nxt)
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        visited: set = set()
        for start in sorted(adj):
            if start not in visited:
                visited.add(start)
                dfs(start, [start], {start})
        return [list(c) for c in found]

    def disarm(self) -> None:
        self.armed = False

    def report(self) -> Dict[str, Any]:
        cycles = self.cycles()
        with self._meta:
            return {
                "locks": sorted(set(
                    [a for a, _ in self.edges] + [b for _, b in self.edges]
                    + list(self.max_hold_ms))),
                "acquisitions": self.acquisitions,
                "edges": sorted(
                    [[a, b, n] for (a, b), n in self.edges.items()]),
                "cycles": cycles,
                "violations": list(self.violations),
                "warnings": list(self.warnings),
                "max_hold_ms": {k: round(v, 3)
                                for k, v in sorted(self.max_hold_ms.items())},
                "max_wait_ms": {k: round(v, 3)
                                for k, v in sorted(self.max_wait_ms.items())},
                "ok": not cycles and not self.violations,
            }


class WatchedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper feeding a watcher."""

    def __init__(self, watcher: LockWatcher, inner, site: str):
        self._watcher = watcher
        self._inner = inner
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._watcher._acquired(self.site, time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        self._watcher._released(self.site)
        self._inner.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    # RLock internals threading.Condition relies on when one is passed in
    def _is_owned(self):  # pragma: no cover - Condition(lock=...) path
        return self._inner._is_owned() if hasattr(self._inner, "_is_owned") \
            else self._inner.locked()


class WatchedCondition:
    """Drop-in ``threading.Condition`` wrapper.

    ``wait`` releases the underlying lock, so the held-stack entry is
    popped for the duration (otherwise every waiter would look like it
    holds the lock across its own sleep) and a wait entered while holding
    a DIFFERENT watched lock is recorded as a warning."""

    def __init__(self, watcher: LockWatcher, inner, site: str):
        self._watcher = watcher
        self._inner = inner
        self.site = site

    def acquire(self, *a, **k) -> bool:
        t0 = time.perf_counter()
        ok = self._inner.acquire(*a, **k)
        if ok:
            self._watcher._acquired(self.site, time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        self._watcher._released(self.site)
        self._inner.release()

    def __enter__(self) -> "WatchedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._watcher.note_cond_wait(self.site)
        self._watcher._released(self.site)
        try:
            return self._inner.wait(timeout)
        finally:
            self._watcher._acquired(self.site, 0.0)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._watcher.note_cond_wait(self.site)
        self._watcher._released(self.site)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._watcher._acquired(self.site, 0.0)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def _creation_site(include: Sequence[str]) -> Optional[str]:
    """Site label for a lock created now, or None when the creating frame
    is outside the watched paths (stdlib, third-party, test machinery)."""
    f: Any = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if "lockwatch" not in fn and not fn.endswith("threading.py"):
            break
        f = f.f_back
    if f is None:
        return None
    fn = f.f_code.co_filename
    if not any(p in fn for p in include):
        return None
    parts = fn.replace(os.sep, "/").split("/")
    tail = "/".join(parts[-2:])
    return f"{tail}:{f.f_lineno}"


@contextmanager
def watch_locks(watcher: Optional[LockWatcher] = None,
                include: Sequence[str] = (PACKAGE_MARKER,),
                patch_jax: bool = True) -> Iterator[LockWatcher]:
    """Instrument package lock creation (and jax's device waits) for the
    duration of the block. Locks created before the block keep their real
    identity; locks created inside it from non-watched paths do too."""
    w = watcher or LockWatcher()

    def _lock_factory():
        site = _creation_site(include)
        if site is None:
            return _REAL_LOCK()
        return WatchedLock(w, _REAL_LOCK(), site)

    def _rlock_factory():
        site = _creation_site(include)
        if site is None:
            return _REAL_RLOCK()
        return WatchedLock(w, _REAL_RLOCK(), site)

    def _cond_factory(lock=None):
        site = _creation_site(include)
        inner_lock = lock._inner if isinstance(lock, WatchedLock) else lock
        if site is None:
            return _REAL_CONDITION(inner_lock)
        return WatchedCondition(w, _REAL_CONDITION(inner_lock), site)

    threading.Lock = _lock_factory          # type: ignore[assignment]
    threading.RLock = _rlock_factory        # type: ignore[assignment]
    threading.Condition = _cond_factory     # type: ignore[assignment]

    jax = None
    real_device_get = real_block = None
    if patch_jax:
        try:
            import jax as _jax
            jax = _jax
        except Exception:           # jax genuinely unavailable: skip hooks
            jax = None
    if jax is not None:
        real_device_get = jax.device_get
        real_block = jax.block_until_ready

        def _device_get(x):
            w.note_device_wait("jax.device_get")
            return real_device_get(x)

        def _block_until_ready(x):
            w.note_device_wait("jax.block_until_ready")
            return real_block(x)

        jax.device_get = _device_get
        jax.block_until_ready = _block_until_ready
    try:
        yield w
    finally:
        threading.Lock = _REAL_LOCK         # type: ignore[assignment]
        threading.RLock = _REAL_RLOCK       # type: ignore[assignment]
        threading.Condition = _REAL_CONDITION  # type: ignore[assignment]
        if jax is not None:
            jax.device_get = real_device_get
            jax.block_until_ready = real_block
        w.disarm()


# ------------------------------------------------------------ drill harness

def run_drill_watched(drill: str, fast: bool = True,
                      seed: int = 7) -> Dict[str, Any]:
    """Run one deterministic drill under the watcher; return
    ``{"drill", "drill_passed", "lockwatch": report}``.

    pool-drill, chaos-drill and mesh-drill need a multi-device host
    platform — callers (the ``rtfd lint --lockwatch`` parent) re-exec
    them into a child with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``; the rest
    run on whatever platform is live.
    """
    import contextlib
    import io

    if drill not in LOCKWATCH_DRILLS:
        raise ValueError(f"unknown drill {drill!r}; "
                         f"expected one of {LOCKWATCH_DRILLS}")
    sink = io.StringIO()
    with watch_locks() as w:
        with contextlib.redirect_stdout(sink):
            if drill == "qos-drill":
                from realtime_fraud_detection_tpu.qos import (
                    run_overload_drill,
                )

                s = run_overload_drill(seed=seed)
                passed = bool(s.get("p99_within_budget"))
            elif drill == "trace-drill":
                from realtime_fraud_detection_tpu.obs.trace_drill import (
                    TraceDrillConfig,
                    run_trace_drill,
                )

                cfg = (TraceDrillConfig.fast() if fast
                       else TraceDrillConfig())
                passed = bool(run_trace_drill(cfg)["passed"])
            elif drill == "autotune-drill":
                from realtime_fraud_detection_tpu.tuning.drill import (
                    AutotuneDrillConfig,
                    run_autotune_drill,
                )

                cfg = (AutotuneDrillConfig.fast() if fast
                       else AutotuneDrillConfig())
                passed = bool(run_autotune_drill(cfg)["passed"])
            elif drill == "feedback-drill":
                from realtime_fraud_detection_tpu.feedback.drill import (
                    FeedbackDrillConfig,
                    run_feedback_drill,
                )

                cfg = (FeedbackDrillConfig.fast() if fast
                       else FeedbackDrillConfig())
                passed = bool(run_feedback_drill(cfg)["passed"])
            elif drill == "pool-drill":
                from realtime_fraud_detection_tpu.scoring.pool_drill import (
                    PoolDrillConfig,
                    run_pool_drill,
                )

                cfg = (PoolDrillConfig.fast() if fast else PoolDrillConfig())
                passed = bool(run_pool_drill(cfg)["passed"])
            elif drill == "chaos-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.chaos.drill import (
                    ChaosDrillConfig,
                    run_chaos_drill,
                )

                # one pass at the drill's own default seed: lock/thread
                # behavior is identical on the replay run, so the
                # bit-identical re-run would only double the watcher's
                # wall time (determinism is the drill's OWN acceptance)
                cfg = dataclasses.replace(
                    ChaosDrillConfig.fast() if fast else ChaosDrillConfig(),
                    replay_check=False)
                passed = bool(run_chaos_drill(cfg)["passed"])
            elif drill == "shard-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.cluster.drill import (
                    ShardDrillConfig,
                    run_shard_drill,
                )

                # single pass for the same reason as chaos-drill; the
                # oracle run inside still executes (it IS a check)
                cfg = dataclasses.replace(
                    ShardDrillConfig.fast() if fast else ShardDrillConfig(),
                    replay_check=False)
                passed = bool(run_shard_drill(cfg)["passed"])
            elif drill == "mesh-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.scoring.mesh_drill import (
                    MeshDrillConfig,
                    run_mesh_drill,
                )

                # single pass for the same reason as chaos-drill: the
                # replay digest is the drill's OWN acceptance; under the
                # watcher it would only double the wall time
                cfg = dataclasses.replace(
                    MeshDrillConfig.fast() if fast else MeshDrillConfig(),
                    replay_check=False)
                passed = bool(run_mesh_drill(cfg)["passed"])
            elif drill == "elastic-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.cluster.elastic_drill import (
                    ElasticDrillConfig,
                    run_elastic_drill,
                )

                # single pass (the fresh-run digest is the drill's own
                # acceptance). The watcher instruments THIS process —
                # the coordinator, broker server, and handoff server
                # threads; the worker subprocesses run their own
                # interpreters and are covered by the drill's checks.
                cfg = dataclasses.replace(
                    ElasticDrillConfig.fast() if fast
                    else ElasticDrillConfig(),
                    replay_check=False)
                passed = bool(run_elastic_drill(cfg)["passed"])
            elif drill == "partition-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.chaos.partition_drill import (
                    PartitionDrillConfig,
                    run_partition_drill,
                )

                # single pass, same rationale as elastic-drill: the
                # fresh-run digest is the drill's own acceptance, and
                # the watcher covers this process's coordinator +
                # broker/handoff server threads (the link-faulted
                # clients live inside the worker subprocesses)
                cfg = dataclasses.replace(
                    PartitionDrillConfig.fast() if fast
                    else PartitionDrillConfig(),
                    replay_check=False)
                passed = bool(run_partition_drill(cfg)["passed"])
            elif drill == "graph-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.graph.drill import (
                    GraphDrillConfig,
                    run_graph_drill,
                )

                # single pass (the fresh-run digest is the drill's own
                # acceptance); the watcher instruments everything here —
                # the in-process worker fleet, the typed graph stores'
                # internal locks, AND the graph-fetch TCP server threads
                # reading live stores while the drive loop ingests
                cfg = dataclasses.replace(
                    GraphDrillConfig.fast() if fast
                    else GraphDrillConfig(),
                    replay_check=False)
                passed = bool(run_graph_drill(cfg)["passed"])
            elif drill == "kernel-drill":
                import dataclasses

                from realtime_fraud_detection_tpu.scoring.kernel_drill import (
                    KernelDrillConfig,
                    run_kernel_drill,
                )

                # single pass (replay is the drill's OWN acceptance gate;
                # under the watcher it would only double the wall time) —
                # both scorer sides dispatch through the real score lock,
                # so the kernel-on path is exercised under instrumentation
                cfg = dataclasses.replace(
                    KernelDrillConfig.fast() if fast
                    else KernelDrillConfig(),
                    replay=False)
                passed = bool(run_kernel_drill(cfg)["passed"])
            else:   # obs-drill
                import dataclasses

                from realtime_fraud_detection_tpu.obs.obs_drill import (
                    ObsDrillConfig,
                    run_obs_drill,
                )

                # single pass, same rationale as partition-drill: the
                # fresh-run digest is the drill's own acceptance; the
                # watcher covers this process's coordinator (fleet
                # metrics fold + trace stitching under their own locks)
                # and the broker/handoff server threads — the tracers
                # live inside the worker subprocesses. One retry absorbs
                # a wall-clock scheduling stall on oversubscribed CI
                # hosts (the drill's p99 attribution and overhead ratio
                # are real-time measurements over real OS processes —
                # the _dryrun_multihost retry discipline); a retried
                # pass still proves the lock story, a double failure
                # fails the gate
                cfg = dataclasses.replace(
                    ObsDrillConfig.fast() if fast else ObsDrillConfig(),
                    replay_check=False)
                passed = bool(run_obs_drill(cfg)["passed"]) \
                    or bool(run_obs_drill(cfg)["passed"])
    return {"drill": drill, "drill_passed": passed, "lockwatch": w.report()}

"""Invariant guard plane: repo-native static checks + dynamic lock watcher.

The system's correctness invariants — virtual-clock determinism, the two
device-timing rules (utils/timing.py), honest counter-delta Prometheus
mirrors, score-lock discipline around param swaps — lived only in
docstrings until this package. ``rtfd lint`` (analysis/lint.py) machine-
checks them over the AST; ``analysis/lockwatch.py`` watches real lock
acquisition order while the deterministic drills run. Both are enforced
in tier-1 (tests/test_analysis.py), so a new wall-clock read in a
virtual-clock subsystem or a d2h pull in a pre-pull-safe module fails
the suite with a pointed message instead of silently corrupting a drill
replay three PRs later.
"""

from realtime_fraud_detection_tpu.analysis.lint import (
    Finding,
    RULES,
    format_findings,
    lint_paths,
    lint_source,
    run_lint,
)
from realtime_fraud_detection_tpu.analysis.lockwatch import (
    LockWatcher,
    watch_locks,
)

__all__ = [
    "Finding",
    "RULES",
    "format_findings",
    "lint_paths",
    "lint_source",
    "run_lint",
    "LockWatcher",
    "watch_locks",
]

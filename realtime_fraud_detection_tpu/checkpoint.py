"""Checkpoint / resume: params via orbax, host state via pickle, offsets JSON.

The reference's only real recovery mechanism is Flink checkpointing — RocksDB
operator state + Kafka offsets, 10 s interval, EXACTLY_ONCE
(FraudDetectionJob.java:112-136, docker-compose.yml:270-276); the ML service
has no model-state checkpointing at all, just immutable files + hot reload
(main.py:291-305). This module covers both roles TPU-natively (SURVEY.md §5.4):

- **device state** (model params / optimizer state — any JAX pytree) goes
  through orbax's StandardCheckpointer, sharding-aware and async-safe;
- **host state** (the scorer's velocity windows, user history ring buffers,
  entity graph, profile caches — the RocksDB analog) is pickled;
- **offsets** (the transport's committed positions — the source of truth for
  effectively-once scoring, SURVEY.md §5.4) land in a JSON manifest.

Layout:  <dir>/step_<N>/{params/, host_state.pkl, manifest.json}
with keep-N retention and a ``latest_step`` probe; ``restore`` of a partial
checkpoint (params-only, say) returns None for the missing parts.
"""

from __future__ import annotations

import dataclasses
import json
import pickle
import shutil
import time
from pathlib import Path
from typing import Any, Dict, Mapping, Optional

__all__ = [
    "Checkpoint",
    "CheckpointManager",
    "snapshot_scorer_host_state",
    "restore_scorer_host_state",
]

_MANIFEST = "manifest.json"
_HOST_STATE = "host_state.pkl"
_PARAMS = "params"

# One process-wide checkpointer: orbax Checkpointer instances own async I/O
# machinery whose finalizer (on GC of a short-lived instance) tears down a
# shared executor and breaks every later save/restore in the process.
_CHECKPOINTER = None


def _orbax_checkpointer():
    global _CHECKPOINTER
    if _CHECKPOINTER is None:
        import atexit

        import orbax.checkpoint as ocp

        _CHECKPOINTER = ocp.StandardCheckpointer()
        # flush + join orbax's async I/O threads before the interpreter
        # tears down (otherwise a save racing process exit logs
        # "cannot schedule new futures after interpreter shutdown")
        atexit.register(_close_checkpointer)
    return _CHECKPOINTER


def _close_checkpointer() -> None:
    global _CHECKPOINTER
    if _CHECKPOINTER is not None:
        try:
            _CHECKPOINTER.close()
        except Exception:  # noqa: BLE001 - best-effort at exit
            pass
        _CHECKPOINTER = None


def _derive_model_shapes(params: Any) -> Optional[Dict[str, Any]]:
    """Auto-derive restore-template shapes from a ScoringModels pytree.

    Recorded on EVERY save that stores a ScoringModels (train, run-job,
    serving), so restore never has to guess shapes from init defaults."""
    import numpy as np

    required = ("trees", "iforest", "lstm", "gnn", "bert")
    if not all(hasattr(params, k) for k in required):
        return None
    try:
        lstm_hidden = int(np.shape(params.lstm["b_gates"])[0]) // 4
        # the word embedding is a bare f32 table, or the weight-only int8
        # form {"qe": i8[rows, h], "scale": f32[rows]} (models/quant.py) —
        # the hidden size lives in the table either way
        word_emb = params.bert["word_emb"]
        if isinstance(word_emb, dict):
            word_emb = word_emb["qe"]
        return {
            "trees": [int(params.trees.n_trees), int(params.trees.depth)],
            "iforest": [
                int(np.shape(params.iforest.feature)[0]),
                int(np.shape(params.iforest.path_length)[1]).bit_length() - 1,
            ],
            "bert_hidden": int(np.shape(word_emb)[1]),
            "bert_layers": len(params.bert["layers"]),
            "feature_dim": int(np.shape(params.lstm["w_gates"])[0])
            - lstm_hidden,
            "node_dim": int(np.shape(params.gnn["w_sage1"])[0]) // 2,
        }
    except (KeyError, TypeError, IndexError, AttributeError):
        return None


def _derive_quant_mode(params: Any) -> Optional[Dict[str, str]]:
    """Auto-derive the quantization-mode stamp from a ScoringModels pytree.

    Recorded on EVERY save that stores a ScoringModels (like model_shapes),
    so restore can refuse silently crossing quantization modes: a
    weight-only int8 checkpoint must never restore into an f32 scorer (or
    vice versa) without an explicit ``allow_arch_mismatch``. Only the BERT
    weight form is a PARAMETER property; the tree kernels are program
    selections, not checkpoint state."""
    if not hasattr(params, "bert"):
        return None
    from realtime_fraud_detection_tpu.models.quant import is_quantized_bert

    return {"bert_weights": "int8" if is_quantized_bert(params.bert)
            else "f32"}


def _derive_graph_mode(params: Any) -> Optional[Dict[str, str]]:
    """Auto-derive the GNN graph-mode stamp from a ScoringModels pytree.

    ``typed`` = the heterogeneous entity-graph layout (per-node-type
    projection weights, graph/ plane) vs ``bipartite`` = the original
    user↔merchant GraphSAGE. The two forms are different programs over
    different sampled tensors, so a silent cross-mode restore would
    change served scores — restore refuses it without
    ``allow_arch_mismatch``, exactly like the quant stamp."""
    if not hasattr(params, "gnn"):
        return None
    from realtime_fraud_detection_tpu.models.gnn import is_typed_gnn

    try:
        typed = is_typed_gnn(params.gnn)
    except TypeError:
        return None
    return {"gnn_nodes": "typed" if typed else "bipartite"}


@dataclasses.dataclass
class Checkpoint:
    step: int
    params: Any = None
    host_state: Any = None
    offsets: Optional[Dict[str, Any]] = None
    metadata: Optional[Dict[str, Any]] = None


class CheckpointManager:
    """Save/restore/retain checkpoints under one directory."""

    def __init__(self, directory: str | Path, keep: int = 3):
        # directory creation is deferred to save(): a restore-only caller
        # (e.g. /reload-models with a user-supplied path) must not mutate
        # the filesystem at an arbitrary location
        self.directory = Path(directory)
        self.keep = keep

    # ------------------------------------------------------------- plumbing
    @staticmethod
    def _orbax():
        return _orbax_checkpointer()

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"step_{step:010d}"

    def steps(self) -> list[int]:
        out = []
        for p in self.directory.glob("step_*"):
            if (p / _MANIFEST).exists():       # incomplete saves don't count
                out.append(int(p.name.split("_")[1]))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # ----------------------------------------------------------------- save
    def save(self, step: int, params: Any = None, host_state: Any = None,
             offsets: Optional[Mapping[str, Any]] = None,
             metadata: Optional[Mapping[str, Any]] = None) -> Path:
        """Write one checkpoint. The manifest is written LAST — a crash
        mid-save leaves a directory without a manifest, which ``steps()``
        ignores and the next ``save`` overwrites."""
        d = self._step_dir(step)
        if d.exists():
            shutil.rmtree(d)                   # overwrite a torn save
        d.mkdir(parents=True)
        if params is not None:
            # StandardCheckpointer wants the target dir absent
            ckptr = self._orbax()
            ckptr.save(str((d / _PARAMS).absolute()), params)
            # block until the async commit lands: the manifest below must
            # only exist once params are durable, and a short-lived process
            # (CLI train) must not exit with the commit still in flight
            ckptr.wait_until_finished()
        if host_state is not None:
            with open(d / _HOST_STATE, "wb") as f:
                pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        # model_shapes is a *derived* manifest field, kept out of the
        # caller's metadata so metadata round-trips verbatim (a caller that
        # recorded shapes itself under metadata wins, for old callers).
        meta = dict(metadata) if metadata is not None else {}
        shapes = meta.get("model_shapes")
        if params is not None and shapes is None:
            shapes = _derive_model_shapes(params)
        quant_mode = meta.get("quant_mode")
        if params is not None and quant_mode is None:
            quant_mode = _derive_quant_mode(params)
        graph_mode = meta.get("graph_mode")
        if params is not None and graph_mode is None:
            graph_mode = _derive_graph_mode(params)
        manifest = {
            "step": step,
            "wall_time": time.time(),
            "has_params": params is not None,
            "has_host_state": host_state is not None,
            "offsets": dict(offsets) if offsets is not None else None,
            "metadata": meta or None,
            "model_shapes": shapes,
            "quant_mode": quant_mode,
            "graph_mode": graph_mode,
        }
        with open(d / _MANIFEST, "w") as f:
            json.dump(manifest, f, indent=1)
        self._retain()
        return d

    def _retain(self) -> None:
        steps = self.steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # -------------------------------------------------------------- restore
    def manifest(self, step: Optional[int] = None) -> Dict[str, Any]:
        """Read a checkpoint's manifest without restoring params."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        with open(self._step_dir(step) / _MANIFEST) as f:
            return json.load(f)

    def scoring_models_template(self, step: Optional[int] = None,
                                bert_config=None, feature_dim: int = 64,
                                node_dim: int = 16):
        """Restore template for a ScoringModels checkpoint.

        Tree/isolation-forest shapes vary with training flags (``train
        --trees N``); ``save`` records them in the manifest's top-level
        ``model_shapes`` field (older checkpoints carried them inside
        metadata) and this rebuilds a template with matching shapes so
        orbax's typed restore succeeds regardless of the trained sizes.
        When the manifest also records bert/feature dims, a mismatch with
        the requested dims raises a clear error instead of a cryptic orbax
        shape failure.
        """
        import jax
        import jax.numpy as jnp

        from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG
        from realtime_fraud_detection_tpu.models.isolation_forest import (
            IsolationForest,
        )
        from realtime_fraud_detection_tpu.scoring import init_scoring_models

        manifest = self.manifest(step)
        meta = manifest.get("metadata") or {}
        shapes = manifest.get("model_shapes") or meta.get("model_shapes") or {}
        quant_mode = manifest.get("quant_mode") or {}
        graph_mode = manifest.get("graph_mode") or {}
        want = {
            "bert_hidden": None if bert_config is None
            else bert_config.hidden_size,
            "bert_layers": None if bert_config is None
            else bert_config.num_layers,
            "feature_dim": feature_dim,
            "node_dim": node_dim,
        }
        for key, expected in want.items():
            recorded = shapes.get(key)
            if (recorded is not None and expected is not None
                    and int(recorded) != int(expected)):
                raise ValueError(
                    f"checkpoint {key}={recorded} does not match the "
                    f"server's {key}={expected}; restore with a matching "
                    f"config")
        n_trees, tree_depth = shapes.get("trees", (100, 6))
        models = init_scoring_models(
            jax.random.PRNGKey(0),
            bert_config=bert_config if bert_config is not None else TINY_CONFIG,
            feature_dim=feature_dim, node_dim=node_dim,
            n_trees=int(n_trees), tree_depth=int(tree_depth),
            # the SAVED pytree carries the typed per-node-type projection
            # leaves — orbax's typed restore needs a structurally matching
            # template (serving permission is restore_into_scorer's
            # graph-mode arch check, not a template concern)
            gnn_typed=(graph_mode.get("gnn_nodes") == "typed"))
        if "iforest" in shapes:
            n_if, if_depth = (int(v) for v in shapes["iforest"])
            models = models.replace(iforest=IsolationForest(
                feature=jnp.zeros((n_if, 2 ** if_depth - 1), jnp.int32),
                threshold=jnp.zeros((n_if, 2 ** if_depth - 1), jnp.float32),
                path_length=jnp.zeros((n_if, 2 ** if_depth), jnp.float32),
                c_psi=jnp.asarray(0.0, jnp.float32),
            ))
        if quant_mode.get("bert_weights") == "int8":
            # the SAVED pytree carries the weight-only int8 layout — orbax's
            # typed restore needs a structurally matching template (whether
            # the restoring scorer is allowed to SERVE it is
            # restore_into_scorer's arch-stamp check, not a template concern)
            from realtime_fraud_detection_tpu.models.quant import (
                quantize_bert_params,
            )

            models = models.replace(bert=quantize_bert_params(models.bert))
        return models

    def restore_into_scorer(self, scorer, step: Optional[int] = None,
                            lock=None,
                            allow_arch_mismatch: bool = False) -> Checkpoint:
        """Restore params + host state into a FraudScorer (one recipe for
        both the CLI's ``serve --checkpoint-dir`` and the serving app's
        ``/reload-models``). The step is resolved ONCE so the template and
        the restore always read the same checkpoint even while a trainer
        writes new steps; ``lock`` (the serving score lock) makes the swap
        atomic w.r.t. in-flight scoring.

        Quantization-mode arch stamp: a checkpoint whose recorded
        ``quant_mode`` crosses the scorer's configured BERT weight form
        (int8 checkpoint into an f32 scorer, or vice versa) is REFUSED
        unless ``allow_arch_mismatch`` — the two forms score differently
        (weight rounding), so a silent cross-mode restore would quietly
        change served scores. With the override, the scorer serves the
        checkpoint's actual form: an f32 restore into a quant scorer is
        quantized by ``set_models``; an int8 restore into an f32 scorer
        serves int8 (``quant_snapshot`` reads the live-params truth).
        Old checkpoints without the stamp restore leniently."""
        import contextlib

        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        ck_mode = (self.manifest(step).get("quant_mode") or {}).get(
            "bert_weights")
        want_mode = getattr(getattr(scorer, "quant", None), "bert_mode",
                            lambda: None)()
        if (ck_mode is not None and want_mode is not None
                and ck_mode != want_mode and not allow_arch_mismatch):
            raise ValueError(
                f"quantization-mode mismatch: checkpoint step {step} "
                f"records bert_weights={ck_mode!r} but the scorer is "
                f"configured for {want_mode!r}; restore with a matching "
                f"quant config or pass allow_arch_mismatch to serve the "
                f"checkpoint's form anyway")
        ck_graph = (self.manifest(step).get("graph_mode") or {}).get(
            "gnn_nodes")
        sc_graph = getattr(getattr(scorer, "sc", None), "graph_mode", None)
        want_graph = ({"typed": "typed", "bipartite": "bipartite"}
                      .get(sc_graph) if sc_graph is not None else None)
        if (ck_graph is not None and want_graph is not None
                and ck_graph != want_graph and not allow_arch_mismatch):
            raise ValueError(
                f"graph-mode mismatch: checkpoint step {step} records "
                f"gnn_nodes={ck_graph!r} but the scorer assembles "
                f"{want_graph!r} neighbor tensors; restore with a "
                f"matching graph_mode or pass allow_arch_mismatch "
                f"(stampless legacy checkpoints restore leniently)")
        template = self.scoring_models_template(
            step=step, bert_config=scorer.bert_config,
            feature_dim=scorer.sc.feature_dim, node_dim=scorer.sc.node_dim)
        ck = self.restore(step=step, params_template=template)
        with (lock if lock is not None else contextlib.nullcontext()):
            if ck.params is not None:
                scorer.set_models(ck.params)
            if ck.host_state is not None:
                restore_scorer_host_state(scorer, ck.host_state)
            # re-attach the trainer's gain importances (set_models cleared
            # them — they describe exactly the restored trees). Host-state
            # restore above already covers checkpoints that snapshot the
            # scorer; this covers params-only train checkpoints.
            imp = (ck.metadata or {}).get("feature_importances")
            if imp is not None and scorer._top_importances is None:
                try:
                    scorer.set_feature_importances(imp)
                except (ValueError, TypeError) as e:
                    import logging

                    # lenient (old/foreign manifest) but never silent: the
                    # operator must be able to see why explanations lack
                    # top_feature_importances
                    logging.getLogger(__name__).warning(
                        "checkpoint step %s: feature_importances in "
                        "manifest not attachable (%s); explanations will "
                        "omit top_feature_importances", step, e)
        return ck

    def restore(self, step: Optional[int] = None,
                params_template: Any = None) -> Checkpoint:
        """Load a checkpoint (latest if ``step`` is None).

        ``params_template`` — a pytree with the target structure/shapes
        (e.g. a freshly-initialized ScoringModels); required to restore
        params, ignored otherwise.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        d = self._step_dir(step)
        with open(d / _MANIFEST) as f:
            manifest = json.load(f)

        params = None
        if manifest["has_params"]:
            if params_template is None:
                raise ValueError(
                    "checkpoint has params; pass params_template to restore")
            params = self._orbax().restore(
                str((d / _PARAMS).absolute()), target=params_template)
        host_state = None
        if manifest["has_host_state"]:
            with open(d / _HOST_STATE, "rb") as f:
                host_state = pickle.load(f)
        return Checkpoint(
            step=manifest["step"],
            params=params,
            host_state=host_state,
            offsets=manifest.get("offsets"),
            metadata=manifest.get("metadata"),
        )


# --------------------------------------------------------------------------
# FraudScorer integration: host-state snapshot = the RocksDB analog
# --------------------------------------------------------------------------

def snapshot_scorer_host_state(scorer) -> Dict[str, Any]:
    """Pickle-able snapshot of a FraudScorer's streaming state (velocity
    windows, per-user history, entity graph/indexes, profiles, txn cache —
    everything the reference kept in Redis/RocksDB, SURVEY.md §2.5)."""
    return {
        "profiles": scorer.profiles,
        "velocity": scorer.velocity,
        "history": scorer.history,
        "graph": scorer.graph,
        "txn_cache": scorer.txn_cache,
        "users_index": scorer._users,
        "merchants_index": scorer._merchants,
        # typed entity graph (graph/ plane): only when scorer-LOCAL — a
        # partition-bundle-backed graph (stores= injection) snapshots
        # with its PartitionState, never here (the handoff path owns it)
        "typed_graph": (scorer.typed_graph
                        if getattr(scorer, "typed_graph", None) is not None
                        and not hasattr(scorer.typed_graph, "_store")
                        else None),
        "stats": dict(scorer.stats),
        # the top-10 explanation importances are scorer host state too —
        # every save/restore path round-trips them, not just the train CLI's
        # metadata (set_models during restore clears them deliberately)
        "top_importances": scorer._top_importances,
    }


def restore_scorer_host_state(scorer, state: Mapping[str, Any]) -> None:
    scorer.profiles = state["profiles"]
    scorer.velocity = state["velocity"]
    scorer.history = state["history"]
    scorer.graph = state["graph"]
    scorer.txn_cache = state["txn_cache"]
    scorer._users = state["users_index"]
    scorer._merchants = state["merchants_index"]
    typed = state.get("typed_graph")
    if (typed is not None
            and getattr(scorer, "typed_graph", None) is not None
            and not hasattr(scorer.typed_graph, "_store")):
        # restore only into a scorer-local typed graph (a partition-
        # bundle facade restores through handoff, not here); the sampler
        # keeps reading the scorer's store by reference, so swap the
        # reference it holds and drop every cached neighborhood
        scorer.typed_graph = typed
        scorer._sampler.graph = typed
        scorer._sampler._cache.clear()
        scorer._sampler._deps.clear()
    scorer.stats.update(state["stats"])
    if state.get("top_importances") is not None:
        scorer._top_importances = dict(state["top_importances"])

"""Deterministic kernel drill: the ``rtfd kernel-drill`` parity oracle that
makes the Pallas kernel plane (ops/ + KernelSettings) shippable.

Hand-fused kernels are free throughput ONLY while numerics are gated, not
assumed — the quant-drill contract, applied to the kernel plane. Run the
way the other eleven drills run (virtual clock, seeded, compact <2 KB JSON
verdict as the final stdout line):

1. **Score-delta oracle.** One seeded transaction stream through TWO real
   scorers — both serving the committed quantized plane
   (``QuantSettings.full()``), one on the stock XLA lowering, one with
   every kernel on (``KernelSettings.full()``: fused dequant-matmul +
   fused score-and-blend epilogue + flash attention, through the Pallas
   interpreter on CPU). Max absolute fraud-score divergence must sit
   BELOW the calibration-noise floor: the score movement the committed
   bf16 compute policy already accepts, measured in-drill on this stream.
2. **Zero decision flips.** Every transaction takes the SAME decision
   under both programs at the pinned operating point.
3. **Masked-rung equality.** At every QoS ladder rung (qos/ladder.py) the
   kernel-on side must serve the same decisions/risk levels, probs within
   the noise bound — and the rules_only rung bit-exactly (its ladder is
   pure f32 comparisons, on-chip in the fused epilogue vs host math).
   The fast config pins the two extremes (full blend + rules_only); the
   full drill walks all four rungs.
4. **Per-kernel oracle.** Each kernel, interpret-mode vs its XLA
   reference, on the drill's REAL served params: fused dequant-matmul
   (f32 compute near-exact, bf16 compute within rounding scale), per-row
   embedding dequant exact, fused epilogue exact decisions across all
   three strategies, flash attention within f32 softmax slack.
5. **Replay.** A second full run must be bit-identical (sha256 over every
   gate-read number).

``--mega`` mode (ISSUE 19) swaps the kernel side onto the persistent
megakernel (``KernelSettings.mega()`` — ONE Pallas program scoring the
whole packed microbatch): phases 1-3 and 5 run unchanged against that
program (divergence under the same measured bf16 noise bound, zero
decision/risk flips at every rung with rules_only bit-exact, replay
digest), and the oracle section gains the megakernel pins — the fused
program against its verbatim-composition reference, GEMM-form tree leaf
indices exactly equal to the pointer-chase descent on the SERVED params,
per-site dispatch counters frozen at zero (the one program subsumes
them), and ``launches_per_batch`` collapsed to 1.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["KernelDrillConfig", "run_kernel_drill",
           "compact_kernel_summary"]


@dataclasses.dataclass
class KernelDrillConfig:
    seed: int = 13
    num_users: int = 600
    num_merchants: int = 120
    batch: int = 96
    n_batches: int = 10         # divergence / decision-flip stream
    tps: float = 200.0          # virtual arrival rate (clock advance)
    # gates
    noise_scale: float = 1.0    # kernel divergence <= scale * bf16 noise floor
    noise_floor_abs: float = 1e-4   # resolution floor for the noise bound
    matmul_rel_tol: float = 0.05    # bf16 dequant-matmul: rounding-scale,
    #                                 relative to the reference magnitude
    matmul_f32_tol: float = 1e-5    # f32 compute: summation-order slack only
    rows_tol: float = 0.0           # per-row dequant: one widen+mul, exact
    epilogue_prob_tol: float = 1e-6
    attention_tol: float = 5e-5     # online-vs-full softmax f32 slack
    replay: bool = True
    # megakernel mode: the kernel side serves ops/megakernel.py's ONE
    # persistent program (KernelSettings.mega()) instead of the per-site
    # kernel chain, and the oracle gains the megakernel-specific pins
    mega: bool = False
    mega_ref_tol: float = 1e-6      # fused program vs verbatim reference:
    #                                 same ops, block-local summation only
    # QoS rung subset for phase 2 (None = every LADDER_LEVELS rung). Each
    # non-zero rung is a fresh static config — a full recompile of BOTH
    # sides, and the kernel side pays interpret-mode Pallas tracing per
    # compile on CPU — so the fast config pins the two extremes (full
    # blend, rules_only) and leaves the interior rungs to the full drill.
    rung_levels: Optional[Tuple[int, ...]] = None

    @classmethod
    def fast(cls) -> "KernelDrillConfig":
        """Tier-1 smoke sizes: every phase runs, compiles stay small."""
        return cls(num_users=300, num_merchants=60, batch=32, n_batches=2,
                   rung_levels=(0, 3))


def _make_side(cfg: KernelDrillConfig, kernels_on: bool):
    """One drill side: seeded generator + scorer. Both sides serve the
    committed quantized plane (int8 BERT + GEMM trees) so the ONLY
    difference is the kernel plane — the thing under test."""
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.utils.config import (
        Config,
        KernelSettings,
        QuantSettings,
    )

    kernels = KernelSettings()
    if kernels_on:
        kernels = (KernelSettings.mega() if cfg.mega
                   else KernelSettings.full())
    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed)
    scorer = FraudScorer(Config(quant=QuantSettings.full(), kernels=kernels),
                         scorer_config=ScorerConfig(), seed=cfg.seed)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, scorer


def _score_stream(cfg: KernelDrillConfig, gen, scorer, ts: float,
                  n_batches: int, keep_tokens: int = 0,
                  ) -> Tuple[Dict[str, Any], float]:
    """Drive ``n_batches`` through the scorer on the virtual clock."""
    probs: List[float] = []
    decisions: List[str] = []
    risks: List[str] = []
    tokens: List[Tuple[np.ndarray, np.ndarray]] = []
    for i in range(n_batches):
        recs = gen.generate_batch(cfg.batch)
        batch = scorer.assemble(recs, now=ts)
        if i < keep_tokens:
            tokens.append((np.asarray(batch.token_ids),
                           np.asarray(batch.token_mask)))
        results = scorer.finalize(
            scorer.dispatch_assembled(batch, recs), now=ts)
        probs.extend(r["fraud_probability"] for r in results)
        decisions.extend(r["decision"] for r in results)
        risks.extend(r["risk_level"] for r in results)
        ts += cfg.batch / cfg.tps
    return {
        "probs": np.asarray(probs, np.float64),
        "decisions": decisions,
        "risks": risks,
        "tokens": tokens,
    }, ts


def _noise_floor(cfg: KernelDrillConfig, scorer,
                 tokens) -> Dict[str, float]:
    """The calibration-noise bound: how far the committed bf16 compute
    policy already moves the ensemble score vs full f32 compute, measured
    on this drill's own token stream with the SERVED weights, scaled by
    the text branch's blend weight (quant-drill recipe)."""
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.models.bert import bert_predict

    bf16 = jax.jit(lambda p, i, m: bert_predict(
        p, i, m, scorer.bert_config))
    f32 = jax.jit(lambda p, i, m: bert_predict(
        p, i, m, scorer.bert_config, compute_dtype=jnp.float32))
    branch_delta = 0.0
    for ids, mask in tokens:
        a = bf16(scorer.models.bert, ids, mask)
        b = f32(scorer.models.bert, ids, mask)
        branch_delta = max(branch_delta,
                           float(jnp.max(jnp.abs(a - b))))
    weights = np.asarray(scorer.ensemble_params.weights, np.float64)
    valid = np.asarray(scorer.effective_model_valid(), bool)
    w = weights * valid
    w_bert = float(w[2] / max(w.sum(), 1e-9))      # MODEL_NAMES order
    bound = max(branch_delta * w_bert, cfg.noise_floor_abs)
    return {"bert_branch_bf16_delta": branch_delta,
            "bert_blend_weight": round(w_bert, 4),
            "bound": bound}


def _rung_phase(cfg: KernelDrillConfig, gen_a, scorer_a, gen_b, scorer_b,
                ts: float, bound: float) -> Tuple[Dict[str, Any], float]:
    """Masked-blend equality at every QoS ladder rung: one batch per rung
    on both sides, decisions/risk exactly equal, probs within the noise
    bound — and the rules_only rung bit-exact (pure f32 ladder)."""
    from realtime_fraud_detection_tpu.qos.ladder import LADDER_LEVELS
    from realtime_fraud_detection_tpu.scoring import MODEL_NAMES

    rungs: Dict[str, Any] = {}
    for level, rung in enumerate(LADDER_LEVELS):
        if cfg.rung_levels is not None and level not in cfg.rung_levels:
            continue
        mask = np.asarray([n not in rung.dropped_branches
                           for n in MODEL_NAMES], bool)
        for scorer in (scorer_a, scorer_b):
            # rtfd-lint: allow[lock-order] drill is single-threaded (no batch in flight during the rung step)
            scorer.set_degradation(None if level == 0 else mask,
                                   rules_only=rung.rules_only, level=level)
        side_a, _ = _score_stream(cfg, gen_a, scorer_a, ts, 1)
        side_b, ts2 = _score_stream(cfg, gen_b, scorer_b, ts, 1)
        ts = ts2
        div = float(np.abs(side_a["probs"] - side_b["probs"]).max())
        flips = sum(x != y for x, y in zip(side_a["decisions"],
                                           side_b["decisions"]))
        risk_flips = sum(x != y for x, y in zip(side_a["risks"],
                                                side_b["risks"]))
        ok = flips == 0 and risk_flips == 0 and (
            div == 0.0 if rung.rules_only else div <= bound)
        rungs[rung.name] = {"max_divergence": div,
                            "decision_flips": int(flips),
                            "risk_flips": int(risk_flips),
                            "exact": div == 0.0, "ok": bool(ok)}
    for scorer in (scorer_a, scorer_b):
        # rtfd-lint: allow[lock-order] drill is single-threaded (no batch in flight during the reset)
        scorer.set_degradation(None, rules_only=False, level=0)
    return rungs, ts


def _kernel_oracle(cfg: KernelDrillConfig, scorer) -> Dict[str, Any]:
    """Per-kernel interpret-vs-XLA-reference parity on the REAL served
    params (plus randomized operands), the numerics section of the gate."""
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
    from realtime_fraud_detection_tpu.ops import (
        attention_reference,
        dequant_matmul,
        dequant_matmul_reference,
        dequant_rows,
        dequant_rows_reference,
        epilogue_reference,
        flash_attention,
        fused_epilogue,
    )

    rng = np.random.default_rng(cfg.seed + 23)
    out: Dict[str, Any] = {}
    layer = scorer.models.bert["layers"][0]
    h = int(scorer.bert_config.hidden_size)

    # --- fused dequant-matmul on the served int8 q/ffn1 kernels
    x = jnp.asarray(rng.standard_normal((cfg.batch, h)), jnp.float32)
    mm: Dict[str, float] = {}
    for name in ("q", "ffn1"):
        p = layer[name]
        for cd, key in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
            ref = dequant_matmul_reference(x, p["qw"], p["scale"], p["b"],
                                           cd).astype(jnp.float32)
            got = dequant_matmul(x, p["qw"], p["scale"], p["b"],
                                 compute_dtype=cd, interpret=True)
            delta = float(jnp.abs(got - ref).max())
            scale = max(1.0, float(jnp.abs(ref).max()))
            k = f"{key}_rel_delta"
            mm[k] = max(mm.get(k, 0.0), delta / scale)
    mm["ok"] = (mm["bf16_rel_delta"] <= cfg.matmul_rel_tol
                and mm["f32_rel_delta"] <= cfg.matmul_f32_tol)
    out["dequant_matmul"] = mm

    # --- per-row embedding dequant on served word_emb rows
    emb = scorer.models.bert["word_emb"]
    idx = rng.integers(0, emb["qe"].shape[0], (64,))
    q = jnp.asarray(np.asarray(emb["qe"])[idx])
    s = jnp.asarray(np.asarray(emb["scale"])[idx])
    rows_delta = float(jnp.abs(
        dequant_rows(q, s, interpret=True)
        - dequant_rows_reference(q, s)).max())
    out["dequant_rows"] = {"max_delta": rows_delta,
                           "ok": rows_delta <= cfg.rows_tol}

    # --- fused epilogue across all three strategies
    base = scorer.ensemble_params
    preds = jnp.asarray(rng.uniform(0, 1, (cfg.batch, 5)), jnp.float32)
    valid = jnp.asarray(rng.uniform(0, 1, (cfg.batch, 5)) > 0.25)
    rule = jnp.asarray(rng.uniform(0, 1, (cfg.batch,)), jnp.float32)
    ep_delta, ep_exact = 0.0, True
    for strat in range(3):
        params: EnsembleParams = base.replace(strategy=strat)
        ref = epilogue_reference(preds, valid, rule, params)
        got = fused_epilogue(preds, valid, rule, params, interpret=True)
        ep_delta = max(ep_delta, float(jnp.abs(
            got["fraud_probability"] - ref["fraud_probability"]).max()))
        ep_exact = ep_exact and all(
            bool(jnp.all(got[k] == ref[k]))
            for k in ("decision", "risk_level", "rule_decision",
                      "rule_risk"))
    out["epilogue"] = {"max_prob_delta": ep_delta,
                       "ladders_exact": bool(ep_exact),
                       "ok": bool(ep_exact
                                  and ep_delta <= cfg.epilogue_prob_tol)}

    # --- flash attention vs reference (f32 operands, drill text shape)
    b, heads, seq = 4, int(scorer.bert_config.num_heads), int(
        scorer.sc.text_len)
    d = int(scorer.bert_config.head_dim)
    qkv = [jnp.asarray(rng.standard_normal((b, heads, seq, d)), jnp.float32)
           for _ in range(3)]
    mask = jnp.asarray(rng.uniform(0, 1, (b, seq)) > 0.1)
    att_delta = float(jnp.abs(
        flash_attention(*qkv, mask, interpret=True)
        - attention_reference(*qkv, mask)).max())
    out["attention"] = {"max_delta": att_delta,
                        "ok": att_delta <= cfg.attention_tol}
    return out


def _mega_oracle(cfg: KernelDrillConfig, gen, scorer,
                 ts: float) -> Dict[str, Any]:
    """Megakernel section (``--mega``): the fused persistent program vs
    its verbatim-composition reference on a REAL assembled batch of the
    served params (decision/risk ladders exactly equal, probs within the
    block-summation tolerance), and the GEMM-form tree contraction's leaf
    indices exactly equal to the pointer-chase descent — the structural
    pin that makes the in-kernel tree branches trustworthy."""
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.models.trees import (
        descend_complete_trees,
        gemm_leaf_index,
    )
    from realtime_fraud_detection_tpu.ops import (
        fused_megakernel,
        megakernel_reference,
    )
    from realtime_fraud_detection_tpu.scoring.pipeline import OUT_COLUMNS

    out: Dict[str, Any] = {}
    recs = gen.generate_batch(cfg.batch)
    batch = scorer.assemble(recs, now=ts)
    mv = tuple(bool(v) for v in scorer.effective_model_valid())
    ref = np.asarray(megakernel_reference(
        scorer.models, batch, scorer.ensemble_params, mega_valid=mv,
        bert_config=scorer.bert_config), np.float64)
    got = np.asarray(fused_megakernel(
        scorer.models, batch, scorer.ensemble_params, mega_valid=mv,
        bert_config=scorer.bert_config, interpret=True), np.float64)
    prob_delta = float(np.abs(got[:, 0] - ref[:, 0]).max())
    c_dec = OUT_COLUMNS.index("decision")
    c_risk = OUT_COLUMNS.index("risk_level")
    ladders_exact = bool(
        np.array_equal(got[:, c_dec], ref[:, c_dec])
        and np.array_equal(got[:, c_risk], ref[:, c_risk]))
    out["reference"] = {
        "max_prob_delta": prob_delta,
        "ladders_exact": ladders_exact,
        "ok": bool(ladders_exact and prob_delta <= cfg.mega_ref_tol),
    }

    rng = np.random.default_rng(cfg.seed + 31)
    x = jnp.asarray(rng.standard_normal(
        (cfg.batch, int(scorer.sc.feature_dim))), jnp.float32)
    leaves: Dict[str, bool] = {}
    for name, ens in (("trees", scorer.models.trees),
                      ("iforest", scorer.models.iforest)):
        gemm = np.asarray(gemm_leaf_index(ens.feature, ens.threshold, x))
        ptr = np.asarray(descend_complete_trees(ens.feature, ens.threshold,
                                                x))
        leaves[name] = bool(np.array_equal(gemm, ptr))
    out["gemm_tree_leaves"] = {**{f"{k}_exact": v
                                  for k, v in leaves.items()},
                               "ok": all(leaves.values())}
    return out


def _run_once(cfg: KernelDrillConfig) -> Dict[str, Any]:
    summary: Dict[str, Any] = {
        "drill": "kernels",
        "seed": cfg.seed,
        "batch": cfg.batch,
        "n_batches": cfg.n_batches,
        "mega": cfg.mega,
        "checks": {},
    }
    checks = summary["checks"]

    gen_a, scorer_a = _make_side(cfg, kernels_on=False)
    gen_b, scorer_b = _make_side(cfg, kernels_on=True)
    ts = 0.0

    # ---------------------------------- phase 1: divergence + decision flips
    keep = min(4, cfg.n_batches)
    side_a, _ = _score_stream(cfg, gen_a, scorer_a, ts, cfg.n_batches,
                              keep_tokens=keep)
    side_b, ts = _score_stream(cfg, gen_b, scorer_b, ts, cfg.n_batches)
    div = np.abs(side_a["probs"] - side_b["probs"])
    flips = sum(a != b for a, b in zip(side_a["decisions"],
                                       side_b["decisions"]))
    noise = _noise_floor(cfg, scorer_a, side_a["tokens"])
    bound = cfg.noise_scale * noise["bound"]
    summary["divergence"] = {
        "max": float(div.max()),
        "mean": float(div.mean()),
        "p99": float(np.percentile(div, 99)),
        "n_txn": int(div.size),
        "noise_floor": noise,
        "noise_scale": cfg.noise_scale,
        "decision_flips": int(flips),
    }
    checks["divergence_below_noise"] = float(div.max()) <= bound
    checks["zero_decision_flips"] = flips == 0

    # --------------------------------- phase 2: masked-rung (QoS) equality
    rungs, ts = _rung_phase(cfg, gen_a, scorer_a, gen_b, scorer_b, ts,
                            bound)
    summary["rungs"] = rungs
    checks["masked_rungs_equal"] = all(r["ok"] for r in rungs.values())
    checks["rules_only_exact"] = bool(rungs["rules_only"]["exact"])

    # ------------------------------------- phase 3: per-kernel oracle
    oracle = _kernel_oracle(cfg, scorer_b)
    summary["kernel_oracle"] = oracle
    checks["dequant_matmul_parity"] = bool(oracle["dequant_matmul"]["ok"])
    checks["dequant_rows_parity"] = bool(oracle["dequant_rows"]["ok"])
    checks["epilogue_parity"] = bool(oracle["epilogue"]["ok"])
    checks["attention_parity"] = bool(oracle["attention"]["ok"])

    # --------------------------- phase 3b (--mega): megakernel oracle
    if cfg.mega:
        mega = _mega_oracle(cfg, gen_b, scorer_b, ts)
        summary["mega_oracle"] = mega
        checks["mega_reference_parity"] = bool(mega["reference"]["ok"])
        checks["gemm_tree_leaves_exact"] = bool(
            mega["gemm_tree_leaves"]["ok"])

    # served-mode truth + honest dispatch accounting: every launch on the
    # kernel side must have engaged every site with zero guard fallbacks
    # (the drill's shapes are the production shapes). In --mega mode the
    # evidence inverts: the megakernel site carries every dispatch, the
    # per-site counters must sit frozen at zero (the one program subsumes
    # them — a non-zero per-site count would mean a hidden chain launch),
    # and the launch count per microbatch collapses to 1.
    snap = scorer_b.kernel_snapshot()
    summary["kernel_snapshot"] = snap
    summary["modes"] = {"off": scorer_a.kernel_snapshot()["modes"],
                        "on": snap["modes"]}
    if cfg.mega:
        checks["mega_dispatched"] = snap["dispatch"].get(
            "megakernel", 0) > 0
        checks["per_site_subsumed"] = all(
            v == 0 for s, v in snap["dispatch"].items()
            if s != "megakernel")
        checks["launches_collapsed_to_one"] = (
            snap.get("launches_per_batch") == 1)
    else:
        checks["all_sites_dispatched"] = all(
            v > 0 for s, v in snap["dispatch"].items()
            if s != "megakernel")
    checks["zero_fallbacks"] = all(
        v == 0 for v in snap["fallback"].values())

    summary["passed"] = all(bool(v) for v in checks.values())
    return summary


def _digest(summary: Dict[str, Any]) -> str:
    """Replay fingerprint over every number the gates read."""
    payload = json.dumps(
        {k: summary.get(k) for k in ("divergence", "rungs", "kernel_oracle",
                                     "mega_oracle", "kernel_snapshot",
                                     "checks")},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def run_kernel_drill(
        cfg: Optional[KernelDrillConfig] = None) -> Dict[str, Any]:
    cfg = cfg or KernelDrillConfig()
    summary = _run_once(cfg)
    summary["digest"] = _digest(summary)
    if cfg.replay:
        second = _run_once(cfg)
        second_digest = _digest(second)
        summary["replay"] = {"digest": second_digest,
                             "bit_identical": second_digest
                             == summary["digest"]}
        summary["checks"]["replay_bit_identical"] = (
            second_digest == summary["digest"])
        summary["passed"] = all(bool(v)
                                for v in summary["checks"].values())
    return summary


def compact_kernel_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """<2 KB single-line verdict (the bench.py final-stdout convention)."""
    div = summary.get("divergence") or {}
    oracle = summary.get("kernel_oracle") or {}
    snap = summary.get("kernel_snapshot") or {}
    out = {
        "drill": "kernels",
        "passed": summary.get("passed", False),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "max_divergence": div.get("max"),
        "noise_bound": (div.get("noise_floor") or {}).get("bound"),
        "decision_flips": div.get("decision_flips"),
        "matmul_bf16_rel": (oracle.get("dequant_matmul")
                            or {}).get("bf16_rel_delta"),
        "attention_delta": (oracle.get("attention") or {}).get("max_delta"),
        "fallbacks": snap.get("fallback"),
        "digest": (summary.get("digest") or "")[:16],
    }
    if summary.get("mega"):
        mega = summary.get("mega_oracle") or {}
        out["mega"] = {
            "ref_delta": (mega.get("reference") or {}).get("max_prob_delta"),
            "leaves_exact": (mega.get("gemm_tree_leaves") or {}).get("ok"),
            "launches_per_batch": snap.get("launches_per_batch"),
        }
    return out

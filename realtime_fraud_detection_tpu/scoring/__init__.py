"""Fused ensemble scoring: device pipeline + host orchestrator."""

from realtime_fraud_detection_tpu.scoring.pipeline import (  # noqa: F401
    MODEL_NAMES,
    NUM_MODELS,
    ScoreBatch,
    ScorerConfig,
    ScoringModels,
    init_scoring_models,
    make_example_batch,
    score_fused,
    score_fused_packed,
)
from realtime_fraud_detection_tpu.scoring.host_pipeline import (  # noqa: F401
    AssembledHandle,
    AssemblerStage,
)
from realtime_fraud_detection_tpu.scoring.device_pool import (  # noqa: F401
    DevicePool,
)
from realtime_fraud_detection_tpu.scoring.mesh_executor import (  # noqa: F401
    MeshExecutor,
)
from realtime_fraud_detection_tpu.scoring.scorer import FraudScorer  # noqa: F401

"""Deterministic mesh-sharding drill: the ``rtfd mesh-drill`` acceptance gate.

Runs the REAL mesh-sharded scoring path (FraudScorer + MeshExecutor over
the host platform's virtual devices, scoring/mesh_executor.py) on
deterministic streams and pins the executor's whole contract in one
verdict:

1. **bit-equality per placement** — every branch-placement combo (pure
   data sharding, BERT-only model sharding, all three neural branches
   sharded, pool x mesh with two mesh replicas, and the int8-quantized
   forms of the sharded combos) scores bit-identical to a true
   single-device reference driven with the same in-flight window;
2. **ladder rungs** — a stream that steps the QoS degradation ladder
   mid-flight (every rung, rules-only included) stays bit-identical, so
   the per-dispatch mask snapshot fans out over the mesh exactly like it
   does over the pool;
3. **hot swap** — a mid-stream ``set_models`` re-shards replica-by-replica
   under the same placement: every batch matches EITHER the old-params or
   the new-params reference wholesale, and the swapped params are still
   sharded (per-chip bytes keep the ratio);
4. **memory** — per-chip resident BERT-branch bytes on the 2-way model
   axis are <= ``max_bert_per_chip_frac`` (60%) of the replicated
   equivalent, read from the COMMITTED array shardings, f32 and int8 both;
5. **donation** — the donated entry carries every staged blob's donation
   annotation into the compiled program (the plain entry carries none)
   and a donated run scores identically, so accelerator deployments
   recycle H2D staging instead of holding depth x blobs per replica
   (CPU PJRT drops non-aliasable donations at RUN time, so the lowering
   is the truthful cross-backend evidence);
6. **replay** — a second full pass replays bit-identically (sha256 digest
   over every scored row of every phase).

Wall-clock scaling is deliberately NOT gated here: 8 virtual CPU devices
timeslice one core budget (the pool-drill precedent), and model-sharding
is an HBM bet that can LOSE on CPU — the honest throughput numbers live
in bench.py's ``mesh_scaling`` stage. Convention matches the other seven
drills: full summary JSON, then a compact (<2 KB) verdict as the final
stdout line (cli.cmd_mesh_drill).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["MeshDrillConfig", "run_mesh_drill", "compact_mesh_summary"]


@dataclasses.dataclass
class MeshDrillConfig:
    n_devices: int = 8
    model_axis: int = 2
    inflight_depth: int = 2
    batch: int = 32
    n_batches: int = 12          # per placement combo
    swap_batches: int = 12       # hot-swap phase (swap at the midpoint)
    rung_batches: int = 2        # batches scored AT each ladder rung
    seed: int = 7
    # the memory acceptance bar: per-chip resident BERT bytes vs the
    # replicated equivalent at model_axis=2 (sharding halves the dense
    # kernels and embeddings; layer norms + head stay whole, hence 0.6
    # rather than 0.5)
    max_bert_per_chip_frac: float = 0.60
    replay_check: bool = True

    @classmethod
    def fast(cls) -> "MeshDrillConfig":
        """Tier-1 smoke sizes: every phase runs, compiles stay small."""
        return cls(batch=16, n_batches=6, swap_batches=8)


ALL_NEURAL = ("bert_text", "graph_neural", "lstm_sequential")


def _make_scorer(cfg: MeshDrillConfig, model_seed: int = 0,
                 quant: bool = False):
    """Fresh generator + scorer pair. The scorer's OWN mesh is pinned to
    one device so the reference runs are genuinely single-device; an
    attached MeshExecutor overrides the batch seam with its data axis."""
    import jax

    from realtime_fraud_detection_tpu.core.mesh import build_mesh
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    config = None
    if quant:
        from realtime_fraud_detection_tpu.utils.config import (
            Config,
            QuantSettings,
        )

        config = Config(quant=QuantSettings.full())
    gen = TransactionGenerator(num_users=500, num_merchants=100,
                               seed=cfg.seed)
    scorer = FraudScorer(config=config, scorer_config=ScorerConfig(),
                         mesh=build_mesh(devices=jax.devices()[:1]),
                         seed=model_seed)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, scorer


def _run_stream(scorer, batches: List[list], window: int,
                now: float = 1000.0,
                swap_at: Optional[int] = None, swap_models=None,
                rung_schedule: Optional[Dict[int, int]] = None,
                ) -> List[List[Dict[str, Any]]]:
    """Dispatch/finalize with at most ``window`` in flight — the SAME
    routine drives the meshed scorer and the single-device reference, so
    both see identical host-state interleaving (the pool-drill fairness
    argument). ``rung_schedule`` maps batch index -> ladder level to
    apply right before that dispatch (mask fan-out mid-stream)."""
    from collections import deque

    from realtime_fraud_detection_tpu.qos.ladder import LADDER_LEVELS
    from realtime_fraud_detection_tpu.scoring import MODEL_NAMES

    results: List[List[Dict[str, Any]]] = []
    inflight: deque = deque()
    for i, recs in enumerate(batches):
        if swap_at is not None and i == swap_at:
            # rtfd-lint: allow[lock-order] the drill IS the only dispatcher; swap purity is what it pins
            scorer.set_models(swap_models)
        if rung_schedule is not None and i in rung_schedule:
            level = rung_schedule[i]
            rung = LADDER_LEVELS[level]
            # rtfd-lint: allow[d2h] host bool list -> validity mask, never a device array
            mask = np.asarray(
                [n not in rung.dropped_branches for n in MODEL_NAMES])
            # rtfd-lint: allow[lock-order] the drill IS the only dispatcher; rung fan-out is what it pins
            scorer.set_degradation(mask, rules_only=rung.rules_only,
                                   level=level)
        inflight.append(scorer.dispatch(recs, now=now))
        while len(inflight) >= window:
            results.append(scorer.finalize(inflight.popleft(), now=now))
    while inflight:
        results.append(scorer.finalize(inflight.popleft(), now=now))
    return results


def _rows(results: List[List[Dict[str, Any]]]) -> List[tuple]:
    return [(r["transaction_id"], r["fraud_probability"], r["confidence"],
             r["decision"]) for batch in results for r in batch]


def _bert_frac(executor) -> float:
    pb = executor.param_bytes()["bert_text"]
    return pb["per_chip"] / max(pb["replicated"], 1)


def _one_pass(cfg: MeshDrillConfig) -> Tuple[Dict[str, Any], str]:
    """One full drill pass; returns (summary, digest-over-every-row)."""
    import jax

    from realtime_fraud_detection_tpu.qos.ladder import LADDER_LEVELS
    from realtime_fraud_detection_tpu.scoring import MeshExecutor
    from realtime_fraud_detection_tpu.scoring.pipeline import (
        init_scoring_models,
    )

    devices = jax.devices()
    if len(devices) < cfg.n_devices:
        raise RuntimeError(
            f"mesh drill needs {cfg.n_devices} devices, found "
            f"{len(devices)} — run via `rtfd mesh-drill` (it re-execs on a "
            f"virtual {cfg.n_devices}-device host platform) or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{cfg.n_devices}")
    devices = devices[:cfg.n_devices]
    window = cfg.inflight_depth       # identical for ref and every combo

    summary: Dict[str, Any] = {
        "drill": "mesh",
        "n_devices": cfg.n_devices,
        "model_axis": cfg.model_axis,
        "inflight_depth": cfg.inflight_depth,
        "batch": cfg.batch,
        "platform": devices[0].platform,
        "checks": {},
        "placements": {},
    }
    checks = summary["checks"]
    digest = hashlib.sha256()

    def fold(rows: List[tuple]) -> None:
        digest.update(json.dumps(rows, sort_keys=True).encode())

    # ------------------------------------------- phase 1: placement combos
    # (name, quantized, executor kwargs) — every combo re-scores the SAME
    # deterministic stream against a fresh single-device reference
    combos: List[Tuple[str, bool, Dict[str, Any]]] = [
        ("data_only", False,
         dict(model_axis=cfg.model_axis, replicas=1, shard_branches=())),
        ("bert_sharded", False,
         dict(model_axis=cfg.model_axis, replicas=1,
              shard_branches=("bert_text",))),
        ("all_neural_sharded", False,
         dict(model_axis=cfg.model_axis, replicas=1,
              shard_branches=ALL_NEURAL)),
        ("pool_x_mesh", False,
         dict(model_axis=cfg.model_axis, replicas=2,
              shard_branches=("bert_text",))),
        ("quant_bert_sharded", True,
         dict(model_axis=cfg.model_axis, replicas=1,
              shard_branches=("bert_text",))),
        ("quant_all_neural_sharded", True,
         dict(model_axis=cfg.model_axis, replicas=1,
              shard_branches=ALL_NEURAL)),
    ]
    ref_rows: Dict[bool, List[tuple]] = {}
    for quant in (False, True):
        gen, ref = _make_scorer(cfg, quant=quant)
        batches = [gen.generate_batch(cfg.batch)
                   for _ in range(cfg.n_batches)]
        ref_rows[quant] = _rows(_run_stream(ref, batches, window))
        fold(ref_rows[quant])

    for name, quant, kwargs in combos:
        gen, scorer = _make_scorer(cfg, quant=quant)
        executor = MeshExecutor(scorer, devices=devices,
                                inflight_depth=cfg.inflight_depth,
                                **kwargs)
        batches = [gen.generate_batch(cfg.batch)
                   for _ in range(cfg.n_batches)]
        got = _rows(_run_stream(scorer, batches, window))
        fold(got)
        bit = got == ref_rows[quant]
        checks[f"bit_identical_{name}"] = bit
        submitted = [str(r.get("transaction_id", "")) for b in batches
                     for r in b]
        checks[f"fifo_{name}"] = [t for t, *_ in got] == submitted
        entry: Dict[str, Any] = {
            "quantized": quant,
            "shard_branches": list(kwargs["shard_branches"]),
            "replicas": kwargs["replicas"],
            "bert_per_chip_frac": round(_bert_frac(executor), 4),
        }
        if kwargs["shard_branches"]:
            checks[f"bert_bytes_{name}"] = (
                entry["bert_per_chip_frac"] <= cfg.max_bert_per_chip_frac)
        if kwargs["replicas"] > 1:
            st = executor.stats()
            entry["per_replica_dispatched"] = [
                r["dispatched"] for r in st["replicas"]]
            checks["all_mesh_replicas_utilized"] = all(
                r["dispatched"] > 0 for r in st["replicas"])
            checks["round_robin_assignment"] = (
                list(executor.assignment_log)
                == [i % kwargs["replicas"]
                    for i in range(cfg.n_batches)])
        summary["placements"][name] = entry

    # --------------------------------------------- phase 2: ladder rungs
    # one stream stepping DOWN through every rung mid-flight (rules-only
    # included), mirrored on the reference — pins the per-dispatch mask
    # snapshot across the mesh, not just a statically-degraded program
    n_rungs = len(LADDER_LEVELS)
    rung_schedule = {i * cfg.rung_batches: i for i in range(n_rungs)}
    n_rung_batches = n_rungs * cfg.rung_batches

    gen_r, rung_ref = _make_scorer(cfg)
    ref_r = _rows(_run_stream(
        rung_ref, [gen_r.generate_batch(cfg.batch)
                   for _ in range(n_rung_batches)],
        window, rung_schedule=rung_schedule))
    gen_m, rung_scorer = _make_scorer(cfg)
    MeshExecutor(rung_scorer, devices=devices,
                 model_axis=cfg.model_axis,
                 inflight_depth=cfg.inflight_depth,
                 shard_branches=ALL_NEURAL)
    got_r = _rows(_run_stream(
        rung_scorer, [gen_m.generate_batch(cfg.batch)
                      for _ in range(n_rung_batches)],
        window, rung_schedule=rung_schedule))
    fold(got_r)
    checks["bit_identical_all_ladder_rungs"] = got_r == ref_r
    summary["ladder"] = {"rungs": n_rungs,
                         "batches_per_rung": cfg.rung_batches}

    # ------------------------------------------------ phase 3: hot swap
    new_models = init_scoring_models(
        jax.random.PRNGKey(101), bert_config=rung_scorer.bert_config,
        feature_dim=rung_scorer.sc.feature_dim,
        node_dim=rung_scorer.sc.node_dim)
    swap_at = cfg.swap_batches // 2

    gen_old, serial_old = _make_scorer(cfg)
    swap_old_ref = _run_stream(
        serial_old, [gen_old.generate_batch(cfg.batch)
                     for _ in range(cfg.swap_batches)], window)
    gen_new, serial_new = _make_scorer(cfg)
    # rtfd-lint: allow[lock-order] serial oracle scorer, single-threaded by construction
    serial_new.set_models(new_models)
    swap_new_ref = _run_stream(
        serial_new, [gen_new.generate_batch(cfg.batch)
                     for _ in range(cfg.swap_batches)], window)

    gen_sw, swap_scorer = _make_scorer(cfg)
    swap_exec = MeshExecutor(swap_scorer, devices=devices,
                             model_axis=cfg.model_axis,
                             inflight_depth=cfg.inflight_depth,
                             shard_branches=("bert_text",))
    swap_got = _run_stream(
        swap_scorer, [gen_sw.generate_batch(cfg.batch)
                      for _ in range(cfg.swap_batches)],
        window, swap_at=swap_at, swap_models=new_models)
    fold(_rows(swap_got))

    mixed = matches_old = matches_new = 0
    for i, batch_res in enumerate(swap_got):
        rows = _rows([batch_res])
        if rows == _rows([swap_old_ref[i]]):
            matches_old += 1
        elif rows == _rows([swap_new_ref[i]]):
            matches_new += 1
        else:
            mixed += 1
    checks["no_mixed_params_batch"] = (
        mixed == 0 and matches_old > 0 and matches_new > 0)
    # the swap must PRESERVE the placement: freshly swapped params are
    # still sharded, not silently replicated
    checks["swap_preserves_sharding"] = (
        _bert_frac(swap_exec) <= cfg.max_bert_per_chip_frac)
    summary["hot_swap"] = {
        "swap_at_batch": swap_at,
        "batches_on_old_params": matches_old,
        "batches_on_new_params": matches_new,
        "mixed_batches": mixed,
        "post_swap_bert_per_chip_frac": round(_bert_frac(swap_exec), 4),
    }

    # ------------------------------------------------ phase 4: donation
    # the donated entry must carry the blob-donation annotations into the
    # compiled program (tf.aliasing_output / jax.buffer_donor in the
    # lowering) and the plain entry must not. This is the truthful
    # evidence on every backend: the fused program's one output matches
    # no input shape, so CPU PJRT (strict aliasing only) drops the
    # donation at RUN time — an is_deleted check here would test the CPU
    # runtime, not our wiring — while TPU reuses the donated staging
    # space for temporaries, which is the batch-256 h2d lever the pool
    # plane measured. A donated run must also still score correctly.
    import warnings

    from realtime_fraud_detection_tpu.core.packing import pack_tree
    from realtime_fraud_detection_tpu.scoring import make_example_batch

    gen_d, don_scorer = _make_scorer(cfg)
    don_exec = MeshExecutor(don_scorer, devices=devices,
                            model_axis=cfg.model_axis,
                            inflight_depth=cfg.inflight_depth,
                            shard_branches=("bert_text",), donate=True)
    with warnings.catch_warnings():
        # CPU PJRT warns when a non-aliasable donation is dropped
        warnings.simplefilter("ignore")
        don_rows = _rows(_run_stream(
            don_scorer, [gen_d.generate_batch(cfg.batch)
                         for _ in range(2)], window))
    gen_p, plain_scorer = _make_scorer(cfg)
    MeshExecutor(plain_scorer, devices=devices,
                 model_axis=cfg.model_axis,
                 inflight_depth=cfg.inflight_depth,
                 shard_branches=("bert_text",), donate=False)
    plain_rows = _rows(_run_stream(
        plain_scorer, [gen_p.generate_batch(cfg.batch)
                       for _ in range(2)], window))
    checks["donated_scores_identical"] = don_rows == plain_rows

    ex_batch = make_example_batch(
        max(cfg.batch, don_exec.batch_multiple), don_scorer.sc,
        rng=np.random.default_rng(cfg.seed))
    blobs, pspec = pack_tree(ex_batch)
    mv = don_scorer.effective_model_valid()

    def _donor_args(text: str) -> int:
        return (text.count("jax.buffer_donor")
                + text.count("tf.aliasing_output"))

    donated_n = _donor_args(don_exec.donation_lowering(
        blobs, pspec, don_scorer.ensemble_params, mv, donate=True))
    plain_n = _donor_args(don_exec.donation_lowering(
        blobs, pspec, don_scorer.ensemble_params, mv, donate=False))
    # only non-empty blobs count: the default transfer layout ships a
    # zero-width bf16 blob, and XLA drops the donor annotation on a
    # 0-byte buffer
    n_blobs = sum(1 for v in blobs.values()
                  if v is not None and np.size(v) > 0)
    checks["donation_reaches_compiler"] = (
        donated_n >= n_blobs and plain_n == 0)
    summary["donation"] = {"donor_args": donated_n,
                           "staged_blobs": n_blobs,
                           "plain_donor_args": plain_n}

    checks = {k: bool(v) for k, v in checks.items()}
    summary["checks"] = checks
    summary["passed"] = all(checks.values())
    return summary, digest.hexdigest()


def run_mesh_drill(cfg: Optional[MeshDrillConfig] = None) -> Dict[str, Any]:
    cfg = cfg or MeshDrillConfig()
    summary, digest = _one_pass(cfg)
    summary["digest"] = digest
    if cfg.replay_check:
        # a second full pass from fresh scorers/streams must replay every
        # scored row bit-identically (the house determinism gate)
        _, digest2 = _one_pass(cfg)
        summary["checks"]["replay_bit_identical"] = digest == digest2
        summary["passed"] = all(
            bool(v) for v in summary["checks"].values())
    return summary


def compact_mesh_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """<2 KB single-line verdict (the bench.py final-stdout convention)."""
    placements = summary.get("placements") or {}
    return {
        "drill": "mesh",
        "passed": summary.get("passed", False),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "n_devices": summary.get("n_devices"),
        "model_axis": summary.get("model_axis"),
        "bert_per_chip_frac": {
            name: p.get("bert_per_chip_frac")
            for name, p in placements.items() if p.get("shard_branches")},
        "digest": (summary.get("digest") or "")[:16],
    }

"""Overlapped host-assembly stage: 2-stage software pipelining of the seam.

The fused device program made the TPU side of a microbatch one call; what
remained serial was the HOST side — ``FraudScorer.assemble`` (state joins,
encode, tokenize) ran on the same thread that then blocked in
``finalize``'s device wait, so assembly and device compute took turns
instead of overlapping. This module is the software-pipelining half of the
host-assembly plane (the input-pipeline lever of tf.data, arXiv:2101.12127):

    stage 1 (background thread): assemble + pad/pack + launch batch N+1
    stage 2 (caller's thread):   block on batch N's result, write back

``AssemblerStage`` owns one daemon thread and a bounded queue. ``submit``
enqueues a record batch and returns an ``AssembledHandle`` immediately; the
thread runs ``scorer.assemble`` + ``scorer.dispatch_assembled`` in FIFO
order, so while the caller waits out batch N's device time in
``finalize``, batch N+1's host assembly is already running. The queue bound
is the pipeline depth — a slow device backpressures ``submit`` instead of
growing an unbounded backlog.

Ordering and state-consistency contract:

- Batches dispatch in submit order (single stage thread, FIFO queue) —
  the overlap never reorders scoring, fan-out, or offset commits.
- ``lock`` serializes the scorer's host-state mutation: the stage holds it
  across assemble+dispatch; callers pass the same lock to
  ``scorer.finalize`` so the state write-back never interleaves with an
  assembly. The device wait itself happens outside the lock — that is the
  window the overlap lives in.
- Velocity/history staleness is the SAME tradeoff the pipelined run loops
  already document (stream/job.JobConfig.pipeline_depth): batch N+1 may
  assemble before batch N's write-back lands. With overlap the interleaving
  becomes timing-dependent rather than fixed, which is why the stream job
  keeps overlap opt-in (``JobConfig.overlap_assembly``).

QoS interaction: admission, dedupe and ladder observation stay on the
caller's thread BEFORE ``submit`` (stream/job.dispatch_batch), and batch
close deadlines remain the assembler's (stream/microbatch) — the overlap
stage neither drops nor reorders admission decisions; the virtual-clock
drill in tests/test_host_pipeline.py pins this.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, List, Mapping, Optional, Sequence

__all__ = ["AssembledHandle", "AssemblerStage"]


class AssembledHandle:
    """Future for one submitted batch: resolves to a PendingScore."""

    __slots__ = ("_event", "_pending", "_exc")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._pending: Any = None
        self._exc: Optional[BaseException] = None

    def _set(self, pending: Any) -> None:
        self._pending = pending
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until the batch is assembled + dispatched; returns the
        PendingScore (or re-raises the stage's assembly error)."""
        if not self._event.wait(timeout):
            raise TimeoutError("assembled batch not ready")
        if self._exc is not None:
            raise self._exc
        return self._pending


class AssemblerStage:
    """Background assemble+dispatch stage over one FraudScorer.

    One daemon thread, one bounded FIFO queue: ``submit`` returns a handle
    immediately, ``handle.result()`` (usually via the caller's finalize
    path) joins the pipeline back up. ``lock`` is the stage's state lock —
    pass it to ``scorer.finalize(..., lock=stage.lock)`` so write-backs
    serialize against assemblies.
    """

    def __init__(self, scorer, depth: int = 2):
        self.scorer = scorer
        self.lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # cumulative seconds the stage spent assembling/dispatching — the
        # numerator of the bench's overlap accounting
        self.busy_s = 0.0
        self.batches = 0

    # ------------------------------------------------------------ lifecycle
    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="host-assembler", daemon=True)
            self._thread.start()

    def close(self) -> None:
        """Drain and stop the stage thread (idempotent)."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=30.0)
            self._thread = None

    # --------------------------------------------------------------- submit
    def submit(self, records: Sequence[Mapping[str, Any]],
               now: Optional[float] = None,
               trace: Optional[Any] = None) -> AssembledHandle:
        """Enqueue one microbatch for background assembly + dispatch.

        Blocks when ``depth`` batches are already queued (backpressure);
        the returned handle resolves to the PendingScore in FIFO order.
        ``trace`` (obs.tracing.TraceBatch) rides the queue item so the
        stage thread's assemble/pack/dispatch marks land on the batch
        that is actually being assembled — trace↔batch attachment is by
        object identity, immune to thread interleaving.
        """
        if self._closed:
            raise RuntimeError("assembler stage is closed")
        self._ensure_started()
        handle = AssembledHandle()
        self._q.put((list(records), now, handle, trace))
        return handle

    def finalize(self, handle: AssembledHandle,
                 now: Optional[float] = None) -> List[dict]:
        """Resolve a handle and finalize under the stage lock — the
        convenience join for callers without their own completion path."""
        pending = handle.result()
        return self.scorer.finalize(pending, now=now, lock=self.lock)

    # ----------------------------------------------------------------- run
    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            records, now, handle, trace = item
            # rtfd-lint: allow[wall-clock] busy_s is real CPU accounting for the bench overlap ratio
            t0 = time.perf_counter()
            try:
                with self.lock:
                    if trace is not None:
                        trace.mark("assemble")
                    batch = self.scorer.assemble(records, now)
                    pending = self.scorer.dispatch_assembled(
                        batch, records, t0=t0, trace=trace)
            except BaseException as e:  # noqa: BLE001 — surfaces at result()
                # account busy time BEFORE resolving the handle: a caller
                # that reads busy_s right after the last result() must see
                # every batch counted
                # rtfd-lint: allow[wall-clock] busy_s is real CPU accounting for the bench overlap ratio
                self.busy_s += time.perf_counter() - t0
                self.batches += 1
                handle._set_exception(e)
            else:
                # rtfd-lint: allow[wall-clock] busy_s is real CPU accounting for the bench overlap ratio
                self.busy_s += time.perf_counter() - t0
                self.batches += 1
                handle._set(pending)

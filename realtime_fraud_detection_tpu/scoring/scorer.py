"""Host-side scoring orchestrator around the fused device program.

Plays the combined role of the reference's Flink ``TransactionProcessor``
(profile/velocity joins, TransactionProcessor.java:51-92), the serving
``FeatureProcessor`` + ``EnsemblePredictor`` (main.py:146-215), and the
``RedisTransactionSink`` state write-backs (RedisTransactionSink.java:53-135)
— but restructured TPU-first:

  host: join state -> encode dense batch -> pad to bucket -> shard over mesh
  device: ONE fused XLA program (features + 5 branches + ensemble + decisions)
  host: unpad -> response dicts -> state write-back

State reads happen before scoring and writes after, matching the reference's
read-then-sink ordering, but single-writer per process (fixing the
RMW races noted in SURVEY.md §5.2).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

import jax
import numpy as np

from realtime_fraud_detection_tpu.core.batching import (
    BATCH_BUCKETS,
    bucket_for,
)
from realtime_fraud_detection_tpu.core.mesh import (
    build_mesh,
    local_mesh_size,
    shard_batch,
)
from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
from realtime_fraud_detection_tpu.features.rules import (
    DECISIONS,
    RISK_LEVEL_NAMES,
)
from realtime_fraud_detection_tpu.features.schema import encode_transactions
from realtime_fraud_detection_tpu.models.bert import BertConfig, TINY_CONFIG
from realtime_fraud_detection_tpu.models.text import combined_text
from realtime_fraud_detection_tpu.models.tokenizer import FraudTokenizer
from realtime_fraud_detection_tpu.core.packing import pack_tree
from realtime_fraud_detection_tpu.scoring.pipeline import (
    MODEL_NAMES,
    NUM_MODELS,
    OUT_COLUMNS,
    ScoreBatch,
    ScorerConfig,
    ScoringModels,
    init_scoring_models,
    score_fused,
    score_fused_packed,
)
from realtime_fraud_detection_tpu.state.history import (
    EntityGraphStore,
    UserHistoryStore,
)
from realtime_fraud_detection_tpu.state.stores import (
    ProfileStore,
    TransactionCache,
    VelocityStore,
)
from realtime_fraud_detection_tpu.utils.config import (
    Config,
    KernelSettings,
    VALID_KERNEL_SITES,
)


import dataclasses


@dataclasses.dataclass
class PendingScore:
    """A dispatched-but-not-finalized microbatch.

    ``out`` holds device arrays still being computed (JAX async dispatch);
    ``features`` is the host copy of this batch's 64-wide feature rows,
    captured at dispatch time because a later dispatch overwrites the
    scorer's ``last_features``.
    """

    records: List[Mapping[str, Any]]
    n: int
    out: Any
    features: np.ndarray
    # Host-side assemble+dispatch cost, captured when dispatch() returns.
    # Under two-deep pipelining the wall time between dispatch and finalize
    # includes queue wait (the caller is off assembling the next batch), so
    # finalize() measures its own device wait and adds this — never the gap.
    dispatch_ms: float
    # The branch-validity mask and rules-only flag THIS batch was dispatched
    # under: the QoS ladder may step between dispatch and finalize, and the
    # response must describe the program that actually ran.
    model_valid: Optional[np.ndarray] = None
    rules_only: bool = False
    # pooled dispatch (scoring/device_pool.py): the PoolToken finalize
    # resolves through DevicePool.wait (retry-on-replica-failure) instead
    # of a plain device_get. None = single-device path.
    pool_token: Optional[Any] = None
    # tracing plane (obs/tracing.py): the microbatch's TraceBatch carrier.
    # The scorer marks assemble/pack/dispatch/device_wait/finalize on it;
    # the owner (stream job / serving app) finishes it after fan-out.
    # None = tracing off (the default no-op fast path).
    trace: Optional[Any] = None


class _EntityIndex:
    """Stable string-id -> dense int index with on-the-fly node features.

    Rows live in one preallocated, doubling (capacity, node_dim) table
    written in place — ``table()`` is a zero-copy slice, never a restack
    (the old stacked-row cache re-stacked every batch that saw a new
    entity, which on a fresh stream is every batch).
    """

    def __init__(self, node_dim: int):
        self.node_dim = node_dim
        self._idx: Dict[str, int] = {}
        self._profiled: set[str] = set()
        self._tbl = np.zeros((256, node_dim), np.float32)
        self._n = 0

    def __setstate__(self, state) -> None:
        """Checkpoint migration: pre-host-plane snapshots pickled the
        stacked-row form (``_rows``/``_table``); rebuild the in-place
        table from it."""
        if "_rows" not in state:
            self.__dict__.update(state)
            return
        self.node_dim = state["node_dim"]
        self._idx = state["_idx"]
        self._profiled = state["_profiled"]
        rows = state["_rows"]
        self._n = len(rows)
        cap = 256
        while cap < max(self._n, 1):
            cap *= 2
        self._tbl = np.zeros((cap, self.node_dim), np.float32)
        if rows:
            self._tbl[: self._n] = np.stack(rows, axis=0)

    def _grow(self, need: int) -> None:
        cap = self._tbl.shape[0]
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        tbl = np.zeros((cap, self.node_dim), np.float32)
        tbl[: self._tbl.shape[0]] = self._tbl
        self._tbl = tbl

    def lookup(self, entity_id: str, profile: Optional[Mapping[str, Any]],
               is_merchant: bool) -> int:
        i = self._idx.get(entity_id)
        if i is None:
            i = self._n
            self._idx[entity_id] = i
            self._grow(i + 1)
            self._tbl[i] = self._featurize(profile, is_merchant)
            self._n += 1
        elif profile is not None and entity_id not in self._profiled:
            # a profile arrived after first sight — refresh the stale zero row
            self._tbl[i] = self._featurize(profile, is_merchant)
        if profile is not None:
            self._profiled.add(entity_id)
        return i

    def lookup_batch(self, entity_ids: Sequence[str],
                     profiles: Mapping[str, Mapping[str, Any]],
                     is_merchant: bool) -> np.ndarray:
        """Batched lookup: one dense index vector for a whole microbatch.
        Featurization runs only for ids never seen (or first seen without a
        profile that has one now) — the steady-state batch is pure dict
        hits."""
        out = np.empty((len(entity_ids),), np.int64)
        idx_get = self._idx.get
        prof_get = profiles.get
        profiled = self._profiled
        for k, eid in enumerate(entity_ids):
            i = idx_get(eid)
            if i is None or (eid not in profiled
                             and prof_get(eid) is not None):
                i = self.lookup(eid, prof_get(eid), is_merchant)
            out[k] = i
        return out

    def _featurize(self, p: Optional[Mapping[str, Any]], is_merchant: bool) -> np.ndarray:
        """Node features mirroring models.gnn.build_node_features slots."""
        row = np.zeros((self.node_dim,), np.float32)
        if p is None:
            row[8] = 1.0 if is_merchant else 0.0
            return row
        if is_merchant:
            from realtime_fraud_detection_tpu.features.schema import (
                MERCHANT_CATEGORIES,
                _code,
            )

            risk = {"low": 0, "medium": 1, "high": 2}.get(str(p.get("risk_level")), 1)
            hours = p.get("operating_hours") or {}
            row[0] = risk / 2.0
            row[1] = float(p.get("fraud_rate", 0.05))
            row[2] = np.log1p(float(p.get("avg_transaction_amount", 0.0)))
            row[3] = float(bool(p.get("is_blacklisted", False)))
            row[4] = _code(MERCHANT_CATEGORIES, p.get("category")) / 10.0
            row[5] = float(hours.get("start_hour", 0)) / 24.0
            row[6] = float(hours.get("end_hour", 24)) / 24.0
            row[8] = 1.0
        else:
            patterns = p.get("behavioral_patterns") or {}
            row[0] = float(p.get("risk_score", 0.5))
            row[1] = np.log1p(float(p.get("avg_transaction_amount", 0.0)))
            row[2] = float(p.get("transaction_frequency", 0.0))
            row[3] = float(p.get("account_age_days", 0.0)) / 365.0
            row[4] = float(str(p.get("kyc_status", "")) == "verified")
            row[5] = float(patterns.get("weekend_activity", 0.5))
            row[6] = float(patterns.get("international_transactions", 0.0) or 0.0)
            row[7] = float(patterns.get("online_preference", 0.7))
        return row

    def table(self) -> np.ndarray:
        return self._tbl[: self._n] if self._n else self._tbl[:1]

    def peek_rows(self, entity_ids: Sequence[str]) -> np.ndarray:
        """Feature rows for KNOWN ids, zero rows for unknown — a read-only
        probe that never creates entries (the typed sampler resolves 2-hop
        users that may belong to other partitions; creating index rows for
        them would grow this table with entities this worker never
        scores)."""
        out = np.zeros((len(entity_ids), self.node_dim), np.float32)
        get = self._idx.get
        for k, eid in enumerate(entity_ids):
            i = get(eid)
            if i is not None:
                out[k] = self._tbl[i]
        return out


class _StagingBuffers:
    """Preallocated, reused pad staging per bucket shape.

    ``pad`` writes a microbatch's leaves into the bucket-sized buffers
    (write-into, not rebuild) with pad rows replicating row 0, exactly like
    core/batching.pad_to_bucket — minus the 65 fresh allocations per batch.
    Safe to reuse because core/packing.pack_tree copies every leaf into the
    transfer blobs before ``dispatch`` returns; nothing downstream holds a
    reference to the staging arrays. NOT safe for concurrent dispatches —
    the same contract as the scorer's state stores (single assembly thread).
    """

    def __init__(self) -> None:
        self._bufs: Dict[int, List[np.ndarray]] = {}
        self._masks: Dict[int, np.ndarray] = {}

    def pad(self, tree: Any, n: int, size: int) -> tuple:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        bufs = self._bufs.get(size)
        shapes = [((size,) + np.shape(lf)[1:], np.asarray(lf).dtype)
                  for lf in leaves]
        if bufs is None or [(b.shape, b.dtype) for b in bufs] != shapes:
            bufs = [np.empty(shape, dtype) for shape, dtype in shapes]
            self._bufs[size] = bufs
        for buf, leaf in zip(bufs, leaves):
            arr = np.asarray(leaf)
            buf[:n] = arr
            if n < size:
                buf[n:] = arr[:1]          # replicate row 0 (pad_to_bucket)
        mask = self._masks.get(size)
        if mask is None:
            self._masks[size] = mask = np.zeros((size,), bool)
        mask[:n] = True
        mask[n:] = False
        return jax.tree_util.tree_unflatten(treedef, bufs), mask


def _stage_bf16(padded):
    """Downcast the float-heavy staging leaves to bfloat16 before packing.

    Runs on freshly written HOST staging buffers (``_StagingBuffers.pad``
    output) — never a device array, so it sits outside the dispatch path's
    d2h discipline by construction. The conversion halves the H2D payload
    for history/entity features (the batch-256 transfer lever)."""
    import ml_dtypes

    bf = ml_dtypes.bfloat16
    out = padded.replace(
        history=np.asarray(padded.history, bf),
        user_feat=np.asarray(padded.user_feat, bf),
        merchant_feat=np.asarray(padded.merchant_feat, bf),
        user_neigh_feat=np.asarray(padded.user_neigh_feat, bf),
        merch_neigh_feat=np.asarray(padded.merch_neigh_feat, bf),
    )
    if padded.user_neigh2_feat is not None:
        # typed-graph two-hop context: by far the widest float payload
        # (K x K2 x D per row) — exactly the tensors the bf16 wire format
        # exists for
        out = out.replace(
            user_neigh2_feat=np.asarray(padded.user_neigh2_feat, bf),
            merch_neigh2_feat=np.asarray(padded.merch_neigh2_feat, bf),
        )
    return out


class FraudScorer:
    """Stateful streaming scorer: the framework's flagship serving object."""

    def __init__(
        self,
        config: Optional[Config] = None,
        models: Optional[ScoringModels] = None,
        mesh=None,
        scorer_config: Optional[ScorerConfig] = None,
        bert_config: BertConfig = TINY_CONFIG,
        seed: int = 0,
        state_client=None,
        stores=None,
    ):
        self.config = config or Config()
        self.sc = scorer_config or ScorerConfig()
        self.bert_config = bert_config
        self.mesh = mesh if mesh is not None else build_mesh()
        self.models = models if models is not None else init_scoring_models(
            jax.random.PRNGKey(seed), bert_config=bert_config,
            feature_dim=self.sc.feature_dim, node_dim=self.sc.node_dim,
        )
        # quantized scoring plane (models/quant.py + QuantSettings): the
        # BERT branch drops to weight-only int8 and the tree branches can
        # take the GEMM-form kernels. Applied HERE (and in set_models) so
        # every downstream consumer — the mesh path, the device pool's
        # per-replica replication, checkpoint save — sees one consistent
        # parameter form.
        self.quant = self.config.quant
        self.models = self._maybe_quantize(self.models)
        # divergence-gate verdict ledger (rtfd quant-drill records its
        # oracle verdicts here; obs.metrics.sync_quant mirrors the counts)
        self._quant_gate_counts: Dict[str, int] = {"pass": 0, "fail": 0}
        # Pallas kernel plane (ops/ + KernelSettings): per-site static
        # kernel selection for the fused program. Interpret mode is
        # resolved ONCE per scorer from the backend — on non-TPU hosts the
        # kernels run through the Pallas interpreter (the parity-pinned
        # CPU path); on TPU they lower for real. Dispatch/fallback
        # counters are kept host-side using the SAME supports() predicates
        # the traced code consults (obs.metrics.sync_kernels mirrors them).
        self.kernels = getattr(self.config, "kernels", None) or KernelSettings()
        self._kernel_interpret = jax.default_backend() != "tpu"
        self._kernel_counts: Dict[str, Dict[str, int]] = {
            "dispatch": {s: 0 for s in VALID_KERNEL_SITES},
            "fallback": {s: 0 for s in VALID_KERNEL_SITES},
        }
        # memoized static-kwarg tuples (kernel_static/quant_static): the
        # hot dispatch path does a dict lookup instead of rebuilding the
        # dicts per microbatch. Keyed by settings VALUES (+ the QoS rung
        # for the megakernel), so mutating the settings or stepping the
        # ladder lands on a different entry — never a stale one.
        self._static_cache: Dict[tuple, Dict[str, Any]] = {}
        # programs-per-microbatch of the most recent dispatch (1 when the
        # megakernel engages, the chain length otherwise); exported as the
        # kernel_launches_per_batch gauge
        self._last_launches_per_batch = 0
        self.ensemble_params = EnsembleParams.from_config(self.config, MODEL_NAMES)
        enabled = self.config.get_enabled_models()
        self.model_valid = np.asarray(
            [n in enabled for n in MODEL_NAMES], bool
        )
        # QoS degradation (qos/ladder.py): an extra mask AND-ed over the
        # deployment validity, set per ladder rung; rules-only replaces the
        # ensemble output with the rule score host-side
        self._qos_mask: Optional[np.ndarray] = None
        self._qos_rules_only = False
        self.qos_level = 0

        # streaming state (the Redis-equivalent plane, SURVEY.md §2.5).
        # Default: in-process single-writer stores (state lives with the
        # microbatcher — no network hop in the hot loop). With
        # ``state_client`` (a state.RespClient), profiles/velocity/txn-cache
        # move to the shared RESP tier so N replicas share one state plane
        # (state/shared.py; the reference's Redis role). Config alone can
        # select the shared tier too: state.backend="redis" connects to
        # state.redis_host:redis_port (the reference's REDIS_HOST/PORT env
        # contract) when no explicit client is passed.
        st = self.config.state
        cache_kwargs = dict(
            txn_ttl_s=st.transaction_ttl_s,
            features_ttl_s=st.features_ttl_s,
            user_list_len=st.user_history_len,
            merchant_list_len=st.merchant_history_len,
        )
        self._owned_state_client = None
        if state_client is None and st.backend == "redis":
            from realtime_fraud_detection_tpu.state import RespClient

            state_client = RespClient(host=st.redis_host, port=st.redis_port)
            # config-driven connection: this scorer owns the socket and
            # close() releases it (an explicitly passed client stays the
            # caller's to manage)
            self._owned_state_client = state_client
        if stores is not None:
            # injected store bundle (cluster/partition.PartitionedStore,
            # or any object exposing the same four store attributes): the
            # partition-parallel worker plane hands each worker a scorer
            # whose state is key-sharded to its owned partitions — the
            # scorer itself stays shard-oblivious. Mutually exclusive
            # with the shared RESP tier: both decide where state lives.
            if state_client is not None:
                raise ValueError(
                    "pass either stores= (partitioned state) or "
                    "state_client= (shared RESP tier), not both")
            self.profiles = stores.profiles
            self.velocity = stores.velocity
            self.txn_cache = stores.txn_cache
            self.history = stores.history
            hist_seq = getattr(self.history, "seq_len", self.sc.seq_len)
            hist_dim = getattr(self.history, "feature_dim",
                               self.sc.feature_dim)
            if (hist_seq != self.sc.seq_len
                    or hist_dim != self.sc.feature_dim):
                # a mismatched history table would silently gather
                # wrong-shaped LSTM inputs — refuse at construction
                raise ValueError(
                    f"injected history store is ({hist_seq}, {hist_dim})"
                    f", scorer expects ({self.sc.seq_len}, "
                    f"{self.sc.feature_dim})")
        elif state_client is not None:
            from realtime_fraud_detection_tpu.state.shared import (
                SharedProfileStore,
                SharedTransactionCache,
                SharedVelocityStore,
            )

            self.profiles = SharedProfileStore(state_client)
            self.velocity = SharedVelocityStore(state_client)
            self.txn_cache = SharedTransactionCache(state_client,
                                                    **cache_kwargs)
            self.history = UserHistoryStore(self.sc.seq_len,
                                            self.sc.feature_dim)
        else:
            self.profiles = ProfileStore()
            self.velocity = VelocityStore()
            self.txn_cache = TransactionCache(**cache_kwargs)
            self.history = UserHistoryStore(self.sc.seq_len,
                                            self.sc.feature_dim)
        self.graph = EntityGraphStore(self.sc.fanout)
        # typed entity-graph plane (graph/): heterogeneous
        # user<->device<->merchant<->IP neighborhoods for the GNN branch.
        # The store rides the injected partition bundle when one is given
        # (PartitionedStore.graph facade — snapshot/handoff/digest for
        # free); otherwise it is scorer-local like the bipartite store.
        if self.sc.graph_mode not in ("bipartite", "typed"):
            raise ValueError(
                f"ScorerConfig.graph_mode must be 'bipartite' or 'typed', "
                f"got {self.sc.graph_mode!r}")
        self.typed_graph = None
        self._sampler = None
        if self.sc.graph_mode == "typed":
            from realtime_fraud_detection_tpu.graph.sampler import (
                NeighborSampler,
            )
            from realtime_fraud_detection_tpu.graph.store import (
                TypedEntityGraph,
            )

            tg = getattr(stores, "graph", None) if stores is not None \
                else None
            self.typed_graph = (tg if tg is not None
                                else TypedEntityGraph(self.sc.fanout))
            self._sampler = NeighborSampler(
                self.typed_graph, self.sc.node_dim, self.sc.fanout,
                self.sc.graph_fanout2,
                user_rows=lambda ids: self._users.peek_rows(ids),
                merchant_rows=lambda ids: self._merchants.peek_rows(ids))
        if self.sc.tokenizer == "wordpiece":
            from realtime_fraud_detection_tpu.models.wordpiece import (
                WordPieceTokenizer,
            )

            self.tokenizer = WordPieceTokenizer(
                max_length=self.sc.text_len,
                cache_entries=self.sc.token_cache_entries)
        elif self.sc.tokenizer == "word":
            self.tokenizer = FraudTokenizer(
                vocab_size=bert_config.vocab_size,
                max_length=self.sc.text_len,
                cache_entries=self.sc.token_cache_entries,
            )
        else:
            # a typo'd tokenizer name must not silently feed a text model
            # ids from the wrong vocabulary
            raise ValueError(
                f"ScorerConfig.tokenizer must be 'word' or 'wordpiece', "
                f"got {self.sc.tokenizer!r}")
        if self.tokenizer.vocab_size > bert_config.vocab_size:
            # JAX gathers clamp out-of-bounds indices SILENTLY: a token id
            # beyond the embedding table would score through row
            # vocab_size-1 with no error anywhere (ADVICE r5) — refuse the
            # pairing at construction instead
            raise ValueError(
                f"tokenizer vocab_size {self.tokenizer.vocab_size} exceeds "
                f"bert_config.vocab_size {bert_config.vocab_size}: "
                f"out-of-range ids would be silently clamped by the "
                f"embedding gather")
        self._users = _EntityIndex(self.sc.node_dim)
        self._merchants = _EntityIndex(self.sc.node_dim)
        # host-assembly plane: cross-batch entity join-row cache
        # (generation-stamped against the profile store), reusable pad
        # staging per bucket, and per-stage wall-clock spans
        # (assemble/pack/dispatch/device_wait) for the obs plane + bench
        from realtime_fraud_detection_tpu.features.schema import (
            EntityRowCache,
        )
        from realtime_fraud_detection_tpu.obs.profiling import SpanTimer

        self._join_cache = EntityRowCache()
        self._staging = _StagingBuffers()
        self.spans = SpanTimer()
        # device-pool scoring plane (scoring/device_pool.py): when attached,
        # dispatch_assembled routes whole microbatches round-robin across
        # per-device model replicas instead of sharding one batch over the
        # mesh — see DevicePool for the ordering/equality contract
        self._pool = None
        self.last_features = np.zeros((0, self.sc.feature_dim), np.float32)
        self.stats: Dict[str, float] = {"scored": 0, "batches": 0, "total_time_s": 0.0}
        # top-10 global feature importances (reference explanation field,
        # ensemble_predictor.py:371-435); set after training via
        # set_feature_importances, attached to every explanation
        self._top_importances: Optional[Dict[str, float]] = None

    # ------------------------------------------------------------- state plane
    def seed_profiles(self, users: Mapping[str, Mapping[str, Any]],
                      merchants: Mapping[str, Mapping[str, Any]]) -> None:
        self.profiles.seed(users, merchants)

    def _model_valid_dev(self, mv: Optional[np.ndarray] = None):
        """Device copy of the branch-validity mask, re-pushed only when the
        mask changes — not one h2d transfer per microbatch."""
        cached = getattr(self, "_mv_cache", None)
        if mv is None:
            mv = self.effective_model_valid()
        mv = np.asarray(mv)
        if cached is None or not np.array_equal(cached[0], mv):
            self._mv_cache = (mv.copy(), jax.device_put(mv))
        return self._mv_cache[1]

    # ------------------------------------------------------------- pooling
    def attach_pool(self, pool) -> None:
        """Adopt a DevicePool: subsequent dispatches route through it.
        Called by DevicePool.__init__ — construct the scorer first, then
        the pool around it."""
        self._pool = pool

    # --------------------------------------------------------- graph plane
    def attach_graph_fetch(self, client) -> None:
        """Adopt a graph.fetch.GraphFetchClient: the typed sampler
        resolves non-owned neighbor nodes through it (budgeted,
        deadlined, degrade-to-local). Typed graph mode only."""
        if self._sampler is None:
            raise ValueError(
                "attach_graph_fetch needs ScorerConfig.graph_mode='typed'")
        self._sampler.attach_fetch(client)

    def graph_snapshot(self) -> Dict[str, Any]:
        """Graph-plane observability payload
        (obs.metrics.MetricsCollector.sync_graph): typed-store node/edge
        counts by type, sampler cache hits/misses/evictions, and — when a
        fetch client is attached — the cross-partition resolution
        counters. Bipartite mode reports just the mode (the legacy store
        has no typed series to mirror)."""
        snap: Dict[str, Any] = {"mode": self.sc.graph_mode}
        if self.typed_graph is not None:
            snap["store"] = self.typed_graph.stats()
            snap["sampler"] = self._sampler.stats()
            if self._sampler.fetch is not None:
                snap["fetch"] = self._sampler.fetch.stats()
        return snap

    @property
    def pool(self):
        return self._pool

    # ---------------------------------------------------------- degradation
    def set_degradation(self, mask: Optional[np.ndarray],
                        rules_only: bool = False, level: int = 0) -> None:
        """Apply a QoS ladder rung: ``mask`` narrows the enabled-branch set
        for subsequent dispatches (None = full ensemble); ``rules_only``
        swaps the served score for the rule score at response build. Cheap
        host-field writes — the fused program takes validity as a runtime
        tensor, so stepping the ladder never recompiles. With a device
        pool attached the rung fans out to all replicas atomically for
        free: every pooled dispatch passes the CURRENT host mask and each
        replica refreshes its device copy by value comparison, so every
        later dispatch — on any replica — runs the new rung while
        in-flight batches complete under their dispatch-time snapshot."""
        self._qos_mask = None if mask is None else np.asarray(mask, bool)
        self._qos_rules_only = bool(rules_only)
        self.qos_level = int(level)

    def effective_model_valid(self) -> np.ndarray:
        """Deployment validity AND the current QoS rung's mask."""
        if self._qos_mask is None:
            return np.asarray(self.model_valid)
        return np.asarray(self.model_valid) & self._qos_mask

    # ----------------------------------------------------------------- models
    def set_feature_importances(self, importances) -> None:
        """Attach global gain importances (e.g. ``GBDTTrainer.
        feature_importances_``) to prediction explanations as the top-10
        name->score mapping the reference emits (§2.2). Pass None to clear."""
        if importances is None:
            self._top_importances = None
            return
        from realtime_fraud_detection_tpu.features.extract import (
            top_feature_importances,
        )

        self._top_importances = top_feature_importances(importances)

    def refresh_blend_from_config(self) -> None:
        """Re-read ensemble weights/strategy and the enabled-branch set
        from ``self.config`` — the zero-recompile blend swap (weights and
        validity are runtime tensors to the fused program, not compile
        constants). Callers hold the score lock; the next microbatch runs
        the new blend."""
        self.ensemble_params = EnsembleParams.from_config(
            self.config, MODEL_NAMES)
        enabled = self.config.get_enabled_models()
        self.model_valid = np.asarray(
            [n in enabled for n in MODEL_NAMES], bool)

    def set_models(self, models: ScoringModels) -> None:
        """Swap the model set (hot reload). Params are replicated onto this
        scorer's mesh — arrays restored from checkpoint arrive committed to
        one device, which would clash with mesh-sharded batch arguments.
        With the quant plane on, incoming f32 params are quantized FIRST
        (host-side, before replication), so a hot swap — /reload-models,
        feedback promotion, drill retrain — always serves this scorer's
        configured form and the pool fan-out replicates the small blobs.

        Clears any attached feature importances: they describe the OLD
        trees; the caller re-attaches via set_feature_importances if it has
        importances for the new model set.
        """
        from realtime_fraud_detection_tpu.core.mesh import replicated_sharding

        models = self._maybe_quantize(models)
        self.models = jax.device_put(models, replicated_sharding(self.mesh))
        self._top_importances = None
        if self._pool is not None:
            # replica-by-replica fan-out; in-flight batches keep the params
            # reference they captured at launch — never mixed within a batch
            self._pool.set_models(models)

    # ------------------------------------------------------------ quantization
    def _maybe_quantize(self, models: ScoringModels) -> ScoringModels:
        """Apply the configured weight quantization to an incoming model
        set (idempotent — already-quantized params pass through). The
        calibrated pytree is committed back onto the mesh immediately:
        calibration runs host-side, and leaving numpy leaves in
        ``self.models`` would re-upload the whole branch H2D on every
        dispatch of the non-pool path."""
        if self.quant.bert_mode() != "int8":
            return models
        from realtime_fraud_detection_tpu.core.mesh import (
            replicated_sharding,
        )
        from realtime_fraud_detection_tpu.models.quant import (
            quantize_bert_params,
        )

        qbert = quantize_bert_params(models.bert)
        if qbert is models.bert:           # already quantized: no re-put
            return models
        return models.replace(
            bert=jax.device_put(qbert, replicated_sharding(self.mesh)))

    def quant_static(self) -> Dict[str, str]:
        """The static kernel-selection kwargs for the fused program —
        threaded into every dispatch (mesh path AND the device pool's
        per-replica launches). The BERT mode needs no static flag: the
        compute seam detects the quantized parameter layout structurally.
        Memoized by settings values — callers splat the returned dict and
        must not mutate it."""
        q = self.quant
        key = ("quant", q.enabled, q.tree_kernel, q.iforest_kernel)
        cached = self._static_cache.get(key)
        if cached is None:
            if not q.enabled:
                cached = {"tree_kernel": "gather",
                          "iforest_kernel": "gather"}
            else:
                cached = {"tree_kernel": q.tree_kernel,
                          "iforest_kernel": q.iforest_kernel}
            self._static_cache[key] = cached
        return cached

    def record_quant_gate(self, passed: bool) -> None:
        """Record a divergence-oracle verdict (rtfd quant-drill / any
        caller running the quantized-vs-f32 comparison); mirrored to the
        ``quant_gate_verdicts_total`` Prometheus series by sync_quant."""
        self._quant_gate_counts["pass" if passed else "fail"] += 1

    def quant_snapshot(self) -> Dict[str, Any]:
        """Quant-plane observability payload (obs.metrics.sync_quant):
        the SERVED per-branch modes (read from the live params, not the
        config — the truth after any allow_arch_mismatch restore), param
        bytes per quantizable branch, and cumulative gate verdicts."""
        from realtime_fraud_detection_tpu.models.quant import (
            bert_param_bytes,
            is_quantized_bert,
        )

        static = self.quant_static()
        return {
            "modes": {
                "bert_text": ("int8" if is_quantized_bert(self.models.bert)
                              else "f32"),
                "xgboost_primary": static["tree_kernel"],
                "isolation_forest": static["iforest_kernel"],
            },
            "param_bytes": {"bert_text": bert_param_bytes(self.models.bert)},
            "gate": dict(self._quant_gate_counts),
        }

    # ------------------------------------------------------------ kernel plane
    def kernel_static(self, model_valid=None) -> Dict[str, Any]:
        """The kernel-plane static kwargs for the fused program — threaded
        into every dispatch next to ``quant_static()``. All-off while the
        plane is disabled, so the compiled program (and the packed result
        layout) is byte-identical to the legacy one.

        With the megakernel on, ``mega_valid`` carries the QoS rung as a
        compile-time branch-validity tuple (``model_valid`` when given —
        the pool/mesh retry paths pass their dispatch-time snapshot — else
        the current effective mask). Each rung is its own jit cache entry:
        the per-rung program cache. With the megakernel off the key stays
        None, so stepping the ladder never churns the jit cache (the
        runtime-mask zero-recompile discipline is untouched). Memoized by
        settings values + rung — callers splat, never mutate."""
        k = self.kernels
        if not k.enabled:
            key = ("kernel", False)
            cached = self._static_cache.get(key)
            if cached is None:
                cached = {"dequant_kernel": "off", "epilogue_kernel": "off",
                          "kernel_interpret": False,
                          "megakernel": "off", "mega_valid": None}
                self._static_cache[key] = cached
            return cached
        mega_valid = None
        if k.megakernel == "pallas":
            mv = (self.effective_model_valid() if model_valid is None
                  else np.asarray(model_valid))
            mega_valid = tuple(bool(v) for v in mv)
        key = ("kernel", True, k.dequant_matmul, k.epilogue, k.attention,
               k.megakernel, self._kernel_interpret, mega_valid)
        cached = self._static_cache.get(key)
        if cached is None:
            cached = {"dequant_kernel": k.dequant_matmul,
                      "epilogue_kernel": k.epilogue,
                      "kernel_interpret": self._kernel_interpret,
                      "megakernel": k.megakernel,
                      "mega_valid": mega_valid}
            self._static_cache[key] = cached
        return cached

    def effective_use_pallas(self) -> bool:
        """Attention implementation selection: with the kernel plane on,
        KernelSettings.attention decides (the tune_tpu.py-driven flip);
        otherwise the legacy ScorerConfig.use_pallas flag stands."""
        if self.kernels.enabled:
            return self.kernels.attention == "flash"
        return bool(self.sc.use_pallas)

    def _record_kernel_dispatch(self, size: int) -> None:
        """Host-side mirror of the per-site kernel engagement for one
        microbatch launch. A site counts as dispatched when its mode asks
        for the Pallas kernel, and as a fallback when the shape/layout
        guard the TRACED code consults (the shared supports() predicates)
        routes it back to the XLA path — so ``kernel_fallback_total``
        reports exactly what the compiled program did, without a device
        readback."""
        if not self.kernels.enabled:
            return
        from realtime_fraud_detection_tpu.models.quant import (
            is_quantized_bert,
        )
        from realtime_fraud_detection_tpu.ops import (
            epilogue_supported,
            matmul_supported,
            mega_launch_accounting,
            rows_supported,
        )

        modes = self.kernels.site_modes()
        disp, fall = (self._kernel_counts["dispatch"],
                      self._kernel_counts["fallback"])
        if modes.get("megakernel") == "pallas":
            # the persistent whole-batch program (ops/megakernel.py). When
            # its shared shape plan admits the dispatch, ONE program runs
            # and the per-site kernels below never launch — so their
            # counters stay untouched (the megakernel subsumes them, it
            # does not fall back from them). A declined plan counts as a
            # megakernel fallback AND the per-site chain is accounted as
            # usual, because that is exactly what the traced guard runs.
            disp["megakernel"] += 1
            if self._mega_plan(size)["supported"]:
                self._last_launches_per_batch = 1
                return
            fall["megakernel"] += 1
        self._last_launches_per_batch = mega_launch_accounting(
            size, NUM_MODELS,
            mega_valid=tuple(bool(v) for v in self.effective_model_valid()),
        )["launches_per_batch_chain"]
        h = self.bert_config.hidden_size
        ffn = self.bert_config.intermediate_size
        s = self.sc.text_len
        m = size * s
        if modes["dequant_matmul"] == "pallas":
            disp["dequant_matmul"] += 1
            # f32 params have no int8 site to fuse — structurally the XLA
            # path, counted as a fallback like any other guard miss
            ok = (is_quantized_bert(self.models.bert)
                  and matmul_supported(m, h, h)
                  and matmul_supported(m, h, ffn)
                  and matmul_supported(m, ffn, h)
                  and rows_supported(m, h) and rows_supported(s, h))
            if not ok:
                fall["dequant_matmul"] += 1
        if modes["epilogue"] == "pallas":
            disp["epilogue"] += 1
            if not epilogue_supported(size, NUM_MODELS):
                fall["epilogue"] += 1
        if modes["attention"] == "flash":
            disp["attention"] += 1
            if s % min(128, s):
                fall["attention"] += 1

    def _mega_plan(self, size: int) -> Dict[str, Any]:
        """Host mirror of the trace-time megakernel shape plan for a
        ``size``-row microbatch — the SAME ``mega_plan`` the traced
        dispatch consults, so ``kernel_fallback_total{site="megakernel"}``
        equals the compiled program's actual fallback behaviour."""
        from realtime_fraud_detection_tpu.ops import mega_plan

        return mega_plan(
            self.models, self.bert_config, b=size,
            text_len=self.sc.text_len, seq_len=self.sc.seq_len,
            feature_dim=self.sc.feature_dim,
            has_two_hop=self._sampler is not None,
        )

    def kernel_snapshot(self) -> Dict[str, Any]:
        """Kernel-plane observability payload (obs.metrics.sync_kernels):
        effective per-site modes, whether the Pallas interpreter is
        serving (non-TPU hosts), cumulative dispatch/fallback counts per
        site, and the launch count of the most recent microbatch (1 when
        the megakernel served it; the per-site chain length otherwise)."""
        return {
            "modes": self.kernels.site_modes(),
            "interpret": bool(self.kernels.enabled
                              and self._kernel_interpret),
            "dispatch": dict(self._kernel_counts["dispatch"]),
            "fallback": dict(self._kernel_counts["fallback"]),
            "launches_per_batch": self._last_launches_per_batch,
        }

    # ---------------------------------------------------------------- assembly
    def assemble(self, records: Sequence[Mapping[str, Any]],
                 now: Optional[float] = None) -> ScoreBatch:
        """Join state + encode one dense ScoreBatch (host side of the seam).

        Columnar: profile/velocity joins gather through the generation-
        stamped entity row cache (features/schema.EntityRowCache), entity
        indices resolve in one batched lookup, history gathers from the
        slot-table ring store, and repeated merchant texts hit the token
        LRU — the per-record Python work shrinks to the transaction-core
        fields. Bit-identical to ``assemble_serial`` (the record-at-a-time
        reference path) by construction and by test.
        """
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        t0 = time.perf_counter()
        user_ids = [str(r.get("user_id", "")) for r in records]
        merchant_ids = [str(r.get("merchant_id", "")) for r in records]
        uprofs = {u: p for u in user_ids
                  if (p := self.profiles.get_user(u)) is not None}
        mprofs = {m: p for m in merchant_ids
                  if (p := self.profiles.get_merchant(m)) is not None}
        velocities = {u: self.velocity.get_all(u, now) for u in set(user_ids)}

        from realtime_fraud_detection_tpu.features.schema import (
            encode_transactions_columnar,
        )

        self._join_cache.sync(self.profiles)
        txn = encode_transactions_columnar(records, uprofs, mprofs,
                                           velocities,
                                           cache=self._join_cache)

        # feature history for the LSTM branch: append-then-gather semantics.
        # Extraction runs on the HOST backend: the rows are needed host-side
        # regardless, and a device round trip here costs a tunnel RTT per
        # microbatch (see extract_features_host).
        from realtime_fraud_detection_tpu.features.extract import (
            extract_features_host,
        )
        feats = extract_features_host(txn)
        self.last_features = feats  # host copy for feature-topic fan-out
        history, history_len = self.history.append_and_gather(user_ids, feats)

        # entity graph for the GNN branch (ONE seam for both assemble paths)
        u_idx = self._users.lookup_batch(user_ids, uprofs, False)
        m_idx = self._merchants.lookup_batch(merchant_ids, mprofs, True)
        graph_t = self._graph_join(user_ids, merchant_ids, u_idx, m_idx)

        token_ids, token_mask = self.tokenizer.encode_batch(
            self._texts_for(records, merchant_ids, mprofs))

        batch = ScoreBatch(
            txn=txn,
            features=feats,
            history=history,
            history_len=history_len,
            token_ids=token_ids.astype(np.int32),
            token_mask=token_mask.astype(bool),
            valid=np.ones((len(records),), bool),
            **graph_t,
        )
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        self.spans.record("assemble", time.perf_counter() - t0)
        return batch

    def _graph_join(self, user_ids: Sequence[str],
                    merchant_ids: Sequence[str],
                    u_idx: np.ndarray, m_idx: np.ndarray,
                    ) -> Dict[str, np.ndarray]:
        """The GNN branch's graph tensors — the ONE seam both assemble
        paths (columnar and record-at-a-time serial) call, so graph-on
        can never diverge columnar-vs-serial (edge maintenance used to
        live in two hand-kept copies).

        Bipartite mode keeps the historical sample-then-insert order:
        this batch's neighborhoods see only earlier batches' edges, then
        the batch's own edges are committed for the NEXT batch. Typed
        mode samples here too, but commits edges at FINALIZE time
        (``_write_back`` → ``TypedEntityGraph.add_batch``): the typed
        store lives in the partition bundle, and write-back is where
        every other partition-owned store mutates."""
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        t0 = time.perf_counter()
        utable, mtable = self._users.table(), self._merchants.table()
        out: Dict[str, np.ndarray] = {
            "user_feat": utable[u_idx],
            "merchant_feat": mtable[m_idx],
        }
        if self._sampler is not None:
            out.update(self._sampler.sample(user_ids, merchant_ids))
        else:
            un_idx, un_mask = self.graph.user_neighbors(u_idx)
            mn_idx, mn_mask = self.graph.merchant_neighbors(m_idx)
            out.update(
                user_neigh_feat=mtable[np.where(un_mask, un_idx, 0)],
                user_neigh_mask=un_mask,
                merch_neigh_feat=utable[np.where(mn_mask, mn_idx, 0)],
                merch_neigh_mask=mn_mask,
            )
            self.graph.add_edges(u_idx, m_idx)
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        self.spans.record("graph", time.perf_counter() - t0)
        return out

    def _texts_for(self, records, merchant_ids, mprofs) -> List[str]:
        """Combined text per record for the text branch (models/text.py)."""
        texts = []
        for r, m in zip(records, merchant_ids):
            mp = mprofs.get(m) or {}
            texts.append(combined_text({
                "merchant_name": mp.get("name") or str(r.get("merchant_name", "")),
                "description": str(r.get("description", "") or ""),
                "category": str(mp.get("category", "") or ""),
                "location": str(r.get("location", "") or ""),
            }))
        return texts

    def assemble_serial(self, records: Sequence[Mapping[str, Any]],
                        now: Optional[float] = None) -> ScoreBatch:
        """Record-at-a-time reference assembly: the pre-columnar baseline.

        Every record runs the full join/encode/tokenize path alone (one
        1-row encode, one 1-row feature extraction, one history append, one
        tokenize) and the rows are stacked at the end — exactly the cost
        profile of the reference's per-request serving loop
        (main.py:235-248). Kept as the equivalence oracle for the columnar
        path and as the baseline the bench's host-assembly stage measures
        against. The one batch-level carve-out: graph neighbor sampling for
        ALL records precedes this batch's edge inserts, matching the batch
        path's sample-then-insert order (per-record interleaving would make
        row i+1 see row i's edge — a different, order-dependent batch).
        """
        from realtime_fraud_detection_tpu.features.extract import (
            extract_features_host,
        )

        n = len(records)
        user_ids = [str(r.get("user_id", "")) for r in records]
        merchant_ids = [str(r.get("merchant_id", "")) for r in records]
        txns: List[Any] = []
        feat_rows: List[np.ndarray] = []
        hist_rows: List[np.ndarray] = []
        hist_lens: List[np.ndarray] = []
        tok_rows: List[np.ndarray] = []
        tok_masks: List[np.ndarray] = []
        u_idx = np.empty((n,), np.int64)
        m_idx = np.empty((n,), np.int64)
        mprofs: Dict[str, Any] = {}
        for i, (r, uid, mid) in enumerate(zip(records, user_ids,
                                              merchant_ids)):
            up = self.profiles.get_user(uid)
            mp = self.profiles.get_merchant(mid)
            if mp is not None:
                mprofs[mid] = mp
            txn = encode_transactions(
                [r],
                {uid: up} if up is not None else {},
                {mid: mp} if mp is not None else {},
                {uid: self.velocity.get_all(uid, now)})
            feats = extract_features_host(txn)
            hist, hlen = self.history.append_and_gather([uid], feats)
            u_idx[i] = self._users.lookup(uid, up, False)
            m_idx[i] = self._merchants.lookup(mid, mp, True)
            ids, mask = self.tokenizer.encode_batch(
                self._texts_for([r], [mid], mprofs))
            txns.append(txn)
            feat_rows.append(feats)
            hist_rows.append(hist)
            hist_lens.append(hlen)
            tok_rows.append(ids)
            tok_masks.append(mask)

        graph_t = self._graph_join(user_ids, merchant_ids, u_idx, m_idx)

        txn_all = jax.tree_util.tree_map(
            lambda *leaves: np.concatenate([np.asarray(lf) for lf in leaves],
                                           axis=0), *txns)
        feats = np.concatenate(feat_rows, axis=0)
        self.last_features = feats
        return ScoreBatch(
            txn=txn_all,
            features=feats,
            history=np.concatenate(hist_rows, axis=0),
            history_len=np.concatenate(hist_lens, axis=0),
            token_ids=np.concatenate(tok_rows, axis=0).astype(np.int32),
            token_mask=np.concatenate(tok_masks, axis=0).astype(bool),
            valid=np.ones((n,), bool),
            **graph_t,
        )

    def host_stats(self) -> Dict[str, Any]:
        """Host-assembly observability payload: per-stage span stats
        (assemble/pack/dispatch/device_wait) and cache hit/miss counters —
        the source obs/metrics.MetricsCollector.sync_host_stats exports as
        Prometheus series."""
        caches: Dict[str, Any] = {"entity_rows": self._join_cache.stats()}
        cache_stats = getattr(self.tokenizer, "cache_stats", None)
        if cache_stats is not None:
            caches["tokens"] = cache_stats()
        return {"stages": self.spans.stats(), "caches": caches}

    # ----------------------------------------------------------------- scoring
    def dispatch(self, records: Sequence[Mapping[str, Any]],
                 now: Optional[float] = None,
                 trace: Optional[Any] = None) -> "PendingScore":
        """Assemble + launch the fused device program WITHOUT blocking.

        JAX dispatch is async: the returned ``PendingScore`` holds device
        arrays still being computed, so the caller can assemble/dispatch the
        next microbatch (or do fan-out work) while the TPU runs this one.
        ``finalize`` blocks, builds §2.7 responses, and write-backs state.
        This is the in-path version of stream/microbatch.DoubleBufferedScorer
        — host→device pipelining, the reference operator pipeline's analog
        (SURVEY.md §2.8).

        ``trace`` (an obs.tracing.TraceBatch) collects batch-granular
        stage marks; None — the default — costs one branch per stage.
        """
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        t0 = time.perf_counter()
        n = len(records)
        if n == 0:
            return PendingScore(records=[], n=0, out=None,
                                features=self.last_features[:0],
                                dispatch_ms=0.0)
        if trace is not None:
            trace.mark("assemble")
        batch = self.assemble(records, now)
        return self.dispatch_assembled(batch, records, t0=t0, trace=trace)

    def dispatch_assembled(self, batch: ScoreBatch,
                           records: Sequence[Mapping[str, Any]],
                           t0: Optional[float] = None,
                           trace: Optional[Any] = None) -> "PendingScore":
        """Pad + pack + launch an already-assembled batch (the device half
        of ``dispatch``). Split out so the overlapped assembler stage
        (scoring/host_pipeline.py) can run ``assemble`` on its own thread
        and hand the result here."""
        if t0 is None:
            # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
            t0 = time.perf_counter()
        if trace is not None:
            trace.mark("pack")
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        t_pack = time.perf_counter()
        n = len(records)
        # an attached mesh executor (scoring/mesh_executor.py) shards the
        # batch over ITS data axis, which may differ from this scorer's
        # own mesh (e.g. a 1-device reference scorer driving a 4x2
        # executor) — pad to whichever seam the batch will actually cross
        multiple = (getattr(self._pool, "batch_multiple", None)
                    or local_mesh_size(self.mesh))
        size = bucket_for(n, BATCH_BUCKETS, multiple_of=multiple)
        # write-into staging: pad rows replicate row 0, the real validity
        # is the staging mask (same contract as pad_to_bucket)
        padded, mask = self._staging.pad(batch, n, size)
        padded = padded.replace(valid=mask)
        # Transfer-optimal seam (core/packing.py): the 65-leaf ScoreBatch
        # collapses to 3 dense blobs (one h2d payload), the program returns
        # ONE f32 matrix (one d2h payload) — on a remote TPU the hot loop
        # pays transport round trips, not FLOPs, so the transfer count is
        # the latency budget.
        if self.sc.transfer_bf16:
            padded = _stage_bf16(padded)
        blobs, spec = pack_tree(padded)
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        self.spans.record("pack", time.perf_counter() - t_pack)
        if trace is not None:
            trace.mark("dispatch")
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        t_disp = time.perf_counter()

        mv = self.effective_model_valid()
        rules_only = self._qos_rules_only
        self._record_kernel_dispatch(size)
        token = None
        if self._pool is not None:
            # pooled mode: the whole microbatch runs on ONE replica (model
            # replication, not batch sharding) picked round-robin by the
            # pool; in-flight depth and retry live there
            token = self._pool.dispatch_packed(
                blobs, spec, self.ensemble_params, mv)
            out = token.out
            if trace is not None:
                # which replica got the batch, and how deep its queue was
                # at dispatch — the tail-attribution metadata the ISSUE's
                # "where did the p99 go" question needs
                trace.annotate(replica=token.replica_idx,
                               inflight_depth=token.inflight_at_dispatch)
        else:
            sharded = shard_batch(self.mesh, blobs)
            out = score_fused_packed(
                self.models, sharded["f32"], sharded["i32"], sharded["u8"],
                spec=spec, params=self.ensemble_params,
                model_valid=self._model_valid_dev(mv),
                blob_bf16=sharded["bf16"],
                bert_config=self.bert_config,
                use_pallas=self.effective_use_pallas(),
                **self.quant_static(), **self.kernel_static(mv),
            )
        # Start the device->host copy NOW (it queues behind the compute):
        # by the time finalize() calls device_get, the transfer is already
        # in flight or done, so the d2h RTT overlaps the next batch's
        # assemble instead of serializing after it.
        if self.sc.async_d2h:
            try:
                out.copy_to_host_async()
            except AttributeError:  # backend without async copy support
                pass
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        self.spans.record("dispatch", time.perf_counter() - t_disp)
        if trace is not None:
            # launch returned: from the transaction's point of view the
            # device residency (compute + any pipeline dwell) starts here
            trace.mark("device_wait")
        return PendingScore(records=list(records), n=n, out=out,
                            # rtfd-lint: allow[d2h] batch.features is a host-assembled ndarray
                            features=np.asarray(batch.features),
                            # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
                            dispatch_ms=(time.perf_counter() - t0) * 1000.0,
                            model_valid=mv, rules_only=rules_only,
                            pool_token=token, trace=trace)

    def finalize(self, pending: "PendingScore", now: Optional[float] = None,
                 lock=None) -> List[Dict[str, Any]]:
        """Block on a dispatched batch, build responses, write back state.

        ``lock`` (optional) is held only around the state write-back, not
        the device wait — a concurrent caller can assemble/dispatch the next
        batch while this one's device result is still in flight.
        """
        import contextlib

        if pending.n == 0:
            return []
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        t_fin = time.perf_counter()
        if pending.pool_token is not None:
            # pooled completion: DevicePool.wait retries the batch on a
            # healthy replica if this one's result fetch fails
            out = self._pool.wait(pending.pool_token)
        else:
            out = jax.device_get(pending.out)  # blocks until device is done
        # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
        self.spans.record("device_wait", time.perf_counter() - t_fin)
        if pending.trace is not None:
            # result in hand: everything after this mark (response build,
            # state write-back, the owner's fan-out) is the finalize stage
            pending.trace.mark("finalize")
        # processing time = assemble/dispatch + device wait; excludes any
        # pipeline queue wait between dispatch() returning and this call
        elapsed_ms = (pending.dispatch_ms
                      # rtfd-lint: allow[wall-clock] span diagnostics (host_stats), not scoring control flow
                      + (time.perf_counter() - t_fin) * 1000.0)
        results = self._build_responses(pending.records, out, pending.n,
                                        elapsed_ms,
                                        model_valid=pending.model_valid,
                                        rules_only=pending.rules_only)
        with (lock if lock is not None else contextlib.nullcontext()):
            self._write_back(pending.records, results, now)
            self.stats["scored"] += pending.n
            self.stats["batches"] += 1
            self.stats["total_time_s"] += elapsed_ms / 1000.0
        return results

    def score_batch(self, records: Sequence[Mapping[str, Any]],
                    now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Score transaction dicts -> FraudPrediction dicts (§2.7 schema)."""
        return self.finalize(self.dispatch(records, now), now)

    def _build_responses(self, records, out, n, elapsed_ms,
                         model_valid=None,
                         rules_only=False) -> List[Dict[str, Any]]:
        # ``out`` is the packed f32[B, 8+M] matrix from score_fused_packed:
        # OUT_COLUMNS then per-model predictions (one d2h transfer's worth).
        if model_valid is None:
            model_valid = self.model_valid
        mat = np.asarray(out)[:n]
        col = {name: mat[:, j] for j, name in enumerate(OUT_COLUMNS)}
        probs = col["fraud_probability"]
        conf = col["confidence"]
        decisions = col["decision"].astype(np.int32)
        risk = col["risk_level"].astype(np.int32)
        base_w = len(OUT_COLUMNS) + NUM_MODELS
        # fused-epilogue extension (pipeline.EXT_COLUMNS, detected by
        # width): the device already computed the explanation
        # contributions and the rules-only ladder — finalize reads the
        # columns instead of re-deriving them per record
        extended = mat.shape[1] >= base_w + NUM_MODELS + 2
        preds = mat[:, len(OUT_COLUMNS):base_w]
        contrib_cols = mat[:, base_w:base_w + NUM_MODELS] if extended else None
        rule = col["rule_score"]
        if rules_only and extended:
            probs = rule
            conf = np.ones_like(probs)
            decisions = mat[:, base_w + NUM_MODELS].astype(np.int32)
            risk = mat[:, base_w + NUM_MODELS + 1].astype(np.int32)
        elif rules_only:
            # the ladder's last rung: no learned branch survives; serve the
            # rule score with the decision/risk ladders recomputed host-side
            # (the device combine saw zero valid branches). Confidence is
            # 1.0 — the rule ladder is deterministic, and anything under
            # the confidence threshold would force every decision to REVIEW.
            from realtime_fraud_detection_tpu.features.rules import (
                APPROVE,
                APPROVE_WITH_MONITORING,
                DECLINE,
                REVIEW,
                risk_level_codes_np,
            )

            p = self.ensemble_params
            probs = rule
            conf = np.ones_like(probs)
            decisions = np.where(
                probs >= p.decline_threshold, DECLINE,
                np.where(probs >= p.review_threshold, REVIEW,
                         np.where(probs >= p.monitor_threshold,
                                  APPROVE_WITH_MONITORING,
                                  APPROVE))).astype(np.int32)
            risk = risk_level_codes_np(probs)
        high_amount = col["high_amount"] > 0.5
        unusual_hour = col["unusual_hour"] > 0.5
        high_risk_payment = col["high_risk_payment"] > 0.5
        per_txn_ms = elapsed_ms / max(n, 1)

        results = []
        weights = np.asarray(self.ensemble_params.weights)
        with_explanation = self.config.ensemble.enable_explanation
        for i, rec in enumerate(records):
            model_predictions = {
                name: float(preds[i, j])
                for j, name in enumerate(MODEL_NAMES) if model_valid[j]
            }
            if with_explanation:
                factors = []
                if high_amount[i]:
                    factors.append("high_transaction_amount")
                if unusual_hour[i]:
                    factors.append("unusual_transaction_hour")
                if high_risk_payment[i]:
                    factors.append("high_risk_payment_method")
                if contrib_cols is not None:
                    # device-computed (ops/epilogue.py), bit-equal to the
                    # single host f32 product it replaces
                    contributions = {
                        name: float(contrib_cols[i, j])
                        for j, name in enumerate(MODEL_NAMES)
                        if model_valid[j]
                    }
                else:
                    contributions = {
                        name: float(weights[j] * preds[i, j])
                        for j, name in enumerate(MODEL_NAMES)
                        if model_valid[j]
                    }
                explanation = {
                    "model_contributions": contributions,
                    "key_factors": factors,
                    "rule_score": float(rule[i]),
                }
                if rules_only:
                    explanation["degraded"] = "rules_only"
                if self._top_importances is not None:
                    # fresh dict per response: a consumer mutating one
                    # explanation must not corrupt its batch-mates
                    explanation["top_feature_importances"] = dict(
                        self._top_importances)
            else:
                # ensemble.enable_explanation=False (reference config.py:85
                # analog): schema keeps the key, host skips the per-record
                # dict assembly
                explanation = {}
            results.append({
                "transaction_id": str(rec.get("transaction_id", "")),
                "fraud_probability": float(probs[i]),
                "fraud_score": float(probs[i]),
                "risk_level": RISK_LEVEL_NAMES[int(risk[i])],
                "decision": DECISIONS[int(decisions[i])],
                "model_predictions": model_predictions,
                "confidence": float(conf[i]),
                "processing_time_ms": per_txn_ms,
                "explanation": explanation,
            })
        return results

    def replay_state(self, records: Sequence[Mapping[str, Any]],
                     now: Optional[float] = None) -> None:
        """State-only replay for the partition-handoff path
        (cluster/fleet.ClusterWorker): re-apply the state updates of
        records that were ALREADY scored, emitted, and committed by a
        worker that died after its last partition snapshot — without
        re-scoring on device or re-emitting anything.

        ``assemble`` reconstructs the history rings + profile/velocity
        read path exactly as the dead worker's scoring pass did; the
        write-back caches each transaction with an explicit marker
        result (the dead worker's served score is unknowable host-side —
        unlike the shard drill's deterministic stand-in — so a later
        duplicate re-emits a REVIEW marker rather than inventing a
        score). Effectively-once scoring and dedupe are preserved; the
        marker is honest about what was lost."""
        if not records:
            return
        self.assemble(records, now=now)
        markers = [{
            "transaction_id": str(r.get("transaction_id", "")),
            "fraud_score": 0.5,
            "decision": "REVIEW",
            "risk_level": "UNKNOWN",
            "confidence": 0.0,
            "explanation": {"replay_restored": True},
        } for r in records]
        self._write_back(records, markers, now)

    def _write_back(self, records, results, now: Optional[float]) -> None:
        """Post-scoring state updates (RedisTransactionSink.java:53-135)."""
        # rtfd-lint: allow[wall-clock] production default time base; virtual-clock callers pass now
        ts = now if now is not None else time.time()
        for rec, res in zip(records, results):
            uid = str(rec.get("user_id", ""))
            self.velocity.update(uid, float(rec.get("amount", 0.0)), ts)
            merged = dict(rec)
            merged["fraud_score"] = res["fraud_score"]
            merged["decision"] = res["decision"]
            # enough for the dedupe path to re-emit a faithful prediction
            # from cache (stream/job.py _emit_cached_dups)
            merged["risk_level"] = res["risk_level"]
            merged["confidence"] = res["confidence"]
            self.txn_cache.cache_transaction(merged, now=ts)
        if self.typed_graph is not None:
            # typed-graph ingest at the finalize seam: the shared
            # device_id/ip_address entity links (the FraudRing signature)
            # flow into per-entity state through ONE path-independent
            # seam — replay_state takes it too, so handoff's committed-
            # gap replay rebuilds the graph exactly like the live pass
            self.typed_graph.add_batch(
                [str(r.get("user_id", "")) for r in records],
                [str(r.get("merchant_id", "")) for r in records],
                [str(r.get("device_id")
                     or r.get("device_fingerprint") or "")
                 for r in records],
                [str(r.get("ip_address") or "") for r in records])
            self._sampler.sync()

    def close(self) -> None:
        """Release resources this scorer owns (currently: the state-tier
        connection it auto-created for config.state.backend="redis")."""
        if self._owned_state_client is not None:
            try:
                self._owned_state_client.close()
            finally:
                self._owned_state_client = None

    # ------------------------------------------------------------------ info
    def model_info(self) -> Dict[str, Any]:
        norm = self.config.normalized_weights()
        return {
            "models": {
                name: {
                    "enabled": bool(self.model_valid[j]),
                    "weight": float(norm.get(name, 0.0)),
                }
                for j, name in enumerate(MODEL_NAMES)
            },
            "strategy": self.config.ensemble.strategy,
            "num_models": NUM_MODELS,
            "mesh": dict(self.mesh.shape),
        }

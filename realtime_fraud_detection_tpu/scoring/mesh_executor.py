"""GSPMD mesh-sharded scoring: ``data x model`` sharding behind the pool seam.

``DevicePool`` (scoring/device_pool.py) replicates FULL params onto every
chip: model size is capped by one chip's HBM and the only parallelism is
whole-microbatch replication. This module is the next unlock the ROADMAP
names — jit + ``NamedSharding`` over the named 2-D ``data x model`` mesh
(core/mesh.py), per the production pattern in "Scaling TensorFlow to 300M
predictions/sec" (arXiv:2109.09541): the microbatch shards over ``data``
(every chip computes B/data rows — the FLOPs lever) while selected branch
params shard over ``model`` (every chip stores 1/model of the branch — the
HBM lever), with trees/iforest/rules always replicated.

Numerics contract — why storage sharding, not Megatron compute sharding:
serving scores must be BIT-IDENTICAL to single-device scoring
(``rtfd mesh-drill`` pins it, like pool-drill before it). Megatron-style
row-parallel blocks end in partial-sum all-reduces that reorder float
additions — fine for training (the dryrun gates TP at rtol 2e-4), fatal
for a bit-replayable serving plane. So a "sharded" branch here stores its
params split over ``model`` and the fused program re-gathers them at the
use seam (``_regather_models`` — ZeRO-3/FSDP semantics): the all-gather
reconstructs exact bytes, the branch computes replicated per model shard,
and activations stay sharded over ``data`` only. Per-chip param bytes at
rest shrink ~1/model_axis; XLA frees the gathered temporaries after each
branch's last use, so transient peak is one branch, not the model. The
Megatron column/row STORAGE positions are kept (parallel/layouts.py
serving specs) so a later flip to true compute sharding is a gather
removal, not a re-layout.

One honest boundary on the bit-equality claim: the gather makes the
PARAMS exact, but splitting the batch over ``data`` changes per-shard
matmul tiling, and at micro shapes (observed: bucket 8 over a 4-way data
axis — 2 rows per shard) a backend's small-M kernel can round one row a
single ulp apart from the full-batch path. The contract is therefore
pinned at the SERVED bucket shapes (>= 8 rows per data shard — every
``rtfd mesh-drill`` phase and the production 128/256 buckets qualify),
the same shape-granularity caveat the bucket ladder already owns.

Pool x mesh composition — replicate the MESH, not the chip: the executor
partitions its devices into ``replicas`` equal subsets, builds one
``data x model`` mesh per subset, and round-robins whole microbatches
across mesh replicas with per-replica in-flight depth — exactly
``DevicePool``'s dispatch shape with "device" generalized to "mesh".
``replicas=N, model_axis=1, one device each`` degenerates to the pool's
layout; ``replicas=1`` is a single program spanning every chip. The
executor sits behind the SAME dispatch/finalize seam the pool uses
(``FraudScorer.attach_pool``), so the overlapped assembler, QoS
degradation masks (per-dispatch snapshot of the host mask), tracing
annotations, and hot swap under the score lock all compose unchanged.

Unlike the pool there is NO retry-on-replica-failure rescue: a mesh
replica's batch lives sharded across its whole device subset, and a chip
loss there is a topology event (rebuild the executor over the survivors),
not a relaunch — ``wait`` marks the replica unhealthy, releases the slot,
and raises. The pool remains the fault-absorbing plane.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from realtime_fraud_detection_tpu.scoring.pipeline import (
    MODEL_NAMES,
    _score_fused_packed_impl,
)

__all__ = ["MeshExecutor", "MeshToken", "mesh_score_packed",
           "mesh_score_packed_donated"]


def _regather_models(models, gather_fields: Tuple[str, ...], mesh):
    """Constrain the named ScoringModels fields back to replicated INSIDE
    the jitted program: GSPMD lowers the constraint to an all-gather of
    the stored shards — exact bytes, so the branch that follows computes
    the identical arithmetic to a single-device run. Branches not named
    are already replicated and pass through untouched."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if not gather_fields or mesh is None:
        return models
    rep = NamedSharding(mesh, P())
    gathered = {
        f: jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(x, rep),
            getattr(models, f))
        for f in gather_fields
    }
    return models.replace(**gathered)


def _mesh_score_packed_impl(models, blob_f32, blob_i32, blob_u8, spec,
                            params, model_valid, blob_bf16=None,
                            bert_config=None, use_pallas=False,
                            tree_kernel="gather", iforest_kernel="gather",
                            dequant_kernel="off", epilogue_kernel="off",
                            kernel_interpret=False,
                            megakernel="off", mega_valid=None,
                            gather_fields: Tuple[str, ...] = (),
                            mesh=None):
    models = _regather_models(models, gather_fields, mesh)
    return _score_fused_packed_impl(
        models, blob_f32, blob_i32, blob_u8, spec=spec, params=params,
        model_valid=model_valid, blob_bf16=blob_bf16,
        bert_config=bert_config, use_pallas=use_pallas,
        tree_kernel=tree_kernel, iforest_kernel=iforest_kernel,
        dequant_kernel=dequant_kernel, epilogue_kernel=epilogue_kernel,
        kernel_interpret=kernel_interpret,
        megakernel=megakernel, mega_valid=mega_valid)


def _jit_entries():
    """Build the jitted (and donated) mesh entries lazily so importing
    this module never initializes a JAX backend (the CLI parents stay
    jax-free — the pool-drill wedge-proofing contract)."""
    import jax

    statics = ("spec", "bert_config", "use_pallas", "tree_kernel",
               "iforest_kernel", "dequant_kernel", "epilogue_kernel",
               "kernel_interpret", "megakernel", "mega_valid",
               "gather_fields", "mesh")
    plain = partial(jax.jit, static_argnames=statics)(
        _mesh_score_packed_impl)
    try:
        donated = partial(
            jax.jit, static_argnames=statics,
            donate_argnames=("blob_f32", "blob_i32", "blob_u8",
                             "blob_bf16"),
        )(_mesh_score_packed_impl)
    except TypeError:  # pragma: no cover - older jax without donate_argnames
        donated = plain
    return plain, donated


_ENTRIES: Optional[tuple] = None


def mesh_entry(donate: bool = False):
    """The jitted mesh scoring entry (donated or plain) — the executor
    dispatches through this, and the drill lowers it to verify the
    donation annotations reach the compiler."""
    global _ENTRIES
    if _ENTRIES is None:
        _ENTRIES = _jit_entries()
    return _ENTRIES[1 if donate else 0]


def mesh_score_packed(*args, **kwargs):
    return mesh_entry(False)(*args, **kwargs)


def mesh_score_packed_donated(*args, **kwargs):
    return mesh_entry(True)(*args, **kwargs)


class MeshToken:
    """One in-flight mesh-dispatched microbatch. Field names mirror
    ``PoolToken`` so the scorer's tracing annotations (replica id,
    in-flight depth at dispatch) read either token unchanged."""

    __slots__ = ("out", "replica_idx", "t_dispatch", "inflight_at_dispatch",
                 "staged")

    def __init__(self, out, replica_idx, t_dispatch,
                 inflight_at_dispatch=0, staged=None):
        self.out = out
        self.replica_idx = replica_idx
        self.t_dispatch = t_dispatch
        self.inflight_at_dispatch = inflight_at_dispatch
        # the device-side staged blobs — with donation on, runtimes that
        # honor it (accelerators; CPU only when the aliasing is strict)
        # consume these at launch, which is exactly why the executor never
        # reads them back (the host blobs stay the caller's)
        self.staged = staged


class _MeshReplica:
    """One ``data x model`` sub-mesh: committed sharded params + dispatch
    bookkeeping (the ``_Replica`` analog with "device" -> "mesh")."""

    def __init__(self, idx: int, mesh, models, shardings,
                 multihost: bool = False):
        import jax

        self.idx = idx
        self.mesh = mesh
        self.shardings = shardings           # NamedSharding tree (storage)
        if multihost:
            # a spanning mesh: every process holds the identical host
            # value (deterministic init / checkpoint), each commits only
            # the shards its chips own — no cross-host param bytes move
            from realtime_fraud_detection_tpu.core.mesh import (
                make_global_batch,
            )

            self.models = make_global_batch(mesh, models, shardings)
        else:
            self.models = jax.device_put(models, shardings)
        self.healthy = True
        self.inflight = 0
        self.dispatched = 0
        self.completed = 0
        self.failures = 0
        self.queue_wait_s = 0.0
        self._mv_cache: Optional[tuple] = None

    def mv_dev(self, mv: np.ndarray):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        cached = self._mv_cache
        if cached is None or not np.array_equal(cached[0], mv):
            self._mv_cache = (
                mv.copy(),
                jax.device_put(mv, NamedSharding(self.mesh, P())))
        return self._mv_cache[1]


class MeshExecutor:
    """Mesh-sharded microbatch executor behind the pool dispatch seam.

    ``devices`` split into ``replicas`` equal subsets; each subset becomes
    a ``(data=per/model_axis) x model_axis`` mesh holding one copy of the
    params, placed per branch (``shard_branches`` store sharded over
    ``model``; the rest replicate). Dispatch is strict round-robin across
    healthy mesh replicas with ``inflight_depth`` programs riding each —
    deterministic for the drill, exactly the pool's discipline.
    """

    def __init__(self, scorer, devices: Optional[Sequence] = None,
                 model_axis: int = 1, replicas: int = 1,
                 inflight_depth: int = 2, donate: Optional[bool] = None,
                 shard_branches: Sequence[str] = ("bert_text",),
                 mesh=None):
        import jax

        from realtime_fraud_detection_tpu.core.mesh import (
            DATA_AXIS,
            MODEL_AXIS,
            MeshConfig,
            build_mesh,
        )
        from realtime_fraud_detection_tpu.parallel.layouts import (
            SHARDABLE_BRANCHES,
            branch_serving_specs,
            tree_specs_to_shardings,
        )

        if mesh is not None:
            # pre-built mesh — the multihost serving mode: the caller
            # constructed it over jax.distributed's global device set
            # (core.mesh.build_multihost_mesh, process-major data axis so
            # model-axis collectives stay on ICI) and this executor is one
            # per-process participant of a single spanning program
            if replicas != 1 or devices is not None:
                raise ValueError(
                    "pass either a pre-built mesh= (one spanning replica) "
                    "or devices/replicas, not both")
            devs = list(mesh.devices.flat)
            model_axis = int(mesh.shape[MODEL_AXIS])
            per = len(devs)
        else:
            devs = (list(devices) if devices is not None
                    else list(jax.devices()))
            if not devs:
                raise ValueError("mesh executor needs at least one device")
            replicas = max(1, int(replicas))
            if len(devs) % replicas:
                raise ValueError(
                    f"{len(devs)} devices do not split into {replicas} "
                    f"equal mesh replicas")
            per = len(devs) // replicas
            model_axis = max(1, int(model_axis))
            if per % model_axis:
                raise ValueError(
                    f"model_axis={model_axis} does not divide the {per} "
                    f"devices of each mesh replica")
        self.scorer = scorer
        self.model_axis = model_axis
        self.data_axis = (int(mesh.shape[DATA_AXIS]) if mesh is not None
                          else per // model_axis)
        # >1 process = the spanning program's inputs/outputs are only
        # partially addressable here: staging goes through
        # make_global_batch and wait() returns THIS host's rows
        self.multihost = len({d.process_index for d in devs}) > 1
        # the scorer pads every microbatch to a multiple of this so the
        # data-axis split is always even (FraudScorer.dispatch_assembled)
        self.batch_multiple = self.data_axis
        self.inflight_depth = max(1, int(inflight_depth))
        # donation needs accelerator buffer aliasing; the CPU backend only
        # warns and ignores it (same default rule as DevicePool)
        self.donate = (devs[0].platform != "cpu" if donate is None
                       else bool(donate))
        # effective placement: requested branches that exist AND an axis to
        # shard over; with model_axis=1 everything is replicated and the
        # gather seam compiles away entirely
        bad = [b for b in shard_branches if b not in SHARDABLE_BRANCHES]
        if bad:
            raise ValueError(
                f"branch(es) {bad} not shardable; expected a subset of "
                f"{sorted(SHARDABLE_BRANCHES)} (trees/iforest/rules are "
                f"replicated by design)")
        self.shard_branches: Tuple[str, ...] = tuple(
            sorted(b for b in shard_branches)) if model_axis > 1 else ()
        self._gather_fields: Tuple[str, ...] = tuple(
            sorted(SHARDABLE_BRANCHES[b] for b in self.shard_branches))
        self._cv = threading.Condition()
        self.replicas: List[_MeshReplica] = []
        for i in range(replicas):
            if mesh is not None:
                rep_mesh = mesh
            else:
                sub = devs[i * per:(i + 1) * per]
                rep_mesh = build_mesh(MeshConfig(model=model_axis), sub)
            specs = branch_serving_specs(scorer.models, model_axis,
                                         self.shard_branches)
            self.replicas.append(_MeshReplica(
                i, rep_mesh, scorer.models,
                tree_specs_to_shardings(rep_mesh, specs),
                multihost=self.multihost))
        self._rr = 0
        self.assignment_log: deque = deque(maxlen=4096)
        scorer.attach_pool(self)

    # ------------------------------------------------------------- capacity
    def __len__(self) -> int:
        return len(self.replicas)

    @property
    def healthy_count(self) -> int:
        return sum(1 for r in self.replicas if r.healthy)

    def total_slots(self) -> int:
        return max(1, self.healthy_count * self.inflight_depth)

    # ------------------------------------------------------------- dispatch
    def _pick_replica(self) -> tuple:
        """Strict round-robin over healthy mesh replicas, blocking at
        depth — the same deterministic discipline as DevicePool (the
        drill replays the assignment log)."""
        with self._cv:
            n = len(self.replicas)
            for off in range(n):
                rep = self.replicas[(self._rr + off) % n]
                if rep.healthy:
                    self._rr = (self._rr + off + 1) % n
                    break
            else:
                raise RuntimeError("mesh executor has no healthy replicas")
            # rtfd-lint: allow[wall-clock] queue-wait diagnostics (host stats), not control flow
            t0 = time.perf_counter()
            while rep.inflight >= self.inflight_depth:
                if not self._cv.wait(timeout=120.0):
                    raise TimeoutError(
                        f"mesh replica {rep.idx} stuck at inflight depth "
                        f"{rep.inflight} for 120s")
                if not rep.healthy:
                    return self._pick_replica()
            # rtfd-lint: allow[wall-clock] queue-wait diagnostics (host stats), not control flow
            rep.queue_wait_s += time.perf_counter() - t0
            rep.inflight += 1
            rep.dispatched += 1
            self.assignment_log.append(rep.idx)
            return rep, rep.inflight

    def _stage(self, rep: _MeshReplica, blobs: Dict[str, np.ndarray]):
        """Device-put the packed blobs sharded over the replica's data
        axis (batch sizes arrive pre-padded to ``batch_multiple``). On a
        multihost mesh each process feeds only the row span its chips own
        (process-major data axis — the per-TM Kafka-partition analog):
        hosts never exchange batch bytes."""
        import jax

        from realtime_fraud_detection_tpu.core.mesh import (
            batch_sharding,
            make_global_batch,
        )

        if not self.multihost:
            return {
                k: jax.device_put(
                    v, batch_sharding(rep.mesh, np.ndim(v) - 1))
                for k, v in blobs.items() if v is not None
            }
        nproc = jax.process_count()
        pid = jax.process_index()
        staged = {}
        for k, v in blobs.items():
            if v is None:
                continue
            rows = v.shape[0] // nproc
            local = v[pid * rows:(pid + 1) * rows]
            staged[k] = make_global_batch(
                rep.mesh, local, batch_sharding(rep.mesh, np.ndim(v) - 1))
        return staged

    def dispatch_packed(self, blobs: Dict[str, np.ndarray], spec, params,
                        model_valid: np.ndarray) -> MeshToken:
        """Stage + launch one packed microbatch on the next mesh replica.
        Non-blocking (JAX async dispatch) except for the depth
        backpressure, which is recorded as queue wait."""
        rep, depth = self._pick_replica()
        # rtfd-lint: allow[d2h] host bool[M] validity mask, never a device array
        mv = np.asarray(model_valid)
        try:
            staged = self._stage(rep, blobs)
            with self._cv:
                models = rep.models      # snapshot: hot swap never tears it
                mv_dev = rep.mv_dev(mv)
            fn = (mesh_score_packed_donated if self.donate
                  else mesh_score_packed)
            out = fn(models, staged["f32"], staged["i32"], staged["u8"],
                     spec=spec, params=params, model_valid=mv_dev,
                     blob_bf16=staged.get("bf16"),
                     bert_config=self.scorer.bert_config,
                     use_pallas=self.scorer.effective_use_pallas(),
                     gather_fields=self._gather_fields,
                     mesh=rep.mesh,
                     # quant + kernel planes: same static kernel selection
                     # on every mesh replica (params are already quantized,
                     # so the sharded storage carries the int8 form for
                     # free, and no batch ever mixes kernel modes). The
                     # dispatch-time rung rides in model_valid so the
                     # megakernel program matches the mask it serves.
                     **self.scorer.quant_static(),
                     **self.scorer.kernel_static(mv))
        except Exception:
            self._mark_failed(rep)
            raise
        return MeshToken(out, rep.idx,
                         # rtfd-lint: allow[wall-clock] dispatch-time diagnostics (host stats), not control flow
                         time.perf_counter(),
                         inflight_at_dispatch=depth, staged=staged)

    # ------------------------------------------------------------ completion
    def _mark_failed(self, rep: _MeshReplica) -> None:
        with self._cv:
            rep.failures += 1
            rep.healthy = False
            rep.inflight = max(0, rep.inflight - 1)
            self._cv.notify_all()

    def _release(self, rep: _MeshReplica) -> None:
        with self._cv:
            rep.inflight = max(0, rep.inflight - 1)
            rep.completed += 1
            self._cv.notify_all()

    def wait(self, token: MeshToken) -> np.ndarray:
        """Block on a mesh batch's result. A fetch failure marks the
        replica unhealthy, releases its slot and RAISES — a sharded
        program has no single-chip rescue copy (see module docstring);
        the caller's degradation path owns what happens next.

        Multihost: only this host's shards are addressable, so the
        return is THIS process's row span (in row order) — each host
        fans out the rows it fed, the multihost serving contract."""
        import jax

        rep = self.replicas[token.replica_idx]
        try:
            if self.multihost:
                jax.block_until_ready(token.out)
                # one shard per distinct row span: the model axis holds
                # replicated copies of each output row block on every
                # tile device — keep exactly one
                uniq = {}
                for s in token.out.addressable_shards:
                    uniq.setdefault(s.index[0].start or 0, s)
                parts = []
                for k in sorted(uniq):
                    # rtfd-lint: allow[d2h] the designated completion pull (finalize path)
                    parts.append(np.asarray(uniq[k].data))
                out = np.concatenate(parts, axis=0)
            else:
                # rtfd-lint: allow[d2h] the designated completion pull (finalize path)
                out = np.asarray(jax.device_get(token.out))
        except Exception:
            self._mark_failed(rep)
            raise
        self._release(rep)
        return out

    def complete_no_fetch(self, token: MeshToken) -> None:
        """Drain a slot via block_until_ready only (pre-pull-safe: the
        bench's mesh_scaling stage must not flip a tunneled TPU into
        synchronous dispatch)."""
        import jax

        rep = self.replicas[token.replica_idx]
        try:
            jax.block_until_ready(token.out)
        except Exception:
            self._mark_failed(rep)
            raise
        self._release(rep)

    # -------------------------------------------------------------- control
    def set_models(self, models) -> None:
        """Re-shard a model swap replica-by-replica per the SAME placement
        (callers hold the score lock — the /reload-models recipe). A batch
        in flight keeps the params reference captured at launch, so no
        batch ever computes on mixed params."""
        import jax

        from realtime_fraud_detection_tpu.parallel.layouts import (
            branch_serving_specs,
            tree_specs_to_shardings,
        )

        from realtime_fraud_detection_tpu.core.mesh import make_global_batch

        for rep in self.replicas:
            specs = branch_serving_specs(models, self.model_axis,
                                         self.shard_branches)
            shardings = tree_specs_to_shardings(rep.mesh, specs)
            new = (make_global_batch(rep.mesh, models, shardings)
                   if self.multihost
                   else jax.device_put(models, shardings))
            with self._cv:
                rep.models = new
                rep.shardings = shardings

    def donation_lowering(self, blobs: Dict[str, np.ndarray], spec, params,
                          model_valid: np.ndarray,
                          donate: bool = True) -> str:
        """Lower (never execute) the selected entry for these blobs on
        replica 0 and return the StableHLO text. The drill greps it for
        the donation annotations (``tf.aliasing_output`` /
        ``jax.buffer_donor``) — the truthful donation evidence on EVERY
        backend: the fused program's output shape matches no input, so
        CPU PJRT (strict aliasing only) drops the donation at run time,
        while TPU reuses the donated space for temporaries. What must
        hold everywhere is that the annotation reaches the compiler."""
        rep = self.replicas[0]
        staged = self._stage(rep, blobs)
        # rtfd-lint: allow[d2h] host bool[M] validity mask, never a device array
        mv = np.asarray(model_valid)
        return mesh_entry(donate).lower(
            rep.models, staged["f32"], staged["i32"], staged["u8"],
            spec=spec, params=params, model_valid=rep.mv_dev(mv),
            blob_bf16=staged.get("bf16"),
            bert_config=self.scorer.bert_config,
            use_pallas=self.scorer.effective_use_pallas(),
            gather_fields=self._gather_fields, mesh=rep.mesh,
            **self.scorer.quant_static(),
            **self.scorer.kernel_static(mv)).as_text()

    # ---------------------------------------------------------------- stats
    def _branch_fields(self) -> Dict[str, str]:
        return {"xgboost_primary": "trees", "lstm_sequential": "lstm",
                "bert_text": "bert", "graph_neural": "gnn",
                "isolation_forest": "iforest"}

    def param_bytes(self) -> Dict[str, Dict[str, int]]:
        """Per-branch param bytes as COMMITTED on mesh replica 0: the
        max-over-chips resident shard bytes vs the replicated-equivalent
        (full pytree bytes, what DevicePool would hold per chip). Read
        from the actual array shardings, never the spec intent — this is
        the number the drill's <=60% acceptance gate and the
        ``mesh_param_bytes_per_chip`` series report."""
        import jax

        rep = self.replicas[0]
        out: Dict[str, Dict[str, int]] = {}
        for branch, field in self._branch_fields().items():
            per_chip: Dict[Any, int] = {}
            total = 0
            for leaf in jax.tree_util.tree_leaves(getattr(rep.models,
                                                          field)):
                total += leaf.nbytes
                for shard in leaf.addressable_shards:
                    per_chip[shard.device] = (per_chip.get(shard.device, 0)
                                              + shard.data.nbytes)
            out[branch] = {
                "per_chip": max(per_chip.values()) if per_chip else 0,
                "replicated": total,
            }
        return out

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            per_replica = [{
                "index": rep.idx,
                "healthy": rep.healthy,
                "dispatched": rep.dispatched,
                "completed": rep.completed,
                "inflight": rep.inflight,
                "failures": rep.failures,
                "queue_wait_ms": round(rep.queue_wait_s * 1e3, 3),
                "devices": int(np.prod(list(rep.mesh.shape.values()))),
            } for rep in self.replicas]
        return {
            "kind": "mesh",
            "replicas": per_replica,
            "n_replicas": len(per_replica),
            "healthy": sum(1 for r in per_replica if r["healthy"]),
            "inflight_depth": self.inflight_depth,
            "data_axis": self.data_axis,
            "model_axis": self.model_axis,
            "dispatched": sum(r["dispatched"] for r in per_replica),
            "completed": sum(r["completed"] for r in per_replica),
        }

    def mesh_snapshot(self) -> Dict[str, Any]:
        """Observability payload for ``obs.metrics.sync_mesh``: mesh
        geometry, the per-branch placement as 0/1 flags, per-chip vs
        replicated param bytes, and the cumulative dispatch counters."""
        pb = self.param_bytes()
        st = self.stats()
        return {
            "data_axis": self.data_axis,
            "model_axis": self.model_axis,
            "replicas": len(self.replicas),
            "placement": {name: ("sharded" if name in self.shard_branches
                                 else "replicated")
                          for name in MODEL_NAMES},
            "param_bytes": pb,
            "dispatched": {str(r["index"]): r["dispatched"]
                           for r in st["replicas"]},
            "completed": {str(r["index"]): r["completed"]
                          for r in st["replicas"]},
            "healthy": st["healthy"],
        }

"""Fused end-to-end scoring pipeline: one jitted program for the whole ensemble.

This is the TPU-native answer to the reference's serving hot path
(main.py:146-215 -> ensemble_predictor.py:75-148 -> model_manager.py:279-346),
which dispatched each of the 5 models as a separate asyncio task over Python
objects at batch=1. Here the entire ensemble — 64-feature extraction, GBDT,
isolation forest, LSTM, GraphSAGE, DistilBERT text branch, rule score, ensemble
combination, decision ladder and explanation factors — is ONE XLA program over
a dense microbatch, so every branch fuses, shares the (B, 64) feature tensor
in VMEM/HBM, and the MXU sees large batched matmuls instead of 5 Python round
trips.

Model order in the (B, M) prediction matrix matches the reference registry
(config.py:126-199): xgboost_primary, lstm_sequential, bert_text,
graph_neural, isolation_forest.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from realtime_fraud_detection_tpu.ensemble.combine import (
    EnsembleParams,
    combine_predictions,
)
from realtime_fraud_detection_tpu.features.extract import extract_features
from realtime_fraud_detection_tpu.features.rules import rule_score
from realtime_fraud_detection_tpu.features.schema import TransactionBatch
from realtime_fraud_detection_tpu.models.bert import (
    BertConfig,
    TINY_CONFIG,
    bert_predict,
    init_bert_params,
)
from realtime_fraud_detection_tpu.models.gnn import gnn_logits, init_gnn_params
from realtime_fraud_detection_tpu.models.isolation_forest import (
    IsolationForest,
    iforest_predict,
)
from realtime_fraud_detection_tpu.models.lstm import init_lstm_params, lstm_logits
from realtime_fraud_detection_tpu.models.trees import (
    TreeEnsemble,
    tree_ensemble_predict,
)
from realtime_fraud_detection_tpu.ops.epilogue import (
    epilogue_supported,
    fused_epilogue,
)

# Registry order (reference config.py:126-199). Index into the (B, M) matrix.
MODEL_NAMES: tuple[str, ...] = (
    "xgboost_primary",
    "lstm_sequential",
    "bert_text",
    "graph_neural",
    "isolation_forest",
)
NUM_MODELS = len(MODEL_NAMES)


@struct.dataclass
class ScoringModels:
    """All five model branches as one pytree (checkpointable unit)."""

    trees: TreeEnsemble
    iforest: IsolationForest
    lstm: Dict[str, jax.Array]
    gnn: Dict[str, jax.Array]
    bert: Dict[str, Any]


@struct.dataclass
class ScoreBatch:
    """Dense device-side inputs for one scoring microbatch.

    Everything is fixed-shape so one compilation serves every batch in the
    same bucket (core/batching.py). ``valid`` masks bucket padding rows.
    """

    txn: TransactionBatch            # struct-of-arrays transaction batch
    features: jax.Array              # f32[B, 64] extracted §2.3 features
    history: jax.Array               # f32[B, T, F] per-user txn history (front-padded)
    history_len: jax.Array           # i32[B] valid suffix lengths
    user_feat: jax.Array             # f32[B, D] center user node features
    merchant_feat: jax.Array         # f32[B, D] center merchant node features
    user_neigh_feat: jax.Array       # f32[B, K, D] merchants around the user
    user_neigh_mask: jax.Array       # bool[B, K]
    merch_neigh_feat: jax.Array      # f32[B, K, D] users around the merchant
    merch_neigh_mask: jax.Array      # bool[B, K]
    token_ids: jax.Array             # i32[B, S] tokenized merchant/description text
    token_mask: jax.Array            # bool[B, S]
    valid: jax.Array                 # bool[B] real row (False = bucket padding)
    # typed-graph two-hop frontier (graph/sampler.py; None in bipartite
    # mode). None fields contribute no pytree leaves, so the legacy
    # PackSpec — a STATIC jit argument — is byte-identical with the graph
    # plane off: the two-hop program is a different compilation selected
    # through the existing static-arg seam, and quant/mesh/pool compose
    # unchanged (they shard/pack whatever leaves the batch carries).
    user_neigh2_feat: Any = None     # f32[B, K, K2, D] users around the
    user_neigh2_mask: Any = None     # bool[B, K, K2]   user's entities
    merch_neigh2_feat: Any = None    # f32[B, K, K2, D] merchants around
    merch_neigh2_mask: Any = None    # bool[B, K, K2]   the merchant's users

    @property
    def batch_size(self) -> int:
        return int(self.history.shape[0])


def init_scoring_models(
    key: jax.Array,
    bert_config: BertConfig = TINY_CONFIG,
    feature_dim: int = 64,
    node_dim: int = 16,
    n_trees: int = 100,
    tree_depth: int = 6,
    gnn_typed: bool = False,
) -> ScoringModels:
    """Randomly-initialized model set (the reference's dummy-model fallback,
    model_manager.py:109-121, except ours are real architectures).
    ``gnn_typed`` selects the heterogeneous entity-graph GNN layout
    (per-node-type projections, graph/ plane)."""
    k_lstm, k_gnn, k_bert = jax.random.split(key, 3)
    return ScoringModels(
        trees=TreeEnsemble.zeros(n_trees, tree_depth),
        iforest=IsolationForest(
            feature=jnp.zeros((n_trees, 2 ** 8 - 1), jnp.int32),
            threshold=jnp.full((n_trees, 2 ** 8 - 1), jnp.inf, jnp.float32),
            path_length=jnp.full((n_trees, 2 ** 8), 8.0, jnp.float32),
            c_psi=jnp.asarray(8.0, jnp.float32),
        ),
        lstm=init_lstm_params(k_lstm, feature_dim=feature_dim),
        gnn=init_gnn_params(k_gnn, node_dim=node_dim, txn_dim=feature_dim,
                            typed=gnn_typed),
        bert=init_bert_params(k_bert, bert_config),
    )


def _key_factors(txn: TransactionBatch) -> Dict[str, jax.Array]:
    """Vectorized key-factor flags (ensemble_predictor.py:389-412)."""
    return {
        "high_amount": txn.amount > 10_000.0,
        "unusual_hour": (txn.hour_of_day < 6) | (txn.hour_of_day >= 23),
        "high_risk_payment": txn.high_risk_payment,
    }


def _score_fused_impl(
    models: ScoringModels,
    batch: ScoreBatch,
    params: EnsembleParams,
    model_valid: jax.Array,          # bool[M] — branch failure mask (§2.2)
    bert_config: BertConfig = TINY_CONFIG,
    use_pallas: bool = False,
    with_model_preds: bool = True,
    tree_kernel: str = "gather",     # quantized plane (QuantSettings):
    iforest_kernel: str = "gather",  # gather oracle | Hummingbird GEMM form
    dequant_kernel: str = "off",     # kernel plane (KernelSettings): Pallas
    epilogue_kernel: str = "off",    # fused dequant-matmul / score-blend
    kernel_interpret: bool = False,  # Pallas interpreter (non-TPU hosts)
) -> Dict[str, jax.Array]:
    """Score one microbatch through the full 5-model ensemble.

    Returns fraud_probability/confidence/decision/risk_level f32|i32[B] plus
    per-model predictions (B, M), the rule-based score (B,) and key-factor
    flags — everything the §2.7 FraudPrediction response needs, computed in a
    single fused XLA program. Features are precomputed once by the assembler
    (``ScoreBatch.features``) — they're also needed host-side for the
    history store, so extracting here again would double the work.
    """
    features = batch.features                                   # f32[B, 64]

    preds = jnp.stack(
        [
            tree_ensemble_predict(models.trees, features,
                                  kernel=tree_kernel),
            jax.nn.sigmoid(
                lstm_logits(models.lstm, batch.history, batch.history_len)
            ),
            bert_predict(
                models.bert, batch.token_ids, batch.token_mask,
                bert_config, use_pallas=use_pallas,
                dequant_kernel=dequant_kernel,
                kernel_interpret=kernel_interpret,
            ),
            jax.nn.sigmoid(
                gnn_logits(
                    models.gnn, features,
                    batch.user_feat, batch.merchant_feat,
                    batch.user_neigh_feat, batch.user_neigh_mask,
                    batch.merch_neigh_feat, batch.merch_neigh_mask,
                    user_neigh2_feat=batch.user_neigh2_feat,
                    user_neigh2_mask=batch.user_neigh2_mask,
                    merch_neigh2_feat=batch.merch_neigh2_feat,
                    merch_neigh2_mask=batch.merch_neigh2_mask,
                )
            ),
            iforest_predict(models.iforest, features,
                            kernel=iforest_kernel),
        ],
        axis=1,
    )                                                            # f32[B, M]

    valid = jnp.broadcast_to(model_valid[None, :], preds.shape) & batch.valid[:, None]
    rule = rule_score(batch.txn)
    if (epilogue_kernel == "pallas"
            and epilogue_supported(preds.shape[0], preds.shape[1])):
        # fused score-and-blend (ops/epilogue.py): combine + decision/risk
        # ladders + the finalize-derived columns (explanation contributions,
        # rules-only ladder) run on-chip in one kernel
        out = dict(fused_epilogue(preds, valid, rule, params,
                                  interpret=kernel_interpret))
    else:
        out = dict(combine_predictions(preds, valid, params))
    out["rule_score"] = rule
    out.update(_key_factors(batch.txn))
    if with_model_preds:
        out["model_predictions"] = preds
    return out


score_fused = partial(
    jax.jit,
    static_argnames=("bert_config", "use_pallas", "with_model_preds",
                     "tree_kernel", "iforest_kernel", "dequant_kernel",
                     "epilogue_kernel", "kernel_interpret"),
)(_score_fused_impl)


# Column layout of the packed f32[B, len(OUT_COLUMNS) + NUM_MODELS] result
# matrix: everything _build_responses needs, in one d2h transfer. ints and
# bools ride as exact small floats (decision/risk are ladder indices < 4).
OUT_COLUMNS: tuple[str, ...] = (
    "fraud_probability", "confidence", "decision", "risk_level",
    "rule_score", "high_amount", "unusual_hour", "high_risk_payment",
)

# With the fused epilogue on (KernelSettings.epilogue="pallas"), the packed
# matrix grows the finalize-derived columns the host used to recompute per
# record: per-model explanation contributions (weights x preds) and the QoS
# rules-only decision/risk ladder over the rule score. Layout becomes
# f32[B, 8 + M + M + 2]: OUT_COLUMNS, model predictions, then these.
# _build_responses detects the extension by width, so the kernels-off
# layout stays byte-identical to the legacy one.
EXT_COLUMNS: tuple[str, ...] = ("model_contributions", "rule_decision",
                                "rule_risk")


def packed_width(num_models: int, epilogue: bool) -> int:
    """Width of the packed result matrix for a given layout."""
    base = len(OUT_COLUMNS) + num_models
    return base + num_models + 2 if epilogue else base


def _score_fused_packed_impl(
    models: ScoringModels,
    blob_f32: jax.Array,             # f32[B, Wf] — packed float leaves
    blob_i32: jax.Array,             # i32[B, Wi] — packed int leaves
    blob_u8: jax.Array,              # u8[B, Wb]  — packed bool leaves
    spec,                            # static core.packing.PackSpec
    params: EnsembleParams,
    model_valid: jax.Array,
    blob_bf16: Optional[jax.Array] = None,  # bf16[B, Wh] — half-width leaves
    bert_config: BertConfig = TINY_CONFIG,
    use_pallas: bool = False,
    tree_kernel: str = "gather",
    iforest_kernel: str = "gather",
    dequant_kernel: str = "off",
    epilogue_kernel: str = "off",
    kernel_interpret: bool = False,
    megakernel: str = "off",         # persistent whole-batch program
    mega_valid: Optional[tuple] = None,  # QoS rung as static branch mask
) -> jax.Array:
    """Transfer-optimal fused scorer: packed blobs in, one matrix out.

    The streaming hot path on a remote TPU is bounded by transport round
    trips, not FLOPs (bench r4: ~85 ms null RTT vs ~25 ms compute per
    256-batch). This entry takes the microbatch as the three packed buffers
    from ``core.packing.pack_tree`` (one h2d payload) and returns the §2.7
    response fields as ONE f32[B, 8+M] matrix (one d2h payload) laid out per
    ``OUT_COLUMNS`` + model_predictions. XLA fuses the unpack slices into
    the branch consumers, so the repack costs nothing on-device.
    """
    from realtime_fraud_detection_tpu.core.packing import unpack_tree

    blobs = {"f32": blob_f32, "i32": blob_i32, "u8": blob_u8}
    if blob_bf16 is not None:
        blobs["bf16"] = blob_bf16
    batch = unpack_tree(blobs, spec)
    # bf16 was a wire format: widen back to f32 before the branches (the
    # cast fuses into the first consumer, costing no extra HBM traffic)
    batch = jax.tree.map(
        lambda x: x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x,
        batch)
    if megakernel == "pallas" and mega_valid is not None:
        # persistent megakernel (ops/megakernel.py): score the whole
        # microbatch in ONE Pallas program whose output IS the extended
        # packed matrix — no branch intermediates in HBM. The QoS rung
        # rides in as the static ``mega_valid`` tuple (one cached program
        # per rung). ``mega_plan`` is the same predicate the host-side
        # fallback counters consult, so this trace-time guard and
        # kernel_fallback_total always agree; unsupported shapes fall
        # through to the per-site kernel chain below.
        from realtime_fraud_detection_tpu.ops.megakernel import (
            fused_megakernel,
            mega_plan,
        )

        plan = mega_plan(
            models, bert_config, b=int(batch.features.shape[0]),
            text_len=int(batch.token_ids.shape[1]),
            seq_len=int(batch.history.shape[1]),
            feature_dim=int(batch.features.shape[1]),
            has_two_hop=batch.user_neigh2_feat is not None)
        if plan["supported"]:
            return fused_megakernel(
                models, batch, params, mega_valid=mega_valid,
                bert_config=bert_config, interpret=kernel_interpret,
                block=plan["block"])
    out = _score_fused_impl(
        models, batch, params, model_valid,
        bert_config=bert_config, use_pallas=use_pallas,
        with_model_preds=True,
        tree_kernel=tree_kernel, iforest_kernel=iforest_kernel,
        dequant_kernel=dequant_kernel, epilogue_kernel=epilogue_kernel,
        kernel_interpret=kernel_interpret,
    )
    cols = [out[name].astype(jnp.float32) for name in OUT_COLUMNS]
    parts = [jnp.stack(cols, axis=1), out["model_predictions"]]
    if "model_contributions" in out:
        # fused-epilogue extension (EXT_COLUMNS): finalize's derived
        # columns come back in the same single d2h matrix
        parts.append(out["model_contributions"].astype(jnp.float32))
        parts.append(jnp.stack(
            [out["rule_decision"].astype(jnp.float32),
             out["rule_risk"].astype(jnp.float32)], axis=1))
    return jnp.concatenate(parts, axis=1)


score_fused_packed = partial(
    jax.jit, static_argnames=("spec", "bert_config", "use_pallas",
                              "tree_kernel", "iforest_kernel",
                              "dequant_kernel", "epilogue_kernel",
                              "kernel_interpret", "megakernel",
                              "mega_valid"),
)(_score_fused_packed_impl)

# Donated-input variant for the device pool's per-replica dispatch
# (scoring/device_pool.py): the packed blobs are throwaway H2D staging —
# fresh per dispatch, never read back — so donating them lets XLA reuse
# the buffers instead of holding depth x 3 blobs per replica alive, which
# is what cuts the batch-256 h2d p99 tail (BENCH_r05). The host keeps its
# own numpy copy for the retry-on-replica-failure path, so donation never
# loses data. Fall back to the plain entry on jax builds without
# donate_argnames.
try:
    score_fused_packed_donated = partial(
        jax.jit, static_argnames=("spec", "bert_config", "use_pallas",
                                  "tree_kernel", "iforest_kernel",
                                  "dequant_kernel", "epilogue_kernel",
                                  "kernel_interpret", "megakernel",
                                  "mega_valid"),
        donate_argnames=("blob_f32", "blob_i32", "blob_u8", "blob_bf16"),
    )(_score_fused_packed_impl)
except TypeError:  # pragma: no cover - older jax
    score_fused_packed_donated = score_fused_packed


@dataclasses.dataclass
class ScorerConfig:
    """Static shapes for the fused scorer (one compilation per bucket)."""

    seq_len: int = 10          # LSTM history length (config.py:151-157)
    feature_dim: int = 64      # the §2.3 feature contract width
    node_dim: int = 16         # GNN node feature width
    fanout: int = 16           # GNN neighbor fanout (last-100-txn graph analog)
    # GNN graph substrate: "bipartite" = the original user<->merchant
    # EntityGraphStore neighborhoods; "typed" = the heterogeneous entity
    # graph (graph/ plane: user<->device<->merchant<->IP, two-hop typed
    # sampling through graph.sampler.NeighborSampler, edges ingested at
    # finalize time, cross-partition fetch attachable). The typed tensors
    # ride new optional ScoreBatch fields, so the mode IS the static
    # PackSpec — no extra flag reaches the fused program.
    graph_mode: str = "bipartite"
    # typed mode's 2-hop width (the [B, K, K2, D] tensors; K2 < K keeps
    # the neighbor payload bounded — bytes scale with K * K2)
    graph_fanout2: int = 8
    text_len: int = 64         # token length for the text branch
    # "word" = hash-OOV word tokenizer (fast, no vocab file);
    # "wordpiece" = trained subword vocab with BERT's greedy longest-match
    # algorithm (models/wordpiece.py — the reference's tokenizer class,
    # bert_text_analyzer.py:47-66, minus the hub download)
    tokenizer: str = "word"
    # whole-text token LRU size (models/tokenizer.TokenLruCache): merchant
    # texts repeat heavily, so the default keeps every live merchant string
    # resident; shrink for memory-tight hosts
    token_cache_entries: int = 65_536
    use_pallas: bool = False   # Pallas flash attention (TPU only)
    # start the result's device->host copy at dispatch time so the transfer
    # overlaps the next batch's host work (scorer.dispatch). Tunable because
    # transport backends differ in how they handle outstanding async copies.
    async_d2h: bool = True
    # ship the bulky float tensors (LSTM history + GNN node/neighbor
    # features, ~45% of the microbatch bytes) as bf16 on the wire; widened
    # back to f32 on-device. Off by default: it perturbs scores at bf16
    # resolution, so it's a knob for bandwidth-bound links, not a freebie.
    transfer_bf16: bool = False


def make_example_batch(
    batch_size: int,
    config: ScorerConfig = ScorerConfig(),
    rng: Optional[np.random.Generator] = None,
) -> ScoreBatch:
    """Synthetic ScoreBatch for compile-checks and benchmarks."""
    from realtime_fraud_detection_tpu.features.extract import (
        extract_features_host,
    )
    from realtime_fraud_detection_tpu.features.schema import encode_transactions
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    rng = rng or np.random.default_rng(0)
    gen = TransactionGenerator(num_users=max(64, batch_size), num_merchants=64)
    records = gen.generate_batch(batch_size)
    txn = encode_transactions(
        records,
        gen.users.profiles(),
        gen.merchants.profiles(),
    )
    b, c = batch_size, config
    return ScoreBatch(
        txn=txn,
        # host-backend extraction: benches/examples must not trigger a
        # device->host pull at staging time (see extract_features_host)
        features=extract_features_host(txn),
        history=rng.standard_normal((b, c.seq_len, c.feature_dim)).astype(np.float32),
        history_len=np.full((b,), c.seq_len, np.int32),
        user_feat=rng.standard_normal((b, c.node_dim)).astype(np.float32),
        merchant_feat=rng.standard_normal((b, c.node_dim)).astype(np.float32),
        user_neigh_feat=rng.standard_normal((b, c.fanout, c.node_dim)).astype(np.float32),
        user_neigh_mask=np.ones((b, c.fanout), bool),
        merch_neigh_feat=rng.standard_normal((b, c.fanout, c.node_dim)).astype(np.float32),
        merch_neigh_mask=np.ones((b, c.fanout), bool),
        token_ids=rng.integers(0, 30522, (b, c.text_len)).astype(np.int32),
        token_mask=np.ones((b, c.text_len), bool),
        valid=np.ones((b,), bool),
    )

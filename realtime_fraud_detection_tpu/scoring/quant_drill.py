"""Deterministic quantization drill: the ``rtfd quant-drill`` score-delta
oracle that makes the quantized scoring plane shippable.

Per the reduced-precision serving result (arXiv:2109.09541), int8 weights
and reshaped tree kernels are free throughput ONLY while quality is gated,
not assumed. This drill is that gate, run the way the other five drills
run (virtual clock, seeded, compact <2 KB JSON verdict as the final
stdout line):

1. **Score-delta oracle.** One seeded transaction stream through TWO real
   scorers — the committed f32 fused program and the fully quantized one
   (weight-only int8 BERT + GEMM-form GBDT/iforest kernels,
   ``QuantSettings.full()``) — driven identically (same generator seed,
   same virtual clock, same state write-back interleaving). The max
   absolute fraud-score divergence must sit BELOW the calibration-noise
   floor: the score movement the committed bf16-compute policy
   (core/precision.py) already accepts, measured in-drill by running the
   SAME f32 weights at bf16 vs f32 compute and scaling the BERT branch
   delta by its blend weight. Quantization may not cost more precision
   than the precision budget production already spends.
2. **Zero decision flips.** At the pinned operating point (the decision
   ladder the reference serves, §2.7), every transaction must take the
   SAME decision under both programs — divergence that crosses an
   operating threshold is a quality regression no throughput buys back.
3. **Quality-protocol AUC.** Trees + isolation forest are trained on a
   stream segment through the PRODUCTION assemble path (the
   blend_eval/feedback-drill recipe, drill-sized) and a held-out labeled
   segment is scored by both programs: |AUC(f32) - AUC(quant)| must be
   ~0 (below the protocol's resolution).
4. **GEMM-vs-gather oracle.** On both the trained and a randomized
   ensemble, the contraction-form tree path must select EXACTLY the same
   leaves as the gather oracle (models/trees.py keeps the split
   convention identical by construction) with logits inside float
   tolerance (summation-order slack only).
5. **Bytes.** The quantized BERT branch must serialize >= ``3.5x``
   smaller than f32 — the HBM headroom the mesh item buys with this PR.
6. **Replay.** A second full run must be bit-identical (sha256 over every
   score, decision, AUC and divergence stat).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["QuantDrillConfig", "run_quant_drill", "compact_quant_summary"]


@dataclasses.dataclass
class QuantDrillConfig:
    seed: int = 11
    num_users: int = 800
    num_merchants: int = 160
    batch: int = 128
    n_train: int = 4_096        # trees/iforest training segment (protocol)
    n_batches: int = 16         # divergence / decision-flip stream
    eval_batches: int = 20      # held-out labeled AUC segment
    n_trees: int = 48
    tree_depth: int = 6
    tps: float = 200.0          # virtual arrival rate (clock advance)
    # gates
    noise_scale: float = 1.0    # quant divergence <= scale * bf16 noise floor
    noise_floor_abs: float = 1e-4   # resolution floor for the noise bound
    max_auc_delta: float = 2e-3
    min_bytes_ratio: float = 3.5
    leaf_logit_tol: float = 1e-4    # documented GEMM summation-order slack
    replay: bool = True

    @classmethod
    def fast(cls) -> "QuantDrillConfig":
        """Tier-1 smoke sizes: every phase runs, compiles stay small."""
        return cls(num_users=400, num_merchants=80, batch=64,
                   n_train=1_536, n_batches=8, eval_batches=10, n_trees=24)


def _make_side(cfg: QuantDrillConfig, quantized: bool):
    """One drill side: seeded generator + scorer (f32 or fully quantized),
    with trees/iforest trained on its own identical stream segment through
    the production assemble path (deterministic, so both sides deploy the
    SAME f32 trees; only the BERT weight form and tree kernels differ)."""
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        IsolationForestTrainer,
    )
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.training import GBDTTrainer
    from realtime_fraud_detection_tpu.utils.config import (
        Config,
        QuantSettings,
    )

    quant = QuantSettings.full() if quantized else QuantSettings()
    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed)
    scorer = FraudScorer(Config(quant=quant), scorer_config=ScorerConfig(),
                         seed=cfg.seed)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

    xs, ys = [], []
    done, ts = 0, 0.0
    while done < cfg.n_train:
        n = min(cfg.batch, cfg.n_train - done)
        recs = gen.generate_batch(n)
        batch = scorer.assemble(recs, now=ts)
        xs.append(np.asarray(batch.features))
        ys.append(np.asarray([bool(r.get("is_fraud")) for r in recs],
                             np.float32))
        for r in recs:   # serving's write-back: later segments see state
            scorer.velocity.update(str(r.get("user_id", "")),
                                   float(r.get("amount", 0.0)), ts)
        done += n
        ts += n / cfg.tps
    x, y = np.concatenate(xs), np.concatenate(ys)
    trees = GBDTTrainer(n_estimators=cfg.n_trees, max_depth=cfg.tree_depth,
                        seed=cfg.seed).fit(x, y)
    iforest = IsolationForestTrainer(n_estimators=cfg.n_trees,
                                     seed=cfg.seed + 1).fit(
        x[y < 0.5][:4000])
    # rtfd-lint: allow[lock-order] drill is single-threaded (no batch in flight during the swap)
    scorer.set_models(scorer.models.replace(trees=trees, iforest=iforest))
    return gen, scorer, ts


def _score_stream(cfg: QuantDrillConfig, gen, scorer, ts: float,
                  n_batches: int, keep_tokens: int = 0,
                  ) -> Tuple[Dict[str, Any], float]:
    """Drive ``n_batches`` through the scorer on the virtual clock;
    returns host-side probs/decisions/labels (+ the first ``keep_tokens``
    token batches for the noise-floor measurement)."""
    probs: List[float] = []
    decisions: List[str] = []
    labels: List[float] = []
    tokens: List[Tuple[np.ndarray, np.ndarray]] = []
    for i in range(n_batches):
        recs = gen.generate_batch(cfg.batch)
        batch = scorer.assemble(recs, now=ts)
        if i < keep_tokens:
            tokens.append((np.asarray(batch.token_ids),
                           np.asarray(batch.token_mask)))
        results = scorer.finalize(
            scorer.dispatch_assembled(batch, recs), now=ts)
        probs.extend(r["fraud_probability"] for r in results)
        decisions.extend(r["decision"] for r in results)
        labels.extend(float(bool(r.get("is_fraud"))) for r in recs)
        ts += cfg.batch / cfg.tps
    return {
        "probs": np.asarray(probs, np.float64),
        "decisions": decisions,
        "labels": np.asarray(labels, np.float32),
        "tokens": tokens,
    }, ts


def _noise_floor(cfg: QuantDrillConfig, scorer,
                 tokens) -> Dict[str, float]:
    """The calibration-noise bound: how far the committed bf16 compute
    policy already moves the ensemble score vs full f32 compute, measured
    on this drill's own token stream with the f32 weights. Quantization
    must fit inside that accepted budget (scaled by ``noise_scale``)."""
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.models.bert import bert_predict

    bf16 = jax.jit(lambda p, i, m: bert_predict(
        p, i, m, scorer.bert_config, use_pallas=scorer.sc.use_pallas))
    f32 = jax.jit(lambda p, i, m: bert_predict(
        p, i, m, scorer.bert_config, use_pallas=scorer.sc.use_pallas,
        compute_dtype=jnp.float32))
    branch_delta = 0.0
    for ids, mask in tokens:
        a = bf16(scorer.models.bert, ids, mask)
        b = f32(scorer.models.bert, ids, mask)
        branch_delta = max(branch_delta,
                           float(jnp.max(jnp.abs(a - b))))
    # the branch feeds the blend through its normalized weight — that is
    # the score-level movement the committed policy accepts
    weights = np.asarray(scorer.ensemble_params.weights, np.float64)
    valid = np.asarray(scorer.effective_model_valid(), bool)
    w = weights * valid
    w_bert = float(w[2] / max(w.sum(), 1e-9))      # MODEL_NAMES order
    bound = max(branch_delta * w_bert, cfg.noise_floor_abs)
    return {"bert_branch_bf16_delta": branch_delta,
            "bert_blend_weight": round(w_bert, 4),
            "bound": bound}


def _tree_oracle(cfg: QuantDrillConfig, scorer) -> Dict[str, Any]:
    """GEMM-vs-gather equivalence on the TRAINED ensembles plus a
    randomized one: exact leaf equality, logits inside tolerance."""
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.models.trees import (
        TreeEnsemble,
        descend_complete_trees,
        gemm_leaf_index,
        tree_ensemble_logits,
    )

    rng = np.random.default_rng(cfg.seed + 7)
    feat_dim = int(scorer.sc.feature_dim)
    x = jnp.asarray(rng.standard_normal((cfg.batch, feat_dim)), jnp.float32)

    out: Dict[str, Any] = {}
    trained = scorer.models.trees
    cases = {"trained_gbdt": (trained.feature, trained.threshold),
             "trained_iforest": (scorer.models.iforest.feature,
                                 scorer.models.iforest.threshold)}
    n_int = int(np.shape(trained.feature)[1])
    depth = int(np.log2(n_int + 1))
    rf = jnp.asarray(rng.integers(0, feat_dim, (8, n_int)), jnp.int32)
    rt = jnp.where(jnp.asarray(rng.random((8, n_int)) < 0.3), jnp.inf,
                   jnp.asarray(rng.standard_normal((8, n_int)), jnp.float32))
    cases["randomized"] = (rf, rt)

    leaves_equal = True
    for name, (feature, threshold) in cases.items():
        gather = descend_complete_trees(feature, threshold, x)
        gemm = gemm_leaf_index(feature, threshold, x)
        eq = bool(jnp.all(gather == gemm))
        out[name] = {"leaves_equal": eq}
        leaves_equal = leaves_equal and eq

    rand_ens = TreeEnsemble(
        feature=rf, threshold=rt,
        leaf=jnp.asarray(rng.standard_normal((8, 2 ** depth)), jnp.float32),
        base_score=jnp.asarray(0.1, jnp.float32))
    logit_delta = 0.0
    for ens in (trained, rand_ens):
        lg = tree_ensemble_logits(ens, x, kernel="gather")
        lm = tree_ensemble_logits(ens, x, kernel="gemm")
        logit_delta = max(logit_delta, float(jnp.max(jnp.abs(lg - lm))))
    out["max_logit_delta"] = logit_delta
    out["leaves_equal"] = leaves_equal
    return out


def _run_once(cfg: QuantDrillConfig) -> Dict[str, Any]:
    from realtime_fraud_detection_tpu.models.quant import (
        bert_param_bytes,
        is_quantized_bert,
        quant_error_bound,
    )
    from realtime_fraud_detection_tpu.training.blend_eval import _auc

    summary: Dict[str, Any] = {
        "drill": "quantization",
        "seed": cfg.seed,
        "batch": cfg.batch,
        "n_batches": cfg.n_batches,
        "checks": {},
    }
    checks = summary["checks"]

    gen_f, scorer_f, ts_f = _make_side(cfg, quantized=False)
    gen_q, scorer_q, ts_q = _make_side(cfg, quantized=True)
    assert ts_f == ts_q

    # param bytes: the HBM/hot-swap payload each replica carries
    bytes_f32 = bert_param_bytes(scorer_f.models.bert)
    bytes_q = bert_param_bytes(scorer_q.models.bert)
    ratio = bytes_f32 / max(bytes_q, 1)
    summary["param_bytes"] = {
        "bert_f32": bytes_f32, "bert_int8": bytes_q,
        "ratio": round(ratio, 3),
        "weight_reconstruction_bound": round(
            quant_error_bound(scorer_q.models.bert), 6),
    }
    checks["bert_is_quantized"] = is_quantized_bert(scorer_q.models.bert)
    checks["bytes_ratio_ge_min"] = ratio >= cfg.min_bytes_ratio

    # ---------------------------------- phase 1: divergence + decision flips
    keep = min(4, cfg.n_batches)
    side_f, ts_f = _score_stream(cfg, gen_f, scorer_f, ts_f, cfg.n_batches,
                                 keep_tokens=keep)
    side_q, ts_q = _score_stream(cfg, gen_q, scorer_q, ts_q, cfg.n_batches)
    div = np.abs(side_f["probs"] - side_q["probs"])
    flips = sum(a != b for a, b in zip(side_f["decisions"],
                                      side_q["decisions"]))
    noise = _noise_floor(cfg, scorer_f, side_f["tokens"])
    summary["divergence"] = {
        "max": float(div.max()),
        "mean": float(div.mean()),
        "p99": float(np.percentile(div, 99)),
        "n_txn": int(div.size),
        "noise_floor": noise,
        "noise_scale": cfg.noise_scale,
        "decision_flips": int(flips),
    }
    checks["divergence_below_noise"] = (
        float(div.max()) <= cfg.noise_scale * noise["bound"])
    checks["zero_decision_flips"] = flips == 0
    scorer_q.record_quant_gate(bool(checks["divergence_below_noise"]
                                    and checks["zero_decision_flips"]))

    # --------------------------------------- phase 2: quality-protocol AUC
    eval_f, _ = _score_stream(cfg, gen_f, scorer_f, ts_f, cfg.eval_batches)
    eval_q, _ = _score_stream(cfg, gen_q, scorer_q, ts_q, cfg.eval_batches)
    auc_f = _auc(eval_f["labels"], eval_f["probs"])
    auc_q = _auc(eval_q["labels"], eval_q["probs"])
    summary["quality"] = {
        "auc_f32": round(auc_f, 6),
        "auc_quant": round(auc_q, 6),
        "auc_delta": round(abs(auc_f - auc_q), 6),
        "eval_txn": int(eval_f["labels"].size),
        "fraud_rate": round(float(eval_f["labels"].mean()), 4),
        "max_auc_delta": cfg.max_auc_delta,
    }
    checks["auc_unchanged"] = abs(auc_f - auc_q) <= cfg.max_auc_delta
    scorer_q.record_quant_gate(bool(checks["auc_unchanged"]))

    # ------------------------------------------ phase 3: GEMM-vs-gather
    oracle = _tree_oracle(cfg, scorer_f)
    summary["tree_oracle"] = oracle
    checks["gemm_leaves_identical"] = oracle["leaves_equal"]
    checks["gemm_logits_within_tol"] = (
        oracle["max_logit_delta"] <= cfg.leaf_logit_tol)

    # served-mode truth (quant_snapshot reads live params, not config)
    summary["modes"] = {"f32": scorer_f.quant_snapshot()["modes"],
                        "quant": scorer_q.quant_snapshot()["modes"]}

    summary["passed"] = all(bool(v) for v in checks.values())
    return summary


def _digest(summary: Dict[str, Any]) -> str:
    """Replay fingerprint over every number the gates read."""
    payload = json.dumps(
        {k: summary.get(k) for k in ("divergence", "quality", "tree_oracle",
                                     "param_bytes", "checks")},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def run_quant_drill(cfg: Optional[QuantDrillConfig] = None) -> Dict[str, Any]:
    cfg = cfg or QuantDrillConfig()
    summary = _run_once(cfg)
    summary["digest"] = _digest(summary)
    if cfg.replay:
        second = _run_once(cfg)
        second_digest = _digest(second)
        summary["replay"] = {"digest": second_digest,
                             "bit_identical": second_digest
                             == summary["digest"]}
        summary["checks"]["replay_bit_identical"] = (
            second_digest == summary["digest"])
        summary["passed"] = all(bool(v)
                                for v in summary["checks"].values())
    return summary


def compact_quant_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """<2 KB single-line verdict (the bench.py final-stdout convention)."""
    div = summary.get("divergence") or {}
    q = summary.get("quality") or {}
    pb = summary.get("param_bytes") or {}
    return {
        "drill": "quantization",
        "passed": summary.get("passed", False),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "max_divergence": div.get("max"),
        "noise_bound": (div.get("noise_floor") or {}).get("bound"),
        "decision_flips": div.get("decision_flips"),
        "auc_f32": q.get("auc_f32"),
        "auc_quant": q.get("auc_quant"),
        "auc_delta": q.get("auc_delta"),
        "bytes_ratio": pb.get("ratio"),
        "digest": (summary.get("digest") or "")[:16],
    }

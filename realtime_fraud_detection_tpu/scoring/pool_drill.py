"""Deterministic device-pool drill: the ``rtfd pool-drill`` acceptance gate.

Runs the REAL pooled scoring path (FraudScorer + DevicePool over the
host-platform's virtual devices) on a deterministic stream and pins the
pool's whole contract in one verdict:

1. **bit-equality** — pooled scores are bit-identical to single-device
   scoring of the same stream under the same dispatch/finalize
   interleaving (same window W of in-flight batches, so host state
   evolves identically);
2. **FIFO** — results come back in submit order, per batch and across
   batches;
3. **utilization** — every replica received work, zero retries;
4. **hot-swap** — a mid-stream ``set_models`` swap is replica-by-replica:
   every batch matches EITHER the old-params reference or the new-params
   reference wholesale — no batch ever serves mixed params;
5. **scaling** — the pool's actual dispatch schedule, replayed on a
   deterministic virtual timeline (nominal v5e-shaped per-batch costs:
   host work ``host_ms``, device compute ``device_ms``, true device
   parallelism), sustains >= 3x the 1-device aggregate throughput.

Why the scaling gate is virtual-time: the drill must be deterministic,
and CI hosts running 8 *virtual* CPU devices share one physical core
budget — XLA's host platform timeslices one intra-op pool, so wall-clock
"scaling" there measures the CI box, not the scheduler. The virtual
replay uses the pool's REAL assignment sequence and in-flight constraint
(a broken round-robin or a depth leak collapses it) with device
parallelism as the hardware would provide it; the measured-on-chip bar
lives in ``bench.py``'s ``pool_scaling`` stage. Wall-clock numbers are
reported alongside, ungated.

Convention matches qos/feedback drills: virtual event clock for state
TTLs, full summary JSON then a compact (<2 KB) verdict as the final
stdout line (cli.cmd_pool_drill).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["PoolDrillConfig", "run_pool_drill", "compact_pool_summary"]


@dataclasses.dataclass
class PoolDrillConfig:
    n_devices: int = 8
    inflight_depth: int = 2
    batch: int = 64
    n_batches: int = 24          # equality/utilization phase
    swap_batches: int = 16       # hot-swap phase (swap at the midpoint)
    seed: int = 7
    # nominal per-batch costs for the virtual-time schedule replay:
    # ~5 ms host assemble+pack+dispatch (PR-2 columnar at batch 256) and
    # ~25 ms device compute (BENCH_r04 on-chip capture shape)
    host_ms: float = 5.0
    device_ms: float = 25.0
    min_scaling: float = 3.0

    @classmethod
    def fast(cls) -> "PoolDrillConfig":
        """Tier-1 smoke sizes: every phase runs, compiles stay small."""
        return cls(batch=16, n_batches=10, swap_batches=8)


def _make_scorer(cfg: PoolDrillConfig, model_seed: int = 0):
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    gen = TransactionGenerator(num_users=500, num_merchants=100,
                               seed=cfg.seed)
    scorer = FraudScorer(scorer_config=ScorerConfig(), seed=model_seed)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, scorer


def _run_stream(scorer, batches: List[list], window: int,
                now: float = 1000.0,
                swap_at: Optional[int] = None, swap_models=None,
                ) -> List[List[Dict[str, Any]]]:
    """Dispatch/finalize ``batches`` with at most ``window`` in flight.

    The SAME routine drives the pooled scorer and the single-device
    reference, so both see identical host-state interleaving (batch N+1
    may assemble before batch N's write-back — identically on both
    sides); that is what makes bit-equality a fair assertion.
    ``swap_at``: call set_models(swap_models) right before dispatching
    that batch index (the hot-swap phase).
    """
    results: List[List[Dict[str, Any]]] = []
    inflight: deque = deque()
    for i, recs in enumerate(batches):
        if swap_at is not None and i == swap_at:
            # rtfd-lint: allow[lock-order] the drill IS the only dispatcher; swap purity is what it pins
            scorer.set_models(swap_models)
        inflight.append(scorer.dispatch(recs, now=now))
        while len(inflight) >= window:
            results.append(scorer.finalize(inflight.popleft(), now=now))
    while inflight:
        results.append(scorer.finalize(inflight.popleft(), now=now))
    return results


def _rows(results: List[List[Dict[str, Any]]]) -> List[tuple]:
    return [(r["transaction_id"], r["fraud_probability"], r["confidence"],
             r["decision"]) for batch in results for r in batch]


def _virtual_makespan_ms(assignments: List[int], n_devices: int,
                         depth: int, host_ms: float,
                         device_ms: float) -> float:
    """Replay a dispatch-assignment sequence on a deterministic timeline:
    one serial host producing a batch every ``host_ms``, each device
    computing for ``device_ms``, at most ``depth`` batches in flight per
    device (the host blocks on the oldest — exactly DevicePool's
    backpressure)."""
    host_t = 0.0
    free = [0.0] * n_devices
    inflight = [deque() for _ in range(n_devices)]
    last_done = 0.0
    for r in assignments:
        while len(inflight[r]) >= depth:
            host_t = max(host_t, inflight[r].popleft())
        host_t += host_ms
        end = max(host_t, free[r]) + device_ms
        free[r] = end
        inflight[r].append(end)
        last_done = max(last_done, end)
    return last_done


def run_pool_drill(cfg: Optional[PoolDrillConfig] = None) -> Dict[str, Any]:
    import jax

    from realtime_fraud_detection_tpu.scoring import DevicePool
    from realtime_fraud_detection_tpu.scoring.pipeline import (
        init_scoring_models,
    )

    cfg = cfg or PoolDrillConfig()
    devices = jax.devices()
    if len(devices) < cfg.n_devices:
        raise RuntimeError(
            f"pool drill needs {cfg.n_devices} devices, found "
            f"{len(devices)} — run via `rtfd pool-drill` (it re-execs on a "
            f"virtual {cfg.n_devices}-device host platform) or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{cfg.n_devices}")
    devices = devices[:cfg.n_devices]

    summary: Dict[str, Any] = {
        "drill": "device_pool",
        "n_devices": cfg.n_devices,
        "inflight_depth": cfg.inflight_depth,
        "batch": cfg.batch,
        "platform": devices[0].platform,
        "checks": {},
    }
    checks = summary["checks"]

    # Warm the per-device executables with a THROWAWAY scorer (same bucket
    # shape -> same jit cache) so phase wall-clocks measure scoring, not
    # 8x XLA compile; the throwaway's state mutations never touch the
    # drill scorers, keeping bit-equality fair.
    gen_w, warm_scorer = _make_scorer(cfg)
    warm_pool = DevicePool(warm_scorer, devices=devices,
                           inflight_depth=cfg.inflight_depth)
    warm_pend = [warm_scorer.dispatch(gen_w.generate_batch(cfg.batch),
                                      now=1000.0)
                 for _ in range(cfg.n_devices)]
    for p in warm_pend:
        warm_scorer.finalize(p, now=1000.0)

    # ---------------------------------------------------- phase 1: equality
    gen_a, serial = _make_scorer(cfg)
    batches = [gen_a.generate_batch(cfg.batch) for _ in range(cfg.n_batches)]

    gen_b, pooled_scorer = _make_scorer(cfg)
    pool = DevicePool(pooled_scorer, devices=devices,
                      inflight_depth=cfg.inflight_depth)
    window = min(cfg.n_batches, pool.total_slots())
    batches_b = [gen_b.generate_batch(cfg.batch)
                 for _ in range(cfg.n_batches)]

    # rtfd-lint: allow[wall-clock] wall time reported ungated (virtual CPU devices share one core)
    t0 = time.perf_counter()
    ref = _run_stream(serial, batches, window)
    # rtfd-lint: allow[wall-clock] wall time reported ungated (virtual CPU devices share one core)
    wall_serial = time.perf_counter() - t0
    # rtfd-lint: allow[wall-clock] wall time reported ungated (virtual CPU devices share one core)
    t0 = time.perf_counter()
    got = _run_stream(pooled_scorer, batches_b, window)
    # rtfd-lint: allow[wall-clock] wall time reported ungated (virtual CPU devices share one core)
    wall_pooled = time.perf_counter() - t0

    checks["bit_identical"] = _rows(ref) == _rows(got)
    submitted = [str(r.get("transaction_id", "")) for b in batches_b
                 for r in b]
    returned = [tid for tid, *_ in _rows(got)]
    checks["fifo_order"] = returned == submitted

    stats = pool.stats()
    per_dev = [d["dispatched"] for d in stats["devices"]]
    checks["all_devices_utilized"] = all(n > 0 for n in per_dev)
    checks["zero_retries"] = stats["retries"] == 0
    summary["per_device_dispatched"] = per_dev
    summary["wall_clock"] = {
        "serial_s": round(wall_serial, 3),
        "pooled_s": round(wall_pooled, 3),
        "note": "informational only — virtual CPU devices timeslice one "
                "physical core budget; the gated scaling number is the "
                "virtual-time replay below",
    }

    # ---------------------------------------------------- phase 2: hot swap
    new_models = init_scoring_models(
        jax.random.PRNGKey(101), bert_config=pooled_scorer.bert_config,
        feature_dim=pooled_scorer.sc.feature_dim,
        node_dim=pooled_scorer.sc.node_dim)
    swap_at = cfg.swap_batches // 2

    gen_old, serial_old = _make_scorer(cfg)
    swap_old_ref = _run_stream(
        serial_old, [gen_old.generate_batch(cfg.batch)
                     for _ in range(cfg.swap_batches)], window)
    gen_new, serial_new = _make_scorer(cfg, model_seed=0)
    # rtfd-lint: allow[lock-order] serial oracle scorer, single-threaded by construction
    serial_new.set_models(new_models)
    swap_new_ref = _run_stream(
        serial_new, [gen_new.generate_batch(cfg.batch)
                     for _ in range(cfg.swap_batches)], window)

    gen_sw, swap_scorer = _make_scorer(cfg)
    swap_pool = DevicePool(swap_scorer, devices=devices,
                           inflight_depth=cfg.inflight_depth)
    swap_got = _run_stream(
        swap_scorer, [gen_sw.generate_batch(cfg.batch)
                      for _ in range(cfg.swap_batches)],
        min(cfg.swap_batches, swap_pool.total_slots()),
        swap_at=swap_at, swap_models=new_models)

    mixed = 0
    matches_old = matches_new = 0
    for i, batch_res in enumerate(swap_got):
        rows = _rows([batch_res])
        if rows == _rows([swap_old_ref[i]]):
            matches_old += 1
        elif rows == _rows([swap_new_ref[i]]):
            matches_new += 1
        else:
            mixed += 1
    checks["no_mixed_params_batch"] = (
        mixed == 0 and matches_old > 0 and matches_new > 0)
    summary["hot_swap"] = {
        "swap_at_batch": swap_at,
        "batches_on_old_params": matches_old,
        "batches_on_new_params": matches_new,
        "mixed_batches": mixed,
    }

    # --------------------------------------- phase 3: virtual-time scaling
    # the REAL assignment sequence the pool produced in phase 1, in
    # dispatch order (DevicePool.assignment_log) — a broken rotation
    # shows up both here and in the strict round-robin check below
    assignments = list(pool.assignment_log)
    checks["round_robin_assignment"] = (
        assignments == [i % cfg.n_devices for i in range(cfg.n_batches)])

    pooled_ms = _virtual_makespan_ms(
        assignments, cfg.n_devices, cfg.inflight_depth,
        cfg.host_ms, cfg.device_ms)
    single_ms = _virtual_makespan_ms(
        [0] * cfg.n_batches, 1, cfg.inflight_depth,
        cfg.host_ms, cfg.device_ms)
    scaling = single_ms / max(pooled_ms, 1e-9)
    txn = cfg.n_batches * cfg.batch
    summary["virtual_time"] = {
        "model": {"host_ms_per_batch": cfg.host_ms,
                  "device_ms_per_batch": cfg.device_ms},
        "single_device_makespan_ms": round(single_ms, 3),
        "pooled_makespan_ms": round(pooled_ms, 3),
        "single_device_txn_per_s": round(txn / (single_ms / 1e3), 1),
        "pooled_txn_per_s": round(txn / (pooled_ms / 1e3), 1),
        "scaling": round(scaling, 3),
        "min_scaling": cfg.min_scaling,
    }
    checks["scaling_ge_min"] = scaling >= cfg.min_scaling

    summary["passed"] = all(bool(v) for v in checks.values())
    return summary


def compact_pool_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """<2 KB single-line verdict (the bench.py final-stdout convention)."""
    vt = summary.get("virtual_time") or {}
    return {
        "drill": "device_pool",
        "passed": summary.get("passed", False),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "n_devices": summary.get("n_devices"),
        "inflight_depth": summary.get("inflight_depth"),
        "scaling": vt.get("scaling"),
        "pooled_txn_per_s": vt.get("pooled_txn_per_s"),
        "per_device_dispatched": summary.get("per_device_dispatched"),
    }

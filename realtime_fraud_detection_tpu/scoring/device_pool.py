"""Replicated multi-chip scoring pool: N model instances, deep dispatch.

Every serving/stream path before this PR drove exactly ONE device — the
mesh sharded a microbatch ACROSS chips, but the hot loops
(serving/batcher.py, stream/job.py, scoring/host_pipeline.py) kept a
single program in flight, so on a v5e-8 seven chips idled while one chip
capped the plane at ~10k txn/s (BENCH_r04_tpu_capture). The throughput
shape that actually scales ads/fraud scoring — "Scaling TensorFlow to 300
million predictions per second" (arXiv:2109.09541) and Google's
ads-serving writeup (arXiv:2501.10546) — is the opposite: REPLICATE the
model onto every chip and keep several whole microbatches in flight per
replica, so each chip runs its own fused program and the host's job is
only to keep the queues fed.

``DevicePool`` implements that shape over the existing packed seam:

- params are replicated per device at construction (one ``device_put``
  per replica — the ``core.mesh.replicated_sharding`` analog, minus the
  mesh: each replica is its own single-device program);
- ``dispatch_packed`` picks a replica round-robin, stages the packed
  blobs onto it (fresh buffers per dispatch = double-buffered H2D; on
  accelerators the donated-input jit lets XLA recycle them — the
  batch-256 h2d p99 lever), and launches without blocking;
- at most ``inflight_depth`` batches ride each replica; a full replica
  backpressures the dispatcher (the wait is recorded as queue-wait);
- completion order is the CALLER's: ``FraudScorer.finalize`` blocks on
  batches in dispatch order, so FIFO per source holds by construction;
- a replica whose result fetch fails is marked unhealthy and its batch
  is relaunched from the host-side blob copy on a healthy replica
  (counted in stats — the bench refuses to headline a degraded run);
- ``set_models`` swaps params replica-by-replica (callers hold the score
  lock); an in-flight batch keeps the reference it captured at launch,
  so no batch ever sees mixed params;
- the branch-validity mask is snapshotted per dispatch: every launch
  passes the scorer's CURRENT host mask and each replica refreshes its
  device copy by value comparison (``_Replica.mv_dev``), so a QoS ladder
  step (``FraudScorer.set_degradation`` — one host-field write) fans out
  to all replicas atomically: every batch dispatched after the step runs
  the new mask on whichever replica it lands on, every batch before it
  completes under its own.

Bit-equality contract: a pooled batch runs the IDENTICAL packed program
on identical inputs — only the device differs — so scores are
bit-identical to single-device scoring on the same platform
(``rtfd pool-drill`` pins it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["DevicePool", "PoolToken"]


class PoolToken:
    """One pooled in-flight batch: the launched device array plus
    everything needed to relaunch it elsewhere if its replica dies."""

    __slots__ = ("out", "replica_idx", "blobs", "spec", "params",
                 "model_valid", "t_dispatch", "inflight_at_dispatch")

    def __init__(self, out, replica_idx, blobs, spec, params, model_valid,
                 t_dispatch, inflight_at_dispatch=0):
        self.out = out
        self.replica_idx = replica_idx
        self.blobs = blobs              # host numpy copies (retry source)
        self.spec = spec
        self.params = params
        self.model_valid = model_valid  # host bool[M] snapshot
        self.t_dispatch = t_dispatch
        # the replica's queue depth (this batch included) captured under
        # the pool lock at assignment — tail-attribution metadata for the
        # tracing plane (a p99 outlier dispatched at depth 2 waited out a
        # predecessor's compute; one dispatched at depth 1 did not)
        self.inflight_at_dispatch = inflight_at_dispatch


class _Replica:
    """One device's model instance + dispatch bookkeeping."""

    def __init__(self, idx: int, device, models):
        import jax

        self.idx = idx
        self.device = device
        self.models = jax.device_put(models, device)
        self.healthy = True
        self.inflight = 0
        self.dispatched = 0
        self.completed = 0
        self.retries = 0            # batches RESCUED ONTO this replica
        self.failures = 0           # fetch failures observed ON this replica
        self.queue_wait_s = 0.0
        self.fail_next = 0          # test fault injection (see inject_fault)
        self.slow_next = 0          # slow-device injection (inject_slow)
        self.slow_s = 0.0           # per-injected-fetch added delay
        self._mv_cache: Optional[tuple] = None  # (host mask, device copy)

    def mv_dev(self, mv: np.ndarray):
        import jax

        cached = self._mv_cache
        if cached is None or not np.array_equal(cached[0], mv):
            self._mv_cache = (mv.copy(), jax.device_put(mv, self.device))
        return self._mv_cache[1]


class DevicePool:
    """Round-robin replicated dispatch across every addressable device.

    ``inflight_depth`` is PER REPLICA (>= 2 keeps a replica's compute
    back-to-back: one batch running while the next one's H2D stages).
    Thread-safe: dispatch and completion may come from different threads
    (AssemblerStage dispatches, the finalize path completes).
    """

    def __init__(self, scorer, devices: Optional[Sequence] = None,
                 inflight_depth: int = 2, donate: Optional[bool] = None):
        import jax

        self.scorer = scorer
        devs = list(devices) if devices is not None else list(jax.devices())
        if not devs:
            raise ValueError("device pool needs at least one device")
        self.inflight_depth = max(1, int(inflight_depth))
        # donation needs accelerator buffer aliasing; the CPU backend only
        # warns and ignores it, so default it off there to keep logs clean
        self.donate = (devs[0].platform != "cpu" if donate is None
                       else bool(donate))
        self._cv = threading.Condition()
        self.replicas = [_Replica(i, d, scorer.models)
                         for i, d in enumerate(devs)]
        self._rr = 0
        # bounded trace of replica assignments in dispatch order (rescue
        # launches included): the drill replays the REAL schedule on its
        # virtual timeline instead of assuming the rotation worked
        self.assignment_log: deque = deque(maxlen=4096)
        scorer.attach_pool(self)

    # ------------------------------------------------------------- capacity
    def __len__(self) -> int:
        return len(self.replicas)

    @property
    def healthy_count(self) -> int:
        return sum(1 for r in self.replicas if r.healthy)

    def total_slots(self) -> int:
        """Max batches in flight across the pool (healthy replicas only) —
        what the stream/serving pipeline depth should rise to so every
        replica actually receives work."""
        return max(1, self.healthy_count * self.inflight_depth)

    # ------------------------------------------------------------- dispatch
    def _pick_replica(self) -> tuple:
        """Round-robin over healthy replicas; blocks (queue wait) while the
        chosen replica is at depth. Strict rotation — not shortest-queue —
        so the assignment sequence is deterministic for the drill.
        Returns ``(replica, inflight_after_assignment)`` — the depth is
        captured under the lock so the tracing plane's dispatch metadata
        is exact, never a racy re-read."""
        with self._cv:
            n = len(self.replicas)
            for off in range(n):
                rep = self.replicas[(self._rr + off) % n]
                if rep.healthy:
                    self._rr = (self._rr + off + 1) % n
                    break
            else:
                raise RuntimeError("device pool has no healthy replicas")
            # rtfd-lint: allow[wall-clock] queue-wait/dispatch diagnostics (host stats), not control flow
            t0 = time.perf_counter()
            while rep.inflight >= self.inflight_depth:
                if not self._cv.wait(timeout=120.0):
                    raise TimeoutError(
                        f"device {rep.idx} stuck at inflight depth "
                        f"{rep.inflight} for 120s")
                if not rep.healthy:     # died while we waited: re-pick
                    return self._pick_replica()
            # rtfd-lint: allow[wall-clock] queue-wait/dispatch diagnostics (host stats), not control flow
            rep.queue_wait_s += time.perf_counter() - t0
            rep.inflight += 1
            rep.dispatched += 1
            self.assignment_log.append(rep.idx)
            return rep, rep.inflight

    def _launch(self, rep: "_Replica", blobs: Dict[str, np.ndarray], spec,
                params, model_valid: np.ndarray):
        import jax

        from realtime_fraud_detection_tpu.scoring.pipeline import (
            score_fused_packed,
            score_fused_packed_donated,
        )

        staged = {k: jax.device_put(v, rep.device)
                  for k, v in blobs.items() if v is not None}
        with self._cv:
            models = rep.models         # snapshot: hot swap never tears it
            # rtfd-lint: allow[d2h] host bool[M] validity mask, never a device array
            mv_dev = rep.mv_dev(np.asarray(model_valid))
        fn = score_fused_packed_donated if self.donate else score_fused_packed
        return fn(models, staged["f32"], staged["i32"], staged["u8"],
                  spec=spec, params=params, model_valid=mv_dev,
                  blob_bf16=staged.get("bf16"),
                  bert_config=self.scorer.bert_config,
                  use_pallas=self.scorer.effective_use_pallas(),
                  # quant + kernel planes: same static kernel selection on
                  # every replica (the scorer's params are already
                  # quantized, so replication/hot-swap carries the int8
                  # form for free, and a kernel-on scorer never mixes
                  # kernel modes within a batch). The dispatch-time rung
                  # snapshot rides in model_valid so a retry relaunches the
                  # SAME megakernel program, not the rung the ladder moved
                  # to meanwhile.
                  **self.scorer.quant_static(),
                  **self.scorer.kernel_static(model_valid))

    def dispatch_packed(self, blobs: Dict[str, np.ndarray], spec, params,
                        model_valid: np.ndarray) -> PoolToken:
        """Stage + launch one packed microbatch on the next replica.

        Returns without blocking on the result; blocks only when the
        chosen replica already has ``inflight_depth`` batches in flight
        (backpressure, recorded as queue wait)."""
        rep, depth = self._pick_replica()
        # rtfd-lint: allow[d2h] host bool[M] validity mask, never a device array
        mv = np.asarray(model_valid)
        host_blobs = {k: v for k, v in blobs.items() if v is not None}
        try:
            out = self._launch(rep, host_blobs, spec, params, mv)
        except Exception:
            # a launch failure is a replica failure too: free the slot,
            # mark it, and let the caller's dispatch path degrade
            self._mark_failed(rep)
            raise
        return PoolToken(out, rep.idx, host_blobs, spec, params, mv,
                         # rtfd-lint: allow[wall-clock] queue-wait/dispatch diagnostics (host stats), not control flow
                         time.perf_counter(), inflight_at_dispatch=depth)

    # ------------------------------------------------------------ completion
    def _mark_failed(self, rep: "_Replica") -> None:
        with self._cv:
            rep.failures += 1
            rep.healthy = False
            rep.inflight = max(0, rep.inflight - 1)
            self._cv.notify_all()

    def _release(self, rep: "_Replica") -> None:
        with self._cv:
            rep.inflight = max(0, rep.inflight - 1)
            rep.completed += 1
            self._cv.notify_all()

    def wait(self, token: PoolToken) -> np.ndarray:
        """Block on a pooled batch's result; on a replica failure, relaunch
        the batch from its host blobs on a healthy replica (per-device
        retry counters feed the metrics plane; the bench refuses to
        headline a run that needed this path)."""
        import jax

        attempts = len(self.replicas) + 1
        for _ in range(attempts):
            rep = self.replicas[token.replica_idx]
            self._maybe_slow(rep)
            try:
                if rep.fail_next > 0:
                    rep.fail_next -= 1
                    raise RuntimeError(
                        f"injected device fault on replica {rep.idx}")
                # rtfd-lint: allow[d2h] the designated completion pull (finalize path)
                out = np.asarray(jax.device_get(token.out))
            except Exception:
                self._mark_failed(rep)
                # rescue bypasses depth backpressure: the caller may be the
                # only thread draining the pool, with every healthy replica
                # at full depth — waiting for a slot here would deadlock.
                # A transient depth overshoot on the least-loaded healthy
                # replica is the lesser evil. A rescue replica whose OWN
                # launch fails is marked too (releasing its slot) and the
                # next candidate is tried.
                while True:
                    with self._cv:
                        candidates = [r for r in self.replicas if r.healthy]
                        if not candidates:
                            raise
                        retry_rep = min(candidates,
                                        key=lambda r: r.inflight)
                        retry_rep.inflight += 1
                        retry_rep.dispatched += 1
                        retry_rep.retries += 1
                        self.assignment_log.append(retry_rep.idx)
                    try:
                        token.out = self._launch(
                            retry_rep, token.blobs, token.spec,
                            token.params, token.model_valid)
                    except Exception:
                        self._mark_failed(retry_rep)
                        continue
                    token.replica_idx = retry_rep.idx
                    break
                continue
            self._release(rep)
            return out
        raise RuntimeError("device pool retry budget exhausted")

    def complete_no_fetch(self, token: PoolToken) -> None:
        """Block until a pooled batch's compute finishes and release its
        slot WITHOUT pulling the result to the host. For throughput
        measurement on tunneled TPUs (bench.py pool_scaling): the first
        d2h pull flips the relay into synchronous dispatch, so the
        pre-pull phases must drain slots via block_until_ready only. A
        failure marks the replica (no retry — a measurement run that
        needed rescue is refused as a headline anyway)."""
        import jax

        rep = self.replicas[token.replica_idx]
        self._maybe_slow(rep)
        try:
            if rep.fail_next > 0:
                rep.fail_next -= 1
                raise RuntimeError(
                    f"injected device fault on replica {rep.idx}")
            jax.block_until_ready(token.out)
        except Exception:
            self._mark_failed(rep)
            raise
        self._release(rep)

    # -------------------------------------------------------------- control
    def set_models(self, models) -> None:
        """Fan a model swap out replica-by-replica. Callers hold the score
        lock (the /reload-models recipe); a batch in flight keeps the
        params reference captured at its launch, so the swap never serves
        mixed params within one batch."""
        import jax

        for rep in self.replicas:
            new = jax.device_put(models, rep.device)
            with self._cv:
                rep.models = new

    def inject_fault(self, replica_idx: int, n: int = 1) -> None:
        """Test hook: make the next ``n`` result fetches on a replica
        raise, exercising the retry-on-healthy-replica path without
        needing real device loss."""
        with self._cv:
            self.replicas[replica_idx].fail_next += n

    def inject_slow(self, replica_idx: int, delay_s: float,
                    n: int = 1) -> None:
        """Chaos hook: the next ``n`` result fetches on a replica take an
        extra ``delay_s`` — a DELAYED device, not a dead one. The batch
        still completes on its own replica (no retry, no health change);
        what must hold is FIFO completion across the pool while one
        replica lags (pinned in tests/test_device_pool.py)."""
        with self._cv:
            rep = self.replicas[replica_idx]
            rep.slow_next += max(0, int(n))
            # rtfd-lint: allow[d2h] delay_s is a host scalar argument, not a device value
            rep.slow_s = float(delay_s)

    def _maybe_slow(self, rep: "_Replica") -> None:
        """Apply an injected slow-device delay OUTSIDE the pool lock (a
        stalled fetch must not block dispatch to healthy replicas)."""
        # lock-free fast path: this runs on EVERY pooled result fetch, and
        # slow_next is nonzero only while a chaos harness has armed
        # inject_slow — a stale read at worst delays one injection by a
        # fetch, so production fetches never contend on the pool CV here
        if rep.slow_next <= 0:
            return
        with self._cv:
            if rep.slow_next <= 0:
                return
            rep.slow_next -= 1
            delay = rep.slow_s
        time.sleep(delay)

    def revive(self, replica_idx: int) -> None:
        """Re-admit a failed replica to the rotation (operator action
        after the underlying device recovers). A revived device is a
        HEALTHY device: any still-armed injected faults/delays are
        cleared — a stale arm must not re-kill the replica after its
        fault window closed."""
        with self._cv:
            rep = self.replicas[replica_idx]
            rep.healthy = True
            rep.fail_next = 0
            rep.slow_next = 0
            self._cv.notify_all()

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Per-device counters for the obs plane
        (obs.metrics.MetricsCollector.sync_device_pool)."""
        with self._cv:
            per_device: List[Dict[str, Any]] = [{
                "device": str(rep.device),
                "index": rep.idx,
                "healthy": rep.healthy,
                "dispatched": rep.dispatched,
                "completed": rep.completed,
                "inflight": rep.inflight,
                "retries": rep.retries,
                "failures": rep.failures,
                "queue_wait_ms": round(rep.queue_wait_s * 1e3, 3),
            } for rep in self.replicas]
        return {
            "devices": per_device,
            "n_devices": len(self.replicas),
            "healthy": sum(1 for d in per_device if d["healthy"]),
            "inflight_depth": self.inflight_depth,
            "dispatched": sum(d["dispatched"] for d in per_device),
            "completed": sum(d["completed"] for d in per_device),
            "retries": sum(d["retries"] for d in per_device),
        }

"""Experimentation utilities (reference src/testing/ab_testing.py parity)."""

from realtime_fraud_detection_tpu.testing.ab import (
    ABTestManager,
    Experiment,
    Variant,
    VariantStats,
    apply_weight_overrides,
)

__all__ = ["ABTestManager", "Experiment", "Variant", "VariantStats",
           "apply_weight_overrides"]

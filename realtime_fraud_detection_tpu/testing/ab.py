"""A/B experimentation for scorer variants: sticky routing + evaluation.

Capability parity with the reference's ABTestManager (ab_testing.py:49-427):
hash-based sticky variant assignment per user, traffic-split validation,
per-variant online metrics (precision/recall/F1 against later-arriving fraud
labels), and a two-sample significance test on fraud-detection rates.

TPU-relevant twist: a variant here is a *scorer configuration* — ensemble
weights / strategy / enabled branches — all of which are runtime tensors to
the ONE compiled ``score_fused`` program (EnsembleParams and the
``model_valid`` mask are arguments, not constants). Serving N variants
therefore costs zero extra compilations; routing just picks which
EnsembleParams rides with the microbatch row's result combination, so
experiments are free on-device.

The significance test is a proper pooled two-proportion z-test rather than
the reference's "simplified t-test" (ab_testing.py:314-372).
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

from realtime_fraud_detection_tpu.utils.config import (
    DECLINE_THRESHOLD_DEFAULT,
    MONITOR_THRESHOLD_DEFAULT,
    REVIEW_THRESHOLD_DEFAULT,
)

__all__ = ["Variant", "VariantStats", "Experiment", "ABTestManager",
           "apply_weight_overrides"]


def apply_weight_overrides(
        model_predictions: Mapping[str, float],
        base_weights: Mapping[str, float],
        overrides: Mapping[str, float],
        confidence_threshold: float = 0.7,
        decline_threshold: float = DECLINE_THRESHOLD_DEFAULT,
        review_threshold: float = REVIEW_THRESHOLD_DEFAULT,
        monitor_threshold: float = MONITOR_THRESHOLD_DEFAULT) -> Optional[Dict[str, Any]]:
    """Re-combine per-model predictions under variant weight overrides.

    The fused scorer returns every branch's prediction, so a variant that
    only changes ensemble weights can be evaluated host-side as the same
    weighted average the device combine computes (ensemble_predictor.py:
    263-284 semantics) — zero extra device work per arm. The full downstream
    outcome is recomputed so the served record stays internally consistent:
    confidence (:325-342), decision ladder (:344-356), risk level (:358-369).
    Returns None when no overridden model actually produced a prediction."""
    from realtime_fraud_detection_tpu.features.rules import (
        ensemble_decision_name,
        model_confidence_value,
        risk_level_name,
    )
    from realtime_fraud_detection_tpu.utils.config import (
        DEFAULT_CONFIDENCE_MULTIPLIER,
        MODEL_CONFIDENCE_MULTIPLIER,
    )

    weights = {k: float(v) for k, v in base_weights.items()}
    weights.update({k: float(v) for k, v in overrides.items()})
    num = den = conf_num = 0.0
    for name, pred in model_predictions.items():
        w = weights.get(name, 0.0)
        p = float(pred)
        mult = MODEL_CONFIDENCE_MULTIPLIER.get(name, DEFAULT_CONFIDENCE_MULTIPLIER)
        num += w * p
        conf_num += w * model_confidence_value(p, mult)
        den += w
    if den <= 0.0:
        return None
    prob = num / den
    confidence = conf_num / den
    return {"fraud_probability": prob, "confidence": confidence,
            "decision": ensemble_decision_name(
                prob, confidence, confidence_threshold,
                decline=decline_threshold, review=review_threshold,
                monitor=monitor_threshold),
            "risk_level": risk_level_name(prob)}


@dataclasses.dataclass
class Variant:
    """One arm of an experiment. ``overrides`` patches the scorer config
    (model weights / strategy / enabled set)."""

    name: str
    traffic: float                       # fraction in [0, 1]
    overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)


class VariantStats:
    """Online confusion-matrix accumulator for one arm."""

    def __init__(self) -> None:
        self.assigned = 0
        self.predictions = 0
        self.score_sum = 0.0
        self.tp = self.fp = self.tn = self.fn = 0

    def record(self, fraud_score: float, flagged: bool,
               actual_fraud: Optional[bool]) -> None:
        self.predictions += 1
        self.score_sum += fraud_score
        if actual_fraud is None:
            return
        if flagged and actual_fraud:
            self.tp += 1
        elif flagged and not actual_fraud:
            self.fp += 1
        elif not flagged and actual_fraud:
            self.fn += 1
        else:
            self.tn += 1

    @property
    def labeled(self) -> int:
        return self.tp + self.fp + self.tn + self.fn

    def metrics(self) -> Dict[str, float]:
        """Precision/recall/F1 (ab_testing.py per-variant metrics analog)."""
        p = self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0
        r = self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return {
            "assigned": self.assigned,
            "predictions": self.predictions,
            "labeled": self.labeled,
            "avg_fraud_score": (self.score_sum / self.predictions
                                if self.predictions else 0.0),
            "precision": p,
            "recall": r,
            "f1": f1,
            "flag_rate": ((self.tp + self.fp) / self.labeled
                          if self.labeled else 0.0),
        }


@dataclasses.dataclass
class Experiment:
    name: str
    variants: List[Variant]
    salt: str = ""
    started_at: float = dataclasses.field(default_factory=time.time)
    active: bool = True

    def __post_init__(self) -> None:
        for v in self.variants:
            if not 0.0 <= v.traffic <= 1.0:
                raise ValueError(
                    f"variant {v.name!r} traffic {v.traffic} not in [0, 1]")
        total = sum(v.traffic for v in self.variants)
        if not math.isclose(total, 1.0, abs_tol=1e-6):
            raise ValueError(
                f"variant traffic must sum to 1.0, got {total:.6f}")
        if len({v.name for v in self.variants}) != len(self.variants):
            raise ValueError("duplicate variant names")


class ABTestManager:
    """Create experiments, stickily route users, evaluate arms."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._experiments: Dict[str, Experiment] = {}
        self._stats: Dict[str, Dict[str, VariantStats]] = {}

    # ------------------------------------------------------------ lifecycle
    def create_experiment(self, name: str, variants: List[Variant],
                          salt: str = "") -> Experiment:
        exp = Experiment(name=name, variants=variants, salt=salt)
        with self._lock:
            if name in self._experiments:
                raise ValueError(f"experiment {name!r} already exists")
            self._experiments[name] = exp
            self._stats[name] = {v.name: VariantStats() for v in variants}
        return exp

    def experiment_from_artifact(self, name: str, artifact_path: str,
                                 traffic: float = 0.5,
                                 salt: str = "") -> Experiment:
        """Canary a measured blend: control = current production weights
        (no overrides), treatment = a quality-eval artifact's
        selected_blend at ``traffic`` share. The treatment rides variant
        weight overrides, so serving re-weights host-side over the
        already-returned per-branch predictions (apply_weight_overrides) —
        zero extra device work per arm. Branches outside the artifact's
        blend are overridden to weight 0, matching the artifact's
        semantics exactly. NOTE: serving can only re-weight branches that
        actually computed a prediction — canarying a blend that
        re-includes a branch disabled in the current deployment requires
        enabling it first (/reload-models with the artifact); the serving
        endpoint enforces this."""
        from realtime_fraud_detection_tpu.scoring import MODEL_NAMES
        from realtime_fraud_detection_tpu.utils.config import Config

        weights = Config.load_selected_blend_weights(artifact_path)
        strategy = Config.load_selected_blend_strategy(artifact_path)
        if strategy not in (None, "weighted_average"):
            # host-side variant evaluation recombines the returned branch
            # predictions as a weighted average; a stacking/voting artifact
            # measured a DIFFERENT combine, so the canary arm would not be
            # serving what the artifact promises — deploy such artifacts
            # via /reload-models (the device combine honors the strategy)
            raise ValueError(
                f"artifact blend uses strategy {strategy!r}, which host-"
                f"side re-weighting cannot emulate; canary it via "
                f"/reload-models instead")
        unknown = [n for n in weights if n not in MODEL_NAMES]
        if unknown:
            raise ValueError(
                f"artifact names unknown model(s) {unknown}; "
                f"known: {list(MODEL_NAMES)}")
        overrides = {"weights": {n: weights.get(n, 0.0)
                                 for n in MODEL_NAMES}}
        return self.create_experiment(name, [
            Variant("control", 1.0 - traffic),
            Variant("artifact", traffic, overrides=overrides),
        ], salt=salt)

    def stop_experiment(self, name: str) -> None:
        with self._lock:
            self._experiments[name].active = False

    def active_experiments(self) -> List[str]:
        with self._lock:
            return [n for n, e in self._experiments.items() if e.active]

    # -------------------------------------------------------------- routing
    def assign(self, experiment: str, user_id: str) -> Variant:
        """Sticky hash assignment (ab_testing.py:49-105 semantics): the same
        user always lands in the same arm for a given experiment+salt."""
        exp = self._experiments[experiment]
        digest = hashlib.sha256(
            f"{experiment}:{exp.salt}:{user_id}".encode()).digest()
        u = int.from_bytes(digest[:8], "big") / 2 ** 64
        acc = 0.0
        chosen = exp.variants[-1]
        for v in exp.variants:
            acc += v.traffic
            if u < acc:
                chosen = v
                break
        with self._lock:
            self._stats[experiment][chosen.name].assigned += 1
        return chosen

    # ------------------------------------------------------------ recording
    def record_prediction(self, experiment: str, variant: str,
                          fraud_score: float, flagged: bool,
                          actual_fraud: Optional[bool] = None) -> None:
        with self._lock:
            self._stats[experiment][variant].record(
                fraud_score, flagged, actual_fraud)

    # ------------------------------------------------------------ analysis
    def results(self, experiment: str) -> Dict[str, Any]:
        exp = self._experiments[experiment]
        with self._lock:
            # snapshot everything under one lock: metrics() and the
            # significance test must see a consistent confusion matrix
            per_variant = {
                name: s.metrics()
                for name, s in self._stats[experiment].items()
            }
            sig = None
            if len(exp.variants) == 2:
                a, b = (exp.variants[0].name, exp.variants[1].name)
                sig = self._two_proportion_test(
                    self._stats[experiment][a], self._stats[experiment][b])
        out: Dict[str, Any] = {
            "experiment": experiment,
            "active": exp.active,
            "running_seconds": time.time() - exp.started_at,
            "variants": per_variant,
        }
        if sig is not None:
            out["significance"] = sig
            out["control"] = exp.variants[0].name
            out["treatment"] = exp.variants[1].name
        return out

    @staticmethod
    def _two_proportion_test(a: VariantStats, b: VariantStats,
                             alpha: float = 0.05) -> Dict[str, Any]:
        """Pooled two-proportion z-test on per-arm detection rate (recall).

        Pooled-variance z statistic; two-sided p via the normal CDF. This is
        the statistically sound version of ab_testing.py:314-372.
        """
        na, nb = a.tp + a.fn, b.tp + b.fn          # labeled positives per arm
        if na < 5 or nb < 5:
            return {"computed": False, "reason": "insufficient labeled fraud"}
        pa, pb = a.tp / na, b.tp / nb
        pooled = (a.tp + b.tp) / (na + nb)
        se = math.sqrt(pooled * (1 - pooled) * (1 / na + 1 / nb))
        if se == 0:
            return {"computed": False, "reason": "zero variance"}
        z = (pb - pa) / se
        p_value = 2 * (1 - 0.5 * (1 + math.erf(abs(z) / math.sqrt(2))))
        return {
            "computed": True,
            "recall_control": pa,
            "recall_treatment": pb,
            "effect": pb - pa,
            "z": z,
            "p_value": p_value,
            "significant": p_value < alpha,
        }

    # -------------------------------------------------------------- serving
    def route_config_overrides(self, experiment: str,
                               user_id: str) -> Mapping[str, Any]:
        """Overrides dict the serving layer applies to the scorer for this
        user's request (weights / strategy / enabled models)."""
        exp = self._experiments.get(experiment)
        if exp is None or not exp.active:
            return {}
        return self.assign(experiment, user_id).overrides

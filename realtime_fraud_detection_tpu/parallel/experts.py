"""Expert parallelism: mixture-of-experts FFN with all_to_all dispatch.

Completes the framework's named-parallelism inventory (dp/tp/sp/pp/ep —
SURVEY.md §2.8 lists the reference's only axis, data parallelism). The
reference has no MoE; this exists so a sparse scoring branch (e.g. per
merchant-category expert FFNs) scales by adding chips without growing
per-chip FLOPs, the standard TPU recipe:

- E experts' weights are stacked [E, ...] and sharded over the ``model``
  axis: each device materializes E/S experts.
- Tokens are sharded over ``data`` AND, within each data row, sliced over
  the expert axis (each device routes only n/(data*S) tokens — adding
  expert shards divides per-chip routing and FFN work instead of
  replicating it). Each device buckets its token slice per EXPERT with a
  fixed capacity slot count (static shapes — XLA-friendly); one
  ``all_to_all`` over the expert axis moves the buckets onto the devices
  that own the experts, where they run as E/S resident batched matmuls
  (weights never replicated per token); a second all_to_all brings the
  outputs home and an ``all_gather`` restores the full data-row shard.
- Tokens over capacity are DROPPED (output zero, like Switch Transformer):
  capacity_factor trades quality for the static bound.

Numerics contract (tests/test_parallel.py): with generous capacity the
result equals the dense reference — every token through its top-1 expert's
FFN scaled by its router probability.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import DATA_AXIS, MODEL_AXIS
from realtime_fraud_detection_tpu.parallel.collectives import shard_map_over

__all__ = ["MoEConfig", "init_moe_params", "moe_ffn", "moe_ffn_reference"]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int
    d_model: int
    d_hidden: int
    capacity_factor: float = 1.25


def init_moe_params(key: jax.Array, cfg: MoEConfig) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(cfg.d_model)
    scale_hid = 1.0 / jnp.sqrt(cfg.d_hidden)
    return {
        "router": jax.random.normal(
            k1, (cfg.d_model, cfg.n_experts)) * scale_in,
        "w1": jax.random.normal(
            k2, (cfg.n_experts, cfg.d_model, cfg.d_hidden)) * scale_in,
        "b1": jnp.zeros((cfg.n_experts, cfg.d_hidden)),
        "w2": jax.random.normal(
            k3, (cfg.n_experts, cfg.d_hidden, cfg.d_model)) * scale_hid,
        "b2": jnp.zeros((cfg.n_experts, cfg.d_model)),
    }


def _expert_ffn(w1, b1, w2, b2, x):
    return jax.nn.relu(x @ w1 + b1) @ w2 + b2


def moe_ffn_reference(params: Dict[str, jax.Array],
                      x: jax.Array) -> jax.Array:
    """Dense reference: every token through its top-1 expert, no capacity
    drops. [N, d] -> [N, d]."""
    logits = x @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(logits, axis=-1)                      # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    all_out = jax.vmap(
        lambda w1, b1, w2, b2: _expert_ffn(w1, b1, w2, b2, x)
    )(params["w1"], params["b1"], params["w2"], params["b2"])  # [E, N, d]
    picked = jnp.take_along_axis(
        all_out, expert[None, :, None], axis=0)[0]             # [N, d]
    return picked * gate[:, None]


def moe_ffn(mesh: Mesh, params: Dict[str, jax.Array], x: jax.Array,
            cfg: MoEConfig, axis: str = MODEL_AXIS) -> jax.Array:
    """Expert-parallel MoE FFN. x: [N, d] sharded over ``data``; expert
    weights sharded over ``axis``. Returns [N, d], same sharding as x."""
    n_shards = mesh.shape[axis]
    if cfg.n_experts % n_shards != 0:
        raise ValueError(
            f"n_experts={cfg.n_experts} not divisible by the "
            f"{axis}-axis size {n_shards}")
    experts_per_shard = cfg.n_experts // n_shards
    n_per_row = x.shape[0] // mesh.shape[DATA_AXIS]
    if n_per_row % n_shards != 0:
        raise ValueError(
            f"tokens per data row ({n_per_row}) not divisible by the "
            f"{axis}-axis size {n_shards}")

    def device_body(p, xs):
        # p: expert weights for THIS shard ([E/S, ...]; router replicated)
        # xs: [n_local, d] the data-row token shard (replicated over the
        #     expert axis — immediately sliced so each expert-shard device
        #     routes only its n_local/S piece)
        n_local, d = xs.shape
        n_sub = n_local // n_shards
        my_row = jax.lax.axis_index(axis)
        xs = jax.lax.dynamic_slice_in_dim(xs, my_row * n_sub, n_sub, 0)
        # per-expert capacity per source device
        cap = max(1, int(cfg.capacity_factor * n_sub / cfg.n_experts))
        e_local = experts_per_shard

        logits = xs @ p["router"]                             # [n_sub, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(logits, axis=-1)                  # [n_sub]
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

        # slot of each token within its expert's bucket (stable order);
        # tokens past the capacity are dropped (output exactly zero)
        onehot = jax.nn.one_hot(expert, cfg.n_experts, dtype=jnp.int32)
        slot = (jnp.cumsum(onehot, axis=0) - 1)               # [n_sub, E]
        my_slot = jnp.take_along_axis(
            slot, expert[:, None], axis=1)[:, 0]              # [n_sub]
        keep = my_slot < cap

        # scatter tokens into the [E, cap] dispatch buffer; dropped tokens
        # get an out-of-bounds index and mode="drop" discards the write —
        # kept tokens have unique slots, so the scatter is deterministic
        flat_idx = expert * cap + my_slot
        scatter_idx = jnp.where(keep, flat_idx, cfg.n_experts * cap)
        disp = (jnp.zeros((cfg.n_experts * cap, d), xs.dtype)
                .at[scatter_idx].set(xs, mode="drop", unique_indices=True))

        # all_to_all by destination shard: shard s owns experts
        # [s*E/S, (s+1)*E/S) -> send [S, e_local*cap, d]; receive the same
        # shape where recv[j] is source device j's buckets for MY experts
        disp = disp.reshape(n_shards, e_local * cap, d)
        recv = jax.lax.all_to_all(disp, axis, 0, 0, tiled=False)

        # regroup by local expert and run E/S RESIDENT batched matmuls —
        # weights are never replicated per token
        recv = recv.reshape(n_shards, e_local, cap, d)
        by_exp = recv.transpose(1, 0, 2, 3).reshape(
            e_local, n_shards * cap, d)                       # [E/S, K, d]
        h = jax.nn.relu(
            jnp.einsum("ekd,edh->ekh", by_exp, p["w1"])
            + p["b1"][:, None, :])
        out = (jnp.einsum("ekh,ehd->ekd", h, p["w2"])
               + p["b2"][:, None, :])                         # [E/S, K, d]

        # send results home (inverse regroup + all_to_all)
        out = out.reshape(e_local, n_shards, cap, d).transpose(1, 0, 2, 3)
        back = jax.lax.all_to_all(
            out.reshape(n_shards, e_local * cap, d), axis, 0, 0,
            tiled=False)
        back = back.reshape(cfg.n_experts * cap, d)
        token_out = back[jnp.where(keep, flat_idx, 0)]        # [n_sub, d]
        mine = jnp.where(keep[:, None], token_out * gate[:, None], 0.0)
        # restore the full data-row shard from the per-device slices
        return jax.lax.all_gather(mine, axis, axis=0).reshape(n_local, d)

    param_specs = {
        "router": P(),
        "w1": P(axis), "b1": P(axis), "w2": P(axis), "b2": P(axis),
    }
    return shard_map_over(
        mesh, device_body,
        in_specs=(param_specs, P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
    )(params, x)

"""Distributed joint training step for the neural branches.

The reference's trainer (model_trainer.py:41-121) trains XGBoost/iforest
offline on a single CPU and never trains the LSTM/BERT/GNN at all
(model_trainer.py docstring claim vs SURVEY.md §3.5). Here training is a
first-class distributed program: one jitted step computes the joint loss of
all three neural branches and updates them with optax, with

- **DP** over the ``data`` mesh axis (gradient all-reduce inserted by XLA
  because params are replicated over ``data``), and
- **TP** for the DistilBERT branch over ``model`` (parallel.layouts specs).

``init_train_state`` device_puts params according to the layout table before
``optimizer.init``, so Adam moments inherit the exact same shardings and the
whole state stays distributed across steps.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh

from realtime_fraud_detection_tpu.models.bert import BertConfig, bert_logits
from realtime_fraud_detection_tpu.models.gnn import gnn_logits
from realtime_fraud_detection_tpu.models.lstm import lstm_logits
from realtime_fraud_detection_tpu.parallel.layouts import (
    batch_shardings,
    bert_param_specs,
    tree_specs_to_shardings,
)
from realtime_fraud_detection_tpu.training.neural import bce_loss


@struct.dataclass
class TrainBatch:
    """Dense supervised batch for the three neural branches."""

    features: jax.Array          # f32[B, 64] (the §2.3 contract)
    history: jax.Array           # f32[B, T, F]
    history_len: jax.Array       # i32[B]
    user_feat: jax.Array         # f32[B, D]
    merchant_feat: jax.Array     # f32[B, D]
    user_neigh_feat: jax.Array   # f32[B, K, D]
    user_neigh_mask: jax.Array   # bool[B, K]
    merch_neigh_feat: jax.Array  # f32[B, K, D]
    merch_neigh_mask: jax.Array  # bool[B, K]
    token_ids: jax.Array         # i32[B, S]
    token_mask: jax.Array        # bool[B, S]
    labels: jax.Array            # f32[B] fraud ground truth


@struct.dataclass
class TrainState:
    params: Dict[str, Any]       # {"lstm": ..., "gnn": ..., "bert": ...}
    opt_state: Any
    step: jax.Array


def neural_param_shardings(mesh: Mesh, params: Dict[str, Any]) -> Dict[str, Any]:
    """Layout table for the joint neural param dict (bert TP, rest replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), {
        "lstm": params["lstm"], "gnn": params["gnn"],
    })
    bert = tree_specs_to_shardings(mesh, bert_param_specs(params["bert"]))
    return {"lstm": rep["lstm"], "gnn": rep["gnn"], "bert": bert}


def init_train_state(
    mesh: Mesh,
    params: Dict[str, Any],
    optimizer: optax.GradientTransformation,
) -> TrainState:
    """Distribute params per the layout table, then init the optimizer on the
    already-sharded params so moments land with identical shardings."""
    sharded = jax.device_put(params, neural_param_shardings(mesh, params))
    opt_state = optimizer.init(sharded)
    return TrainState(params=sharded, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32))


def joint_loss(
    params: Dict[str, Any],
    batch: TrainBatch,
    bert_config: BertConfig,
    use_pallas: bool = False,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Sum of per-branch BCE losses + per-branch aux dict."""
    lstm_l = bce_loss(
        lstm_logits(params["lstm"], batch.history, batch.history_len),
        batch.labels,
    )
    gnn_l = bce_loss(
        gnn_logits(
            params["gnn"], batch.features, batch.user_feat,
            batch.merchant_feat, batch.user_neigh_feat, batch.user_neigh_mask,
            batch.merch_neigh_feat, batch.merch_neigh_mask,
        ),
        batch.labels,
    )
    logits2 = bert_logits(
        params["bert"], batch.token_ids, batch.token_mask, bert_config,
        use_pallas=use_pallas,
    )
    bert_l = bce_loss(logits2[:, 1] - logits2[:, 0], batch.labels)
    total = lstm_l + gnn_l + bert_l
    return total, {"lstm": lstm_l, "gnn": gnn_l, "bert": bert_l}


def make_train_step(
    optimizer: optax.GradientTransformation,
    bert_config: BertConfig,
    use_pallas: bool = False,
    donate: bool = True,
) -> Callable[[TrainState, TrainBatch], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted joint train step.

    Sharding is carried by the arrays themselves (init_train_state +
    layouts.batch_shardings); jit propagates it and XLA inserts the DP
    gradient all-reduce and the TP all-reduce pair per BERT block.
    """

    def step(state: TrainState, batch: TrainBatch):
        (loss, aux), grads = jax.value_and_grad(joint_loss, has_aux=True)(
            state.params, batch, bert_config, use_pallas
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        metrics = {"loss": loss, **aux}
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def shard_train_batch(mesh: Mesh, batch: TrainBatch) -> TrainBatch:
    """Device-put a host batch with every leaf sharded over ``data``."""
    return jax.device_put(batch, batch_shardings(mesh, batch))

"""Sharding layouts: how every parameter and batch tensor maps onto the mesh.

The reference's only distribution strategy is data parallelism (Kafka
partitions x Flink parallelism 12, SURVEY.md §2.8); its "communication
backend" is Kafka + Flink's netty shuffle. The TPU-native equivalent is a
named-axis layout table: annotate shardings here, and XLA's SPMD partitioner
inserts the ICI collectives (the NCCL analog) automatically.

Layout policy:
- batch tensors: leading dim over ``data`` — pure DP, the Flink analog;
- the DistilBERT encoder (the only branch with enough FLOPs to want it) gets
  Megatron-style tensor parallelism over ``model``: q/k/v and ffn1 split on
  the output feature dim, o and ffn2 on the input dim, so each attention+FFN
  block needs exactly one all-reduce pair, riding ICI;
- every other branch (GBDT, iforest, LSTM, GraphSAGE) is tiny: replicated
  params, sharded batch.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import DATA_AXIS, MODEL_AXIS


def _named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh):
    return _named(mesh, P())


def batch_spec() -> P:
    return P(DATA_AXIS)


def bert_layer_specs() -> Dict[str, Any]:
    """Megatron TP specs for one encoder layer (column/row parallel pairs)."""
    col = {"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)}       # split heads/ffn
    row = {"w": P(MODEL_AXIS, None), "b": P()}                 # partial-sum in
    ln = {"scale": P(), "bias": P()}
    return {
        "q": col, "k": col, "v": col, "o": row,
        "attn_ln": ln,
        "ffn1": col, "ffn2": row,
        "ffn_ln": ln,
    }


def bert_param_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching models.bert.init_bert_params' structure."""
    dense_rep = {"w": P(), "b": P()}
    ln = {"scale": P(), "bias": P()}
    return {
        "word_emb": P(None, None),   # gathered by token ids; keep replicated
        "pos_emb": P(None, None),
        "emb_ln": ln,
        "layers": [bert_layer_specs() for _ in params["layers"]],
        "pre_classifier": dense_rep,
        "classifier": dense_rep,
    }


def scoring_model_specs(models) -> Any:
    """PartitionSpec pytree for a full ScoringModels set.

    Trees/iforest/LSTM/GNN are replicated (far below the ~1 MB/chip where TP
    would pay); the BERT branch is TP over ``model``.
    """
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)  # noqa: E731
    return type(models)(
        trees=rep(models.trees),
        iforest=rep(models.iforest),
        lstm=rep(models.lstm),
        gnn=rep(models.gnn),
        bert=bert_param_specs(models.bert),
    )


def tree_specs_to_shardings(mesh: Mesh, specs: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: _named(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# Serving-plane STORAGE specs (scoring/mesh_executor.py)
#
# The mesh serving path has a contract the training specs above do not:
# scores must be BIT-IDENTICAL to single-device scoring (rtfd mesh-drill
# pins it). Megatron-style row-parallel compute ends each block in a
# partial-sum all-reduce, which reorders float additions — allclose-safe
# (the dryrun's TP gate) but not bit-safe. So the serving plane shards the
# BYTES, not the math: params live sharded over ``model`` at rest (the
# per-chip HBM win — the cap the ROADMAP names) and the fused program
# re-gathers each sharded branch at its use seam
# (mesh_executor._regather_models — ZeRO-3/FSDP semantics). The all-gather
# reconstructs exact bytes, every branch then computes replicated per
# model shard, and the batch stays sharded over ``data`` (the FLOPs win).
#
# The specs below keep the Megatron COLUMN/ROW positions anyway (q/k/v/
# ffn1 split the output feature dim, o/ffn2 the input dim, embeddings the
# vocab/position rows) so flipping a branch to true compute-sharding later
# is a one-line gather removal, not a re-layout. Every sharded dim is
# guarded for divisibility by the model-axis size — an indivisible leaf
# falls back to replicated rather than failing the device_put.
# ---------------------------------------------------------------------------

# ScoringModels fields that can take the sharded placement, keyed by the
# registry branch names (scoring/pipeline.MODEL_NAMES). Trees/iforest stay
# replicated always: far below the bytes where sharding pays, and their
# int-heavy pytrees gain nothing from a gather seam.
SHARDABLE_BRANCHES: Dict[str, str] = {
    "bert_text": "bert",
    "lstm_sequential": "lstm",
    "graph_neural": "gnn",
}


def _dim_spec(shape: Sequence[int], dim: int, axis_size: int) -> P:
    """P sharding ``dim`` over ``model`` when divisible, else replicated."""
    if axis_size <= 1 or not shape or shape[dim] % axis_size:
        return P()
    spec = [None] * len(shape)
    spec[dim] = MODEL_AXIS
    return P(*spec)


def leaf_storage_spec(leaf: Any, axis_size: int) -> P:
    """Generic storage spec for one serving param leaf: shard the largest
    dim divisible by the model-axis size, else replicate. The rule the
    LSTM/GNN branches use — their pytrees are flat w/b dicts with no
    attention/FFN structure to honor. The typed-graph GNN's per-node-type
    projection squares (``w_node_user``/``w_node_merchant``/
    ``w_node_device``/``w_node_ip``, models/gnn.init_gnn_params
    ``typed=True``) are (D, D) leaves in the same flat dict and take this
    rule unchanged — D=16 divides every practical model-axis size, so the
    new params store sharded wherever the rest of the branch does
    (pinned in tests/test_graph.py)."""
    shape = np.shape(leaf)
    if axis_size <= 1 or not shape:
        return P()
    for dim in sorted(range(len(shape)), key=lambda d: -shape[d]):
        if shape[dim] % axis_size == 0 and shape[dim] >= axis_size:
            return _dim_spec(shape, dim, axis_size)
    return P()


def _dense_storage_specs(p: Dict[str, Any], axis_size: int,
                         column: bool) -> Dict[str, P]:
    """Storage specs for one dense layer dict, f32 ``{"w", "b"}`` or
    weight-only int8 ``{"qw", "scale", "b"}`` (models/quant.py layout).
    ``column``: split the output feature dim (q/k/v/ffn1) — the bias and
    the per-output-channel scale split with it; row layers (o/ffn2) split
    the input dim and keep bias/scale whole."""
    wkey = "qw" if "qw" in p else "w"
    wdim = 1 if column else 0
    specs: Dict[str, P] = {
        wkey: _dim_spec(np.shape(p[wkey]), wdim, axis_size),
    }
    out_split = (column
                 and specs[wkey] != P())      # output dim actually sharded
    if "scale" in p:
        specs["scale"] = (_dim_spec(np.shape(p["scale"]), 0, axis_size)
                          if out_split else P())
    specs["b"] = (_dim_spec(np.shape(p["b"]), 0, axis_size)
                  if out_split else P())
    return specs


def _embedding_storage_spec(table: Any, axis_size: int) -> Any:
    """Embedding storage specs: rows (vocab/positions) over ``model`` —
    both the bare f32 table and the quantized ``{"qe", "scale"}`` form
    (per-row scales shard with their rows)."""
    if isinstance(table, dict) and "qe" in table:
        rows_spec = _dim_spec(np.shape(table["qe"]), 0, axis_size)
        if rows_spec != P():
            return {"qe": rows_spec,
                    "scale": _dim_spec(np.shape(table["scale"]), 0,
                                       axis_size)}
        # rows indivisible (e.g. vocab 30522 on a 4-way axis): split the
        # hidden dim instead — per-row scales then stay whole
        return {"qe": _dim_spec(np.shape(table["qe"]), 1, axis_size),
                "scale": P()}
    spec = _dim_spec(np.shape(table), 0, axis_size)
    if spec == P():
        spec = _dim_spec(np.shape(table), 1, axis_size)
    return spec


def bert_serving_param_specs(params: Dict[str, Any],
                             axis_size: int) -> Dict[str, Any]:
    """Storage-spec pytree for the BERT branch, f32 OR weight-only int8.

    Megatron positions (column: q/k/v/ffn1, row: o/ffn2; embeddings over
    rows); layer norms and the 2-logit head stay replicated — they are a
    rounding error in bytes and the head feeds the decision ladder."""
    ln = {"scale": P(), "bias": P()}
    rep_dense = lambda p: {k: P() for k in p}                 # noqa: E731
    return {
        "word_emb": _embedding_storage_spec(params["word_emb"], axis_size),
        "pos_emb": _embedding_storage_spec(params["pos_emb"], axis_size),
        "emb_ln": ln,
        "layers": [{
            "q": _dense_storage_specs(layer["q"], axis_size, column=True),
            "k": _dense_storage_specs(layer["k"], axis_size, column=True),
            "v": _dense_storage_specs(layer["v"], axis_size, column=True),
            "o": _dense_storage_specs(layer["o"], axis_size, column=False),
            "attn_ln": ln,
            "ffn1": _dense_storage_specs(layer["ffn1"], axis_size,
                                         column=True),
            "ffn2": _dense_storage_specs(layer["ffn2"], axis_size,
                                         column=False),
            "ffn_ln": ln,
        } for layer in params["layers"]],
        "pre_classifier": rep_dense(params["pre_classifier"]),
        "classifier": rep_dense(params["classifier"]),
    }


def branch_serving_specs(models: Any, axis_size: int,
                         shard_branches: Sequence[str]) -> Any:
    """Storage-spec pytree for a full ScoringModels set under a per-branch
    placement: branches named in ``shard_branches`` (registry names, must
    be SHARDABLE_BRANCHES members) store sharded over ``model``; everything
    else — trees, iforest, and any un-named branch — replicates."""
    for name in shard_branches:
        if name not in SHARDABLE_BRANCHES:
            raise ValueError(
                f"branch {name!r} is not shardable; expected one of "
                f"{sorted(SHARDABLE_BRANCHES)} (trees/iforest/rules are "
                f"replicated by design)")
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)  # noqa: E731
    sharded = set(shard_branches) if axis_size > 1 else set()
    return type(models)(
        trees=rep(models.trees),
        iforest=rep(models.iforest),
        lstm=(jax.tree_util.tree_map(
            lambda lf: leaf_storage_spec(lf, axis_size), models.lstm)
            if "lstm_sequential" in sharded else rep(models.lstm)),
        gnn=(jax.tree_util.tree_map(
            lambda lf: leaf_storage_spec(lf, axis_size), models.gnn)
            if "graph_neural" in sharded else rep(models.gnn)),
        bert=(bert_serving_param_specs(models.bert, axis_size)
              if "bert_text" in sharded else rep(models.bert)),
    )


def batch_shardings(mesh: Mesh, tree: Any) -> Any:
    """NamedShardings sharding every leaf's leading dim over ``data``."""

    def _spec(x):
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return _named(mesh, P())
        return _named(mesh, P(DATA_AXIS, *([None] * (nd - 1))))

    return jax.tree_util.tree_map(_spec, tree)

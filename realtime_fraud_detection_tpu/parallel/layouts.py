"""Sharding layouts: how every parameter and batch tensor maps onto the mesh.

The reference's only distribution strategy is data parallelism (Kafka
partitions x Flink parallelism 12, SURVEY.md §2.8); its "communication
backend" is Kafka + Flink's netty shuffle. The TPU-native equivalent is a
named-axis layout table: annotate shardings here, and XLA's SPMD partitioner
inserts the ICI collectives (the NCCL analog) automatically.

Layout policy:
- batch tensors: leading dim over ``data`` — pure DP, the Flink analog;
- the DistilBERT encoder (the only branch with enough FLOPs to want it) gets
  Megatron-style tensor parallelism over ``model``: q/k/v and ffn1 split on
  the output feature dim, o and ffn2 on the input dim, so each attention+FFN
  block needs exactly one all-reduce pair, riding ICI;
- every other branch (GBDT, iforest, LSTM, GraphSAGE) is tiny: replicated
  params, sharded batch.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import DATA_AXIS, MODEL_AXIS


def _named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh):
    return _named(mesh, P())


def batch_spec() -> P:
    return P(DATA_AXIS)


def bert_layer_specs() -> Dict[str, Any]:
    """Megatron TP specs for one encoder layer (column/row parallel pairs)."""
    col = {"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)}       # split heads/ffn
    row = {"w": P(MODEL_AXIS, None), "b": P()}                 # partial-sum in
    ln = {"scale": P(), "bias": P()}
    return {
        "q": col, "k": col, "v": col, "o": row,
        "attn_ln": ln,
        "ffn1": col, "ffn2": row,
        "ffn_ln": ln,
    }


def bert_param_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching models.bert.init_bert_params' structure."""
    dense_rep = {"w": P(), "b": P()}
    ln = {"scale": P(), "bias": P()}
    return {
        "word_emb": P(None, None),   # gathered by token ids; keep replicated
        "pos_emb": P(None, None),
        "emb_ln": ln,
        "layers": [bert_layer_specs() for _ in params["layers"]],
        "pre_classifier": dense_rep,
        "classifier": dense_rep,
    }


def scoring_model_specs(models) -> Any:
    """PartitionSpec pytree for a full ScoringModels set.

    Trees/iforest/LSTM/GNN are replicated (far below the ~1 MB/chip where TP
    would pay); the BERT branch is TP over ``model``.
    """
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)  # noqa: E731
    return type(models)(
        trees=rep(models.trees),
        iforest=rep(models.iforest),
        lstm=rep(models.lstm),
        gnn=rep(models.gnn),
        bert=bert_param_specs(models.bert),
    )


def tree_specs_to_shardings(mesh: Mesh, specs: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: _named(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_shardings(mesh: Mesh, tree: Any) -> Any:
    """NamedShardings sharding every leaf's leading dim over ``data``."""

    def _spec(x):
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return _named(mesh, P())
        return _named(mesh, P(DATA_AXIS, *([None] * (nd - 1))))

    return jax.tree_util.tree_map(_spec, tree)

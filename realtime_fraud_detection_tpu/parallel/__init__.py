"""Parallelism layer: sharding layouts, collectives, distributed training.

The ICI/DCN equivalent of the reference's Kafka + Flink-shuffle + Redis
communication fabric (SURVEY.md §2.8/§5.8), expressed as named-axis
shardings that XLA lowers to collectives.
"""

from realtime_fraud_detection_tpu.parallel.context import (  # noqa: F401
    bert_context_parallel_predict,
    ring_attention,
)
from realtime_fraud_detection_tpu.parallel.experts import (  # noqa: F401
    MoEConfig,
    init_moe_params,
    moe_ffn,
    moe_ffn_reference,
)
from realtime_fraud_detection_tpu.parallel.pipeline import (  # noqa: F401
    bert_pipeline_encode,
    pipeline_forward,
    stack_stage_params,
)
from realtime_fraud_detection_tpu.parallel.layouts import (  # noqa: F401
    batch_shardings,
    bert_param_specs,
    scoring_model_specs,
    tree_specs_to_shardings,
)
from realtime_fraud_detection_tpu.parallel.train import (  # noqa: F401
    TrainBatch,
    TrainState,
    init_train_state,
    joint_loss,
    make_train_step,
    neural_param_shardings,
    shard_train_batch,
)

"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

The reference has no sequence parallelism anywhere (SURVEY.md §5.7) — its
longest context is the text branch's 512 tokens. This framework keeps
long-context first-class anyway: the same online-softmax accumulation that
the Pallas flash kernel (ops/attention.py) runs over k-blocks is run here
over *devices* — each device owns one sequence shard of K/V and rotates it
around the ring via ``ppermute`` while every device's Q shard stays put.
After ``seq_size()`` hops each Q block has seen every K/V block, with ICI
transfers overlapping compute hop by hop. Numerics are identical to dense
attention (softmax in f32, one global normalization at the end).

Layout convention matches ops/attention.py: q/k/v are [B, H, S, D] with a
bool ``key_mask`` [B, S] for padding; globally the batch dim is sharded over
``data`` and the sequence dim over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import DATA_AXIS, SEQ_AXIS
from realtime_fraud_detection_tpu.parallel.collectives import (
    ppermute_seq,
    seq_size,
    shard_map_over,
)

NEG_INF = -1e30


def _ring_attention_local(q, k, v, mask):
    """Per-device body (runs under shard_map, manual axes).

    q: [B, H, Sq, D] local query shard (stationary)
    k, v: [B, H, Sk, D] local key/value shard (rotates around the ring)
    mask: [B, Sk] validity of the local key shard (rotates with k/v)
    """
    d = q.shape[-1]
    qf = q.astype(jnp.float32) * (1.0 / float(np.sqrt(d)))
    n_hops = seq_size()

    def hop(_, carry):
        acc, m_prev, l_prev, k_cur, v_cur, mask_cur = carry
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32)
        )                                                   # [B,H,Sq,Sk] f32
        s = jnp.where(mask_cur[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m_prev, s.max(axis=-1))         # [B,H,Sq]
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l_prev * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32)
        )
        # rotate the K/V shard (and its mask) one step around the ring; the
        # final rotation returns them to their home device (no-op cost-wise
        # relative to the n-1 useful hops, keeps the loop branch-free)
        k_nxt = ppermute_seq(k_cur)
        v_nxt = ppermute_seq(v_cur)
        mask_nxt = ppermute_seq(mask_cur)
        return acc, m_new, l_new, k_nxt, v_nxt, mask_nxt

    b, h, sq, _ = q.shape
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc, _, l, _, _, _ = jax.lax.fori_loop(
        0, n_hops, hop, (acc0, m0, l0, k, v, mask)
    )
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    key_mask: jax.Array | None = None,
) -> jax.Array:
    """Context-parallel attention over global [B, H, S, D] arrays.

    B is sharded over ``data``, S over ``seq``; S must divide evenly by the
    seq-axis size. Works on any mesh built by ``core.mesh.build_mesh`` —
    with seq=1 it degrades to one local flash pass (identical code path).
    """
    b, _, s, _ = q.shape
    n_seq = mesh.shape[SEQ_AXIS]
    if s % n_seq:
        raise ValueError(f"seq len {s} not divisible by seq axis {n_seq}")
    if key_mask is None:
        key_mask = jnp.ones((b, s), bool)

    qkv_spec = P(DATA_AXIS, None, SEQ_AXIS, None)
    mask_spec = P(DATA_AXIS, SEQ_AXIS)
    fn = shard_map_over(
        mesh,
        _ring_attention_local,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, key_mask)


import functools


@functools.partial(jax.jit, static_argnames=("mesh", "config"))
def _cp_bert_forward(params, ids, mask, mesh, config):
    from realtime_fraud_detection_tpu.models.bert import bert_predict

    return bert_predict(
        params, ids, mask, config,
        attention_fn=lambda q, k, v, m: ring_attention(mesh, q, k, v, m),
    )


def bert_context_parallel_predict(
    mesh: Mesh,
    params,
    input_ids: jax.Array,       # i32[B, S]
    attention_mask: jax.Array,  # bool[B, S]
    config,
) -> jax.Array:
    """Long-context text-branch forward with the sequence dim sharded over
    the ``seq`` mesh axis.

    Attention runs as ring attention; every other op in the encoder
    (embeddings, layernorm, FFN matmuls, residuals) is per-token, so with
    the activations laid out P(data, seq, ...) XLA partitions them along S
    with no further annotation. Only the [CLS] pooling gathers across
    shards at the end. Numerics match the single-device encoder.

    At the reference's 512-token ceiling this is optional; it is the
    scaling path for long-context work (SURVEY.md §5.7).
    """
    from jax.sharding import NamedSharding

    ids = jax.device_put(input_ids, NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS)))
    mask = jax.device_put(
        attention_mask, NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS)))
    # replicate params onto THIS mesh: arrays restored from checkpoint (or
    # any earlier device_put) arrive committed to one device and would
    # clash with the mesh-sharded activations (same hazard FraudScorer.
    # set_models handles). No-op when already replicated, so repeated calls
    # don't re-copy; the forward itself is jitted (mesh/config static) so
    # layers trace once per (mesh, config, shapes).
    params = jax.device_put(params, NamedSharding(mesh, P()))
    return _cp_bert_forward(params, ids, mask, mesh, config)

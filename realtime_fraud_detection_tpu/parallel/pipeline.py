"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference scales only by data parallelism (Flink parallelism 12,
SURVEY.md §2.8); this framework adds pipeline parallelism as a first-class
mesh axis so models deeper than one chip's HBM (or latency budget) split by
LAYER SPAN instead of by tensor. Design, TPU-first:

- Stage parameters are stacked on a leading ``[n_stages, ...]`` dim and
  sharded over the pipeline axis — each device materializes only its own
  span's weights (1/S of the model).
- The schedule is a single ``lax.scan`` inside ``shard_map``: every tick,
  each device runs its stage on the activation it holds, then the
  activations rotate one hop along the ring via ``ppermute`` — the same
  compute/ICI-overlap pattern as ring attention (parallel/context.py), with
  the pipeline bubble (S-1 idle ticks) amortized by M microbatches.
- The last stage's outputs are replicated with a ``psum`` over the axis
  (every other device contributes zeros), so callers get a full [M, ...]
  result on every device — composable with data parallelism on ``data``.
- The whole schedule is differentiable (scan + ppermute have transposes),
  so ``jax.grad`` through ``pipeline_forward`` yields 1B1F-style reverse
  scheduling from XLA with no hand-written backward pass.

No counterpart exists in the reference; the contract here is numerical
equivalence with the sequential layer stack (tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import MODEL_AXIS
from realtime_fraud_detection_tpu.parallel.collectives import shard_map_over

__all__ = ["pipeline_forward", "stack_stage_params", "bert_pipeline_encode",
           "PIPELINE_AXIS"]

# default pipeline axis: reuse the ``model`` mesh axis — tensor and pipeline
# parallelism partition the same weight dimension budget, pick per model
PIPELINE_AXIS = MODEL_AXIS


def stack_stage_params(per_stage_params: list) -> Any:
    """[p_0, ..., p_{S-1}] pytrees -> one pytree with leading stage dim S.

    The result is what ``pipeline_forward`` shards over the pipeline axis
    (each device holds rows of its own stage only)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_forward(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    axis: str = PIPELINE_AXIS,
) -> jax.Array:
    """Run ``stage_fn`` S times over each of M microbatches, pipelined.

    mesh:        mesh containing ``axis`` (size S = number of stages)
    stage_fn:    (params_for_one_stage, h) -> h' where h is an array
                 [mb, ...] or a PYTREE of arrays (e.g. (hidden, mask) so
                 per-microbatch side inputs ride the pipeline); shapes must
                 be stage-invariant
    stage_params: pytree with leading dim S (see ``stack_stage_params``)
    microbatches: pytree of [M, mb, ...] arrays (replicated over ``axis``)

    Returns the same pytree with [M, mb, ...] outputs, replicated over
    ``axis``. Total ticks = M + S - 1; efficiency = M / (M + S - 1), so
    use M >= 4*S in earnest.
    """
    n_stages = mesh.shape[axis]
    n_micro = jax.tree.leaves(microbatches)[0].shape[0]

    def device_body(params, mb):
        # params: [S, ...] (replicated), mb: [M, mb, ...] (replicated).
        # Each device gathers its own stage's rows by axis index rather
        # than receiving a P(axis)-split input: jax 0.4.x's partitioner
        # miscompiles shard_map inputs split over a non-leading mesh axis
        # when the operand is a traced INTERMEDIATE (values arrive scaled
        # by the data-axis size — a spurious cross-axis reduction), and
        # callers like bert_pipeline_encode stack the stage params inside
        # their jit. Replicated-in + local gather is immune, at the cost
        # of each device holding all S stages' weights — revisit when the
        # models outgrow per-device HBM.
        stage = jax.lax.axis_index(axis)
        my_params = jax.tree.map(
            lambda x: jax.lax.dynamic_index_in_dim(
                x, stage, axis=0, keepdims=False), params)
        is_first = stage == 0
        is_last = stage == n_stages - 1
        zero = jax.tree.map(lambda m: jnp.zeros_like(m[0]), mb)
        outputs0 = jax.tree.map(jnp.zeros_like, mb)

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 injects microbatch t while t < M; later stages use
            # the activation that arrived over the ring last tick
            t_idx = jnp.minimum(t, n_micro - 1)
            inj = jax.tree.map(
                lambda m: jax.lax.dynamic_index_in_dim(
                    m, t_idx, axis=0, keepdims=False), mb)
            h_in = jax.tree.map(
                lambda a, b: jnp.where(is_first, a, b), inj, incoming)
            h_out = stage_fn(my_params, h_in)
            # the last stage banks its result at slot t-(S-1) once the
            # pipeline has filled; everyone else banks zeros (psum later)
            slot = t - (n_stages - 1)
            valid = is_last & (slot >= 0) & (slot < n_micro)
            slot_c = jnp.maximum(slot, 0)
            outputs = jax.tree.map(
                lambda o, h: jax.lax.dynamic_update_index_in_dim(
                    o,
                    jnp.where(valid, h, jax.lax.dynamic_index_in_dim(
                        o, slot_c, axis=0, keepdims=False)),
                    slot_c, axis=0),
                outputs, h_out)
            # rotate activations one hop down the pipeline ring
            nxt = jax.lax.ppermute(
                h_out, axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (zero, outputs0), jnp.arange(n_micro + n_stages - 1))
        # replicate the last stage's banked outputs to every stage device
        return jax.lax.psum(
            jax.tree.map(
                lambda o: jnp.where(is_last, o, jnp.zeros_like(o)), outputs),
            axis)

    in_specs = (
        jax.tree.map(lambda _: P(), stage_params),   # replicated; see body
        P(),                                     # microbatches replicated
    )
    return shard_map_over(
        mesh, device_body, in_specs=in_specs, out_specs=P(),
    )(stage_params, microbatches)


def bert_pipeline_encode(
    mesh: Mesh,
    params: Any,
    input_ids: jax.Array,       # i32[B, S]
    attention_mask: jax.Array,  # bool[B, S]
    config: Any,                # models.bert.BertConfig
    n_micro: int = 4,
    axis: str = PIPELINE_AXIS,
    use_pallas: bool = False,
) -> jax.Array:
    """DistilBERT encoder with its layers PIPELINED over ``axis``.

    Each device holds ``num_layers / S`` transformer blocks; hidden states
    (with their attention mask riding along as a pytree leaf) flow through
    the GPipe schedule in ``n_micro`` microbatches. Embeddings and the
    mask are computed replicated (they are ~free next to the blocks).
    Numerics are identical to the sequential ``models.bert.bert_encode``
    (tests/test_parallel.py pins it).
    """
    from realtime_fraud_detection_tpu.models.bert import (
        bert_embed,
        bert_layer,
    )

    n_stages = mesh.shape[axis]
    if config.num_layers % n_stages:
        raise ValueError(
            f"num_layers={config.num_layers} not divisible by the "
            f"{axis}-axis size {n_stages}")
    span = config.num_layers // n_stages
    b, s = input_ids.shape
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")

    x = bert_embed(params, input_ids, config)

    stage_params = stack_stage_params([
        {"layers": params["layers"][i * span:(i + 1) * span]}
        for i in range(n_stages)
    ])
    mb = b // n_micro
    micro_x = x.reshape(n_micro, mb, s, config.hidden_size)
    micro_mask = attention_mask.reshape(n_micro, mb, s)

    def stage_fn(p, h):
        hid, mask = h
        for layer in p["layers"]:
            hid = bert_layer(layer, hid, mask, config,
                             use_pallas=use_pallas)
        return (hid, mask)

    out_x, _ = pipeline_forward(
        mesh, stage_fn, stage_params, (micro_x, micro_mask), axis=axis)
    return out_x.reshape(b, s, config.hidden_size)

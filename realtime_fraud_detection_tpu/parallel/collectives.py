"""Named-axis collective wrappers: the framework's ICI/DCN communication API.

The reference moves data between parallel workers via Kafka partitions and
Flink's keyBy shuffle (SURVEY.md §5.8). Inside a jitted TPU program the
equivalents are XLA collectives over the mesh axes; these thin wrappers pin
the axis-name conventions so call sites never hard-code strings.

All of these are valid only inside ``shard_map`` (or vmapped/pjit code with
manual axes) over a mesh built by ``core.mesh.build_mesh``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def psum_data(x):
    """All-reduce over the data axis (gradient sync; the allreduce of DP)."""
    return jax.lax.psum(x, DATA_AXIS)


def pmean_data(x):
    return jax.lax.pmean(x, DATA_AXIS)


def psum_model(x):
    """All-reduce over the tensor-parallel axis (Megatron row-parallel sums)."""
    return jax.lax.psum(x, MODEL_AXIS)


def all_gather_seq(x, axis: int = 0):
    """Gather sequence shards (context-parallel rendezvous)."""
    return jax.lax.all_gather(x, SEQ_AXIS, axis=axis, tiled=True)


def _static_axis_size(axis: str) -> int:
    """Trace-time axis size as a Python int (needed for ppermute's static
    permutation and fori_loop trip counts). ``jax.lax.axis_size`` where it
    exists; on 0.4.x, read the axis environment the shard_map trace
    installed."""
    asz = getattr(jax.lax, "axis_size", None)
    if asz is not None:
        return asz(axis)
    from jax.core import axis_frame

    frame = axis_frame(axis)
    return int(getattr(frame, "size", frame))


def ppermute_seq(x, shift: int = 1):
    """Ring shift over the seq axis (ring attention's KV rotation)."""
    n = _static_axis_size(SEQ_AXIS)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, SEQ_AXIS, perm)


def reduce_scatter_data(x, axis: int = 0):
    """Reduce-scatter over data (ZeRO-style sharded gradient reduction)."""
    return jax.lax.psum_scatter(x, DATA_AXIS, scatter_dimension=axis, tiled=True)


def seq_index():
    return jax.lax.axis_index(SEQ_AXIS)


def seq_size():
    return _static_axis_size(SEQ_AXIS)


def shard_map_over(mesh: Mesh, fn, in_specs, out_specs, check_rep: bool = False):
    """``shard_map`` pinned to this framework's mesh axis names.

    Version shim: newer JAX exposes ``jax.shard_map`` with the
    ``check_vma`` keyword; 0.4.x has it at ``jax.experimental.shard_map``
    with ``check_rep``. Resolve whichever this interpreter ships — every
    collective call site goes through here, so the compatibility decision
    lives in exactly one place.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_rep)
        except TypeError:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_rep)


def identity_spec() -> P:
    return P()

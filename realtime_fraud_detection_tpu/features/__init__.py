from realtime_fraud_detection_tpu.features.schema import (  # noqa: F401
    TransactionBatch,
    encode_transactions,
    PAYMENT_METHODS,
    TRANSACTION_TYPES,
    CARD_TYPES,
    MERCHANT_CATEGORIES,
    KYC_STATUSES,
    RISK_LEVELS,
)
from realtime_fraud_detection_tpu.features.extract import (  # noqa: F401
    FEATURE_NAMES,
    NUM_FEATURES,
    extract_features,
    feature_index,
)
from realtime_fraud_detection_tpu.features.rules import (  # noqa: F401
    DECISIONS,
    RISK_LEVEL_NAMES,
    rule_score,
    make_decision,
    risk_level_code,
)
from realtime_fraud_detection_tpu.features.serving import ServingFeatureProcessor  # noqa: F401

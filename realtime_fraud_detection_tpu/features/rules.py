"""Rule-based fraud scoring + decision ladder, vectorized.

Reimplements the only scoring path actually wired into the reference's job
graph: ``TransactionProcessor.applyFraudDetectionRules`` / ``makeFinalDecision``
(reference TransactionProcessor.java:327-473). All branches become masked
arithmetic so the whole thing jits onto the VPU.

Unknown-profile semantics follow the processor's minimal profiles
(TransactionProcessor.java:489-508): unknown user -> risk 0.5, unverified,
brand-new account; unknown merchant -> "medium" risk, fraud rate 0.05, not
blacklisted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from realtime_fraud_detection_tpu.features.schema import TransactionBatch

DECISIONS: tuple[str, ...] = (
    "APPROVE", "APPROVE_WITH_MONITORING", "REVIEW", "DECLINE",
)
APPROVE, APPROVE_WITH_MONITORING, REVIEW, DECLINE = range(4)

RISK_LEVEL_NAMES: tuple[str, ...] = (
    "VERY_LOW", "LOW", "MEDIUM", "HIGH", "CRITICAL",
)
VERY_LOW, LOW, MEDIUM, HIGH, CRITICAL = range(5)

# The ONE copy of the ensemble risk-band rungs (ensemble_predictor.py:
# 358-369) — shared by the device ladder (risk_level_code), its host
# scalar twin (risk_level_name), and the host vectorized twin
# (risk_level_codes_np, the QoS rules-only degraded path).
RISK_LEVEL_THRESHOLDS: tuple[float, ...] = (0.3, 0.6, 0.8, 0.95)


# One shared rung-default definition (utils/config.py) re-exported for the
# device ladder (ensemble/combine.py) and this host-side twin.
from realtime_fraud_detection_tpu.utils.config import (  # noqa: E402
    DECLINE_THRESHOLD_DEFAULT,
    MONITOR_THRESHOLD_DEFAULT,
    REVIEW_THRESHOLD_DEFAULT,
)


def ensemble_decision_name(prob: float, confidence: float,
                           confidence_threshold: float = 0.7,
                           decline: float = DECLINE_THRESHOLD_DEFAULT,
                           review: float = REVIEW_THRESHOLD_DEFAULT,
                           monitor: float = MONITOR_THRESHOLD_DEFAULT) -> str:
    """Host-side scalar twin of ``ensemble.combine.ensemble_decision``
    (ensemble_predictor.py:344-356). Rung defaults match the device ladder;
    callers serving configured rungs must pass the SAME values here (the
    serving A/B path passes config.ensemble's) or variant-arm decisions
    would diverge from the compiled ladder."""
    if confidence < confidence_threshold:
        return DECISIONS[REVIEW]
    if prob >= decline:
        return DECISIONS[DECLINE]
    if prob >= review:
        return DECISIONS[REVIEW]
    if prob >= monitor:
        return DECISIONS[APPROVE_WITH_MONITORING]
    return DECISIONS[APPROVE]


def risk_level_name(prob: float) -> str:
    """Host-side scalar twin of ``risk_level_code``
    (ensemble_predictor.py:358-369)."""
    code = sum(prob >= t for t in RISK_LEVEL_THRESHOLDS)
    return RISK_LEVEL_NAMES[int(code)]


def risk_level_codes_np(probs) -> "np.ndarray":
    """Host VECTORIZED twin of ``risk_level_code`` over a numpy array —
    same rungs, same int codes; used where the device combine did not run
    (the QoS rules-only degraded path)."""
    import numpy as np

    probs = np.asarray(probs)
    code = np.zeros(probs.shape, np.int32)
    for t in RISK_LEVEL_THRESHOLDS:
        code += (probs >= t).astype(np.int32)
    return code


def model_confidence_value(prob: float, multiplier: float) -> float:
    """Host-side scalar twin of ``ensemble.combine.model_confidence``
    (ensemble_predictor.py:325-342)."""
    return min(1.0, abs(prob - 0.5) * 2.0 * multiplier)


@jax.jit
def rule_score(b: TransactionBatch) -> jax.Array:
    """Rule-based fraud score in [0, 1] (TransactionProcessor.java:327-439)."""
    f32 = lambda x: x.astype(jnp.float32)  # noqa: E731

    # Base: half-weight on the upstream score (:330-333)
    score = 0.5 * b.prior_fraud_score

    # User component (:353-375); unknown user -> minimal profile (risk 0.5,
    # age 0 -> new account, kyc pending -> unverified): 0.5*0.2 + 0.1 + 0.15
    user_known = (
        b.user_risk_score * 0.2
        + 0.1 * f32(b.account_age_days < 30)
        + 0.15 * f32(~b.user_verified)
    )
    score = score + jnp.where(b.has_user, user_known, jnp.float32(0.35))

    # Merchant component (:380-410); unknown merchant -> minimal profile
    # ("medium" 0.1, rate 0.05 not > 0.05, not blacklisted): 0.1
    merch_known = (
        0.2 * f32(b.merchant_risk_code == 2)
        + 0.1 * f32(b.merchant_risk_code == 1)
        + 0.4 * f32(b.merchant_blacklisted)
        + jnp.where(b.merchant_fraud_rate > 0.05, b.merchant_fraud_rate * 2.0, 0.0)
        + 0.15 * f32(b.merchant_high_risk_category)
    )
    score = score + jnp.where(b.has_merchant, merch_known, jnp.float32(0.1))

    # Feature flags (:415-439)
    large_amount = b.has_user & (b.user_avg_amount > 0) & (
        b.amount / jnp.maximum(b.user_avg_amount, 1e-9) > 5.0
    )
    # reference requires the txn to actually carry a fingerprint
    # (TransactionProcessor.java:252-262) — no penalty when it's absent
    new_device = (
        b.has_txn_fingerprint & b.has_user & b.has_device_list & ~b.known_device
    )
    unusual_hour = (b.hour_of_day <= 5) | (b.hour_of_day >= 23)
    outside_hours = b.has_merchant & b.has_op_hours & ~(
        (b.hour_of_day >= b.merchant_op_start) & (b.hour_of_day <= b.merchant_op_end)
    )
    score = (
        score
        + 0.15 * f32(large_amount)
        + 0.1 * f32(new_device)
        + 0.05 * f32(unusual_hour)
        + 0.1 * f32(outside_hours)
    )

    return jnp.clip(score, 0.0, 1.0)


def make_decision(
    score: jax.Array,
    blacklisted: jax.Array,
    fraud_threshold: float = 0.7,
) -> tuple[jax.Array, jax.Array]:
    """Decision + risk-level codes (TransactionProcessor.java:444-473).

    Ladder: >=0.9 DECLINE/CRITICAL, >=threshold REVIEW/HIGH, >=0.5
    APPROVE/MEDIUM, else APPROVE/LOW; blacklisted merchants override to
    DECLINE/CRITICAL. Returns (decision i32[B], risk_level i32[B]).
    """
    decision = jnp.where(
        score >= 0.9, DECLINE, jnp.where(score >= fraud_threshold, REVIEW, APPROVE)
    )
    risk = jnp.where(
        score >= 0.9, CRITICAL,
        jnp.where(score >= fraud_threshold, HIGH, jnp.where(score >= 0.5, MEDIUM, LOW)),
    )
    decision = jnp.where(blacklisted, DECLINE, decision).astype(jnp.int32)
    risk = jnp.where(blacklisted, CRITICAL, risk).astype(jnp.int32)
    return decision, risk


def risk_level_code(fraud_probability: jax.Array) -> jax.Array:
    """Five-level ensemble risk ladder (ensemble_predictor.py:358-369)."""
    t0, t1, t2, t3 = RISK_LEVEL_THRESHOLDS
    return (
        (fraud_probability >= t0).astype(jnp.int32)
        + (fraud_probability >= t1)
        + (fraud_probability >= t2)
        + (fraud_probability >= t3)
    ).astype(jnp.int32)


# ---------------------------------------------------------------- enrichment
@jax.jit
def enrichment_score(features: jax.Array) -> jax.Array:
    """Category-weighted feature score over the 64-wide feature tensor
    (FeatureEnrichmentProcessor.calculateFeatureBasedFraudScore,
    FeatureEnrichmentProcessor.java:122-344): six category sub-scores
    weighted .2/.1/.25/.2/.15/.1; only the weighted SUM is clipped to
    [0, 1] (java :149) — individual categories are unbounded there too.

    The reference builds this processor but never attaches it to the job
    graph (SURVEY.md §0.3); here it runs vectorized on device and is wired
    behind ``stream.JobConfig.enable_enrichment``.
    """
    from realtime_fraud_detection_tpu.features.extract import feature_index

    f = features.astype(jnp.float32)

    def col(name: str) -> jax.Array:
        return f[:, feature_index(name)]

    # amount (x0.2, :157-179)
    amount_cat = col("amount_category")
    amount = (
        0.3 * (col("is_large_for_user") > 0)
        + 0.1 * (col("is_round_100") > 0)
        + jnp.where(amount_cat >= 4, 0.2,
                    jnp.where(amount_cat < 1, 0.1, 0.0))  # very_large / micro
    )
    # temporal (x0.1, :184-206)
    temporal = (
        0.2 * (col("is_night_time") > 0)
        + 0.15 * (col("in_user_preferred_time") <= 0)
        + 0.1 * ((col("is_weekend") > 0)
                 & (col("weekend_activity_factor") < 0.3))
    )
    # user behavior (x0.25, :211-238)
    user = (
        jnp.where(col("is_very_new_account") > 0, 0.4,
                  jnp.where(col("is_new_account") > 0, 0.2, 0.0))
        + 0.3 * (col("is_kyc_verified") <= 0)
        + col("user_risk_score") * 0.5
    )
    # merchant risk (x0.2, :243-277)
    merchant = (
        0.8 * (col("is_blacklisted_merchant") > 0)
        + 0.3 * (col("is_high_risk_category") > 0)
        + col("merchant_fraud_rate") * 2.0
        + 0.2 * (col("suspicious_merchant_name") > 0)
        + 0.15 * (col("within_merchant_hours") <= 0)
    )
    # velocity (x0.15, :282-307)
    velocity = (
        0.6 * (col("high_velocity_5min") > 0)
        + 0.4 * (col("high_velocity_1hour") > 0)
        + 0.2 * (col("velocity_5min_count") > 3)
        + 0.15 * (col("velocity_1hour_count") > 10)
    )
    # device / network (x0.1, :312-334)
    device = (
        0.3 * (col("is_new_device") > 0)
        + col("ip_risk_score")
        + 0.2 * (col("suspicious_user_agent") > 0)
    )
    score = (
        amount * 0.2 + temporal * 0.1 + user * 0.25
        + merchant * 0.2 + velocity * 0.15 + device * 0.1
    )
    return jnp.clip(score, 0.0, 1.0)


@jax.jit
def blend_enrichment(prior_score: jax.Array, features: jax.Array):
    """60/40 blend of the prior score with the feature-based score, then
    re-level (FeatureEnrichmentProcessor.java:84-90, 341-367). Returns
    (blended f32[B], decision i32[B], risk_level i32[B]) where decision/
    risk follow the enrichment ladder: >=0.95 DECLINE/CRITICAL, >=0.8
    REVIEW/HIGH, >=0.6 REVIEW/MEDIUM, >=0.3 APPROVE/LOW, else
    APPROVE/VERY_LOW."""
    blended = jnp.clip(
        prior_score * 0.6 + enrichment_score(features) * 0.4, 0.0, 1.0
    )
    decision = jnp.where(
        blended >= 0.95, DECLINE,
        jnp.where(blended >= 0.6, REVIEW, APPROVE),
    ).astype(jnp.int32)
    return blended, decision, risk_level_code(blended)

"""The 64-feature contract, vectorized for TPU.

Reimplements ``FeatureExtractor.extractAllFeatures``
(reference FeatureExtractor.java:50-87) as a single jittable function
``TransactionBatch -> f32[B, 64]``. The canonical ordering below is this
framework's contract (the reference stores features in a Java HashMap whose
iteration order is unspecified — the 64-wide vector the serving side builds,
ensemble_predictor.py:221-250, was therefore never deterministic; we fix
that defect by pinning the order).

Null semantics: where the reference omits a key (profile missing, no
geolocation, ...), the dense vector holds the documented default — 0.0 for
everything except ``within_merchant_hours`` (default 1.0: "no operating-hours
info" must not look like "outside operating hours") and the unknown-profile
defaults applied at encode time (schema.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from realtime_fraud_detection_tpu.features.schema import TransactionBatch

# Canonical feature ordering — 8 categories, 64 names, matching the union of
# FeatureExtractor.java:92-382 emissions (amount 12, temporal 8, geographic 8,
# user 10, merchant 8, device/network 5, velocity 8, contextual 5).
FEATURE_NAMES: tuple[str, ...] = (
    # amount (12) — FeatureExtractor.java:92-131
    "amount", "amount_log", "amount_sqrt", "is_round_amount", "is_round_10",
    "is_round_100", "amount_to_user_avg_ratio", "amount_deviation_zscore",
    "is_large_for_user", "amount_to_merchant_avg_ratio", "is_large_for_merchant",
    "amount_category",
    # temporal (8) — :136-168
    "hour_of_day", "day_of_week", "day_of_month", "is_weekend", "time_period",
    "is_business_hours", "is_night_time", "in_user_preferred_time",
    # geographic (8) — :173-211
    "has_geolocation", "has_merchant_location", "latitude", "longitude",
    "is_high_risk_country", "distance_to_merchant_km", "user_intl_preference",
    "unexpected_intl_transaction",
    # user behavior (10) — :216-252
    "account_age_days", "is_new_account", "is_very_new_account",
    "user_risk_score", "is_kyc_verified", "kyc_status",
    "weekend_activity_factor", "online_preference", "user_avg_amount",
    "user_transaction_frequency",
    # merchant risk (8) — :257-296
    "merchant_risk_level", "merchant_fraud_rate", "is_blacklisted_merchant",
    "merchant_category", "is_high_risk_category", "within_merchant_hours",
    "merchant_risk_multiplier", "suspicious_merchant_name",
    # device / network (5) — :301-325
    "is_known_device", "is_new_device", "is_private_ip", "ip_risk_score",
    "suspicious_user_agent",
    # velocity (8) — :330-363
    "velocity_5min_count", "velocity_5min_amount", "velocity_1hour_count",
    "velocity_1hour_amount", "velocity_24hour_count", "velocity_24hour_amount",
    "high_velocity_5min", "high_velocity_1hour",
    # contextual (5) — :368-382
    "payment_method", "is_high_risk_payment", "transaction_type", "is_refund",
    "card_type",
)
NUM_FEATURES = len(FEATURE_NAMES)
assert NUM_FEATURES == 64

_INDEX = {name: i for i, name in enumerate(FEATURE_NAMES)}


def feature_index(name: str) -> int:
    return _INDEX[name]


def _haversine_km(lat1, lon1, lat2, lon2):
    """Haversine distance (FeatureExtractor.java:407-417)."""
    rad = jnp.pi / 180.0
    dlat = (lat2 - lat1) * rad
    dlon = (lon2 - lon1) * rad
    a = (
        jnp.sin(dlat / 2) ** 2
        + jnp.cos(lat1 * rad) * jnp.cos(lat2 * rad) * jnp.sin(dlon / 2) ** 2
    )
    return 6371.0 * 2.0 * jnp.arctan2(jnp.sqrt(a), jnp.sqrt(1.0 - a))


def top_feature_importances(importances, k: int = 10):
    """Top-k {feature name: score} from a per-feature importance vector.

    The reference surfaces this in prediction explanations
    (ensemble_predictor.py:371-435). Length must match the 64-name
    contract — a trainer fit on a different feature matrix must not get
    its indices silently mislabeled with canonical names.
    """
    import numpy as np

    arr = np.asarray(importances, np.float32)
    if arr.shape != (len(FEATURE_NAMES),):
        raise ValueError(
            f"importances shape {arr.shape} != ({len(FEATURE_NAMES)},) — "
            "not the canonical feature contract")
    order = np.argsort(arr)[::-1][:k]
    return {FEATURE_NAMES[i]: round(float(arr[i]), 6)
            for i in order if arr[i] > 0}


def extract_features_host(b: TransactionBatch):
    """``extract_features`` pinned to the host CPU backend. Returns f32[B, 64]
    as a NumPy array.

    The streaming assembler needs the feature rows host-side anyway (history
    store, feature-topic fan-out), and on a remote/tunneled TPU the
    ``np.asarray(extract_features(...))`` round trip costs a full network RTT
    per microbatch (~85 ms measured) for ~1 ms of arithmetic. Running the
    same jitted program on the CPU backend keeps the hot loop free of
    blocking device round trips; the device program still consumes the rows
    as part of the packed ScoreBatch transfer.
    """
    import numpy as np

    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        return np.asarray(extract_features(b))


@jax.jit
def extract_features(b: TransactionBatch) -> jax.Array:
    """Vectorized 64-feature extraction. Returns f32[B, 64]."""
    f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
    amount = f32(b.amount)
    hour = b.hour_of_day

    # --- amount (12)
    cents = jnp.round(amount * 100.0).astype(jnp.int32)
    has_user_avg = b.has_user & (b.user_avg_amount > 0)
    user_ratio = jnp.where(has_user_avg, amount / jnp.maximum(b.user_avg_amount, 1e-9), 0.0)
    user_z = jnp.where(
        has_user_avg, (amount - b.user_avg_amount) / jnp.maximum(b.user_avg_amount, 1e-9), 0.0
    )
    has_merch_avg = b.has_merchant & (b.merchant_avg_amount > 0)
    merch_ratio = jnp.where(
        has_merch_avg, amount / jnp.maximum(b.merchant_avg_amount, 1e-9), 0.0
    )
    amount_category = (
        (amount >= 10).astype(jnp.int32)
        + (amount >= 100)
        + (amount >= 1000)
        + (amount >= 10000)
    )

    # --- temporal (8); time_period: morning 0 / afternoon 1 / evening 2 / night 3
    time_period = jnp.where(
        (hour >= 6) & (hour < 12), 0,
        jnp.where((hour >= 12) & (hour < 18), 1, jnp.where((hour >= 18) & (hour < 22), 2, 3)),
    )
    in_preferred = (
        b.has_user & b.has_preferred_hours
        & (hour >= b.preferred_start) & (hour <= b.preferred_end)
    )

    # --- geographic (8)
    high_risk_loc = b.has_geo & (
        (jnp.abs(b.lat) > 60) | ((jnp.abs(b.lat) < 10) & (jnp.abs(b.lon) < 10))
    )
    both_geo = b.has_geo & b.has_merchant_geo
    dist = jnp.where(
        both_geo, _haversine_km(b.lat, b.lon, b.merchant_lat, b.merchant_lon), 0.0
    )
    intl_pref = jnp.where(b.has_user & b.has_intl_ratio, b.intl_ratio, 0.0)
    unexpected_intl = b.has_user & b.has_intl_ratio & (b.intl_ratio < 0.1)

    # --- user (10); unknown users: is_new/is_very_new true, risk 0.8 set at
    # encode (FeatureExtractor.java:244-251)
    is_new_account = jnp.where(b.has_user, b.account_age_days < 30, True)
    is_very_new = jnp.where(b.has_user, b.account_age_days < 7, True)

    # --- merchant (8)
    within_hours = jnp.where(
        b.has_merchant & b.has_op_hours,
        (hour >= b.merchant_op_start) & (hour <= b.merchant_op_end),
        True,
    )
    risk_mult = jnp.where(
        b.has_merchant & (b.merchant_risk_code == 0), 1.0,
        jnp.where(b.has_merchant & (b.merchant_risk_code == 1), 1.5, 2.0),
    )

    # --- velocity flags (FeatureExtractor.java:353-354)
    high_vel_5m = b.velocity_5min_count > 5
    high_vel_1h = b.velocity_1hour_count > 20

    cols = [
        # amount
        amount,
        jnp.log1p(jnp.maximum(amount, 0.0)),
        jnp.sqrt(jnp.maximum(amount, 0.0)),
        f32(cents % 100 == 0),
        f32(cents % 1000 == 0),
        f32(cents % 10000 == 0),
        user_ratio,
        user_z,
        f32(has_user_avg & (user_ratio > 3.0)),
        merch_ratio,
        f32(has_merch_avg & (amount > b.merchant_avg_amount * 2.0)),
        f32(amount_category),
        # temporal
        f32(hour),
        f32(b.day_of_week),
        f32(b.day_of_month),
        f32(b.is_weekend),
        f32(time_period),
        f32((hour >= 9) & (hour <= 17)),
        f32((hour <= 6) | (hour >= 22)),
        f32(in_preferred),
        # geographic
        f32(b.has_geo),
        f32(b.has_merchant_geo),
        jnp.where(b.has_geo, b.lat, 0.0),
        jnp.where(b.has_geo, b.lon, 0.0),
        f32(high_risk_loc),
        dist,
        intl_pref,
        f32(unexpected_intl),
        # user
        f32(b.account_age_days),
        f32(is_new_account),
        f32(is_very_new),
        f32(b.user_risk_score),
        f32(b.has_user & b.user_verified),
        f32(b.kyc_code),
        f32(b.weekend_activity),
        f32(b.online_preference),
        f32(b.user_avg_amount),
        f32(b.user_txn_frequency),
        # merchant
        f32(b.merchant_risk_code),
        f32(b.merchant_fraud_rate),
        f32(b.merchant_blacklisted),
        f32(b.merchant_category_code),
        f32(b.merchant_high_risk_category),
        f32(within_hours),
        f32(risk_mult),
        f32(b.suspicious_merchant_name),
        # device / network
        f32(b.known_device),
        f32(~b.known_device),
        f32(b.private_ip),
        f32(b.ip_risk),
        f32(b.suspicious_user_agent),
        # velocity
        f32(b.velocity_5min_count),
        f32(b.velocity_5min_amount),
        f32(b.velocity_1hour_count),
        f32(b.velocity_1hour_amount),
        f32(b.velocity_24hour_count),
        f32(b.velocity_24hour_amount),
        f32(high_vel_5m),
        f32(high_vel_1h),
        # contextual
        f32(b.payment_method_code),
        f32(b.high_risk_payment),
        f32(b.transaction_type_code),
        f32(b.transaction_type_code == 1),  # refund (TRANSACTION_TYPES[1])
        f32(b.card_type_code),
    ]
    return jnp.stack(cols, axis=-1)

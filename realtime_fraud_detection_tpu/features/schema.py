"""Transaction batch schema: struct-of-arrays + categorical encodings.

The reference passes transactions around as JSON dicts (simulator.py:78-101)
and Java POJOs (the reconstructed ``Transaction``/``UserProfile``/
``MerchantProfile`` of SURVEY.md section 2.10). A TPU program wants dense,
statically-shaped tensors, so ingest converts a list of transaction records +
profile lookups into a ``TransactionBatch``: one flat array per field, with
presence flags standing in for the reference's null checks.

Everything string-shaped (regex merchant-name analysis
FeatureExtractor.java:427-432, IP/user-agent analysis :434-451, device
fingerprint membership :307-313) is resolved host-side here, so the
device-side feature extractor is pure arithmetic.
"""

from __future__ import annotations

import re
from dataclasses import fields
from typing import Any, Dict, List, Mapping, Sequence

import jax
import numpy as np
from flax import struct

# --- categorical vocabularies (closed sets from the simulator,
#     simulator.py:255-266,330-332) -------------------------------------------
PAYMENT_METHODS = ("credit_card", "debit_card", "digital_wallet", "bank_transfer",
                   "crypto", "gift_card", "prepaid_card", "wire_transfer")
TRANSACTION_TYPES = ("purchase", "refund", "authorization")
CARD_TYPES = ("visa", "mastercard", "amex", "discover")
MERCHANT_CATEGORIES = ("retail", "grocery", "gas_station", "restaurant",
                       "online_retail", "gambling", "adult_entertainment",
                       "pharmacy", "jewelry", "electronics")
KYC_STATUSES = ("verified", "pending", "rejected")
RISK_LEVELS = ("low", "medium", "high")
# Categories the reference treats as high-risk (simulator risk_level='high')
HIGH_RISK_CATEGORIES = frozenset({"gambling", "adult_entertainment", "jewelry"})

UNKNOWN = -1  # encoding for absent/unknown categorical values


def _code(vocab: Sequence[str], value: Any) -> int:
    if value is None:
        return UNKNOWN
    try:
        return vocab.index(str(value))
    except ValueError:
        return UNKNOWN


# dict-form vocab lookups for the encode hot loop (O(1) vs index() scans)
_PM_CODE = {v: i for i, v in enumerate(PAYMENT_METHODS)}
_TT_CODE = {v: i for i, v in enumerate(TRANSACTION_TYPES)}
_CT_CODE = {v: i for i, v in enumerate(CARD_TYPES)}
_MC_CODE = {v: i for i, v in enumerate(MERCHANT_CATEGORIES)}
_KYC_CODE = {v: i for i, v in enumerate(KYC_STATUSES)}
_RL_CODE = {v: i for i, v in enumerate(RISK_LEVELS)}


def _dcode(codes: Dict[str, int], value: Any) -> int:
    if value is None:
        return UNKNOWN
    return codes.get(value if type(value) is str else str(value), UNKNOWN)


# --- host-side string analysis (FeatureExtractor.java:30-41,427-451) ---------
_SUSPICIOUS_NAME_RE = re.compile(
    r"(?i)(bitcoin|crypto|coinbase|binance|blockchain|wallet|mining|exchange"
    r"|gift\s*card|prepaid|reload|vanilla|amazon\s*gift|itunes"
    r"|western\s*union|moneygram|remit|transfer|wire|paypal|venmo"
    r"|casino|gambling|betting|lottery|forex|trading|investment|loan)"
)


def is_suspicious_merchant_name(name: str | None) -> bool:
    return bool(name) and _SUSPICIOUS_NAME_RE.search(name) is not None


def is_private_ip(ip: str | None) -> bool:
    # FeatureExtractor.java:434-438 (note: the reference only checks 172.16.)
    return bool(ip) and (
        ip.startswith("192.168.") or ip.startswith("10.") or ip.startswith("172.16.")
    )


def ip_risk_score(ip: str | None) -> float:
    # FeatureExtractor.java:440-445
    if not ip:
        return 0.3
    return 0.1 if is_private_ip(ip) else 0.3


def is_suspicious_user_agent(ua: str | None) -> bool:
    # FeatureExtractor.java:447-451
    if ua is None:
        return False
    return "bot" in ua or "crawler" in ua or len(ua) < 20


def is_high_risk_payment(method: str | None) -> bool:
    # FeatureExtractor.java:486-493
    if not method:
        return False
    lower = method.lower()
    return any(tok in lower for tok in ("prepaid", "gift", "crypto", "wire"))


@struct.dataclass
class TransactionBatch:
    """Dense batch of transactions + joined profile state.

    All arrays share leading dim B. ``has_*`` flags encode the reference's
    null checks; when a flag is False the corresponding value fields hold
    neutral defaults and must be ignored by consumers.
    """

    # transaction core
    amount: jax.Array               # f32[B]
    hour_of_day: jax.Array          # i32[B]
    day_of_week: jax.Array          # i32[B]  ISO 1=Mon..7=Sun (Java getValue)
    day_of_month: jax.Array         # i32[B]
    is_weekend: jax.Array           # bool[B]
    lat: jax.Array                  # f32[B]
    lon: jax.Array                  # f32[B]
    has_geo: jax.Array              # bool[B]
    merchant_lat: jax.Array         # f32[B]
    merchant_lon: jax.Array         # f32[B]
    has_merchant_geo: jax.Array     # bool[B]
    payment_method_code: jax.Array  # i32[B]
    transaction_type_code: jax.Array  # i32[B]
    card_type_code: jax.Array       # i32[B]
    high_risk_payment: jax.Array    # bool[B] (host-analyzed)
    suspicious_user_agent: jax.Array  # bool[B] (host-analyzed)
    private_ip: jax.Array           # bool[B] (host-analyzed)
    ip_risk: jax.Array              # f32[B] (host-analyzed)
    prior_fraud_score: jax.Array    # f32[B] (simulator label channel)

    # user profile join (presence = profile found in store)
    has_user: jax.Array             # bool[B]
    user_risk_score: jax.Array      # f32[B]
    account_age_days: jax.Array     # f32[B]
    user_verified: jax.Array        # bool[B]
    kyc_code: jax.Array             # i32[B]
    user_avg_amount: jax.Array      # f32[B]
    user_txn_frequency: jax.Array   # f32[B]
    preferred_start: jax.Array      # i32[B]
    preferred_end: jax.Array        # i32[B]
    has_preferred_hours: jax.Array  # bool[B]
    weekend_activity: jax.Array     # f32[B]
    intl_ratio: jax.Array           # f32[B]
    has_intl_ratio: jax.Array       # bool[B]
    online_preference: jax.Array    # f32[B]
    known_device: jax.Array         # bool[B] (host membership check)
    has_device_list: jax.Array      # bool[B] (profile carries fingerprints)
    has_txn_fingerprint: jax.Array  # bool[B] (transaction carried a fingerprint)

    # merchant profile join
    has_merchant: jax.Array         # bool[B]
    merchant_risk_code: jax.Array   # i32[B] (RISK_LEVELS index or UNKNOWN)
    merchant_fraud_rate: jax.Array  # f32[B]
    merchant_blacklisted: jax.Array  # bool[B]
    merchant_category_code: jax.Array  # i32[B]
    merchant_high_risk_category: jax.Array  # bool[B]
    merchant_op_start: jax.Array    # i32[B]
    merchant_op_end: jax.Array      # i32[B]
    has_op_hours: jax.Array         # bool[B]
    merchant_avg_amount: jax.Array  # f32[B]
    suspicious_merchant_name: jax.Array  # bool[B] (host regex)

    # velocity state join (5min / 1hour / 24hour windows,
    # RedisService.java:178-207 key schema)
    velocity_5min_count: jax.Array   # f32[B]
    velocity_5min_amount: jax.Array  # f32[B]
    velocity_1hour_count: jax.Array  # f32[B]
    velocity_1hour_amount: jax.Array  # f32[B]
    velocity_24hour_count: jax.Array  # f32[B]
    velocity_24hour_amount: jax.Array  # f32[B]

    @property
    def batch_size(self) -> int:
        return self.amount.shape[0]


def encode_transactions(
    records: Sequence[Mapping[str, Any]],
    user_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    merchant_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    velocities: Mapping[str, Mapping[str, Mapping[str, float]]] | None = None,
) -> TransactionBatch:
    """Encode transaction JSON records + profile joins into a dense batch.

    ``records`` follow the simulator schema (simulator.py:78-101).
    ``user_profiles``/``merchant_profiles`` map ids to profile dicts
    (simulator.py:40-75 schema). ``velocities`` maps user_id ->
    {"5min"|"1hour"|"24hour" -> {"count": n, "amount": a}}.
    """
    user_profiles = user_profiles or {}
    merchant_profiles = merchant_profiles or {}
    velocities = velocities or {}

    # Hot path for the 50k-TPS budget: build per-field Python lists and
    # convert ONCE (bulk np.array beats 53xN scalar setitem by ~3x), with
    # per-batch memoization of the profile-derived field groups — merchant
    # and user joins repeat heavily inside a microbatch.
    field_names = [f.name for f in fields(TransactionBatch)]
    rows: Dict[str, list] = {name: [] for name in field_names}

    # unknown-user defaults (FeatureExtractor.java:244-251):
    # (present, risk, age, verified, kyc, avg, freq, has_pref, ps, pe,
    #  weekend, has_intl, intl, online, has_devlist, fingerprints)
    _NO_USER = (False, 0.8, 0.0, False, UNKNOWN, 0.0, 0.0, False, 0, 23,
                0.5, False, 0.0, 0.7, False, ())
    # unknown-merchant defaults (FeatureExtractor.java:288-295)
    _NO_MERCH = (False, UNKNOWN, 0.1, False, UNKNOWN, False, False, 0, 24,
                 0.0, False)
    user_memo: Dict[str, tuple] = {}
    merch_memo: Dict[str, tuple] = {}

    def _user_row(uid: str) -> tuple:
        row = user_memo.get(uid)
        if row is None:
            user = user_profiles.get(uid)
            if user is None:
                row = _NO_USER
            else:
                patterns = user.get("behavioral_patterns") or {}
                ps = patterns.get("preferred_time_start")
                pe = patterns.get("preferred_time_end")
                intl = patterns.get("international_transactions")
                kyc = user.get("kyc_status")
                row = (
                    True,
                    float(user.get("risk_score", 0.5)),
                    float(user.get("account_age_days", 0.0)),
                    str(kyc or "") == "verified",
                    _dcode(_KYC_CODE, kyc),
                    float(user.get("avg_transaction_amount", 0.0)),
                    float(user.get("transaction_frequency", 0.0)),
                    ps is not None and pe is not None,
                    int(ps if ps is not None else 0),
                    int(pe if pe is not None else 23),
                    float(patterns.get("weekend_activity", 0.5)),
                    intl is not None,
                    float(intl if intl is not None else 0.0),
                    float(patterns.get("online_preference", 0.7)),
                    bool(user.get("device_fingerprints")),
                    user.get("device_fingerprints") or (),
                )
            user_memo[uid] = row
        return row

    def _merch_row(mid: str) -> tuple:
        row = merch_memo.get(mid)
        if row is None:
            merch = merchant_profiles.get(mid)
            if merch is None:
                row = _NO_MERCH
            else:
                cat, risk = merch.get("category"), merch.get("risk_level")
                hours = merch.get("operating_hours") or {}
                row = (
                    True,
                    _dcode(_RL_CODE, risk),
                    float(merch.get("fraud_rate", 0.05)),
                    bool(merch.get("is_blacklisted", False)),
                    _dcode(_MC_CODE, cat),
                    (str(cat) in HIGH_RISK_CATEGORIES or str(risk) == "high"),
                    "start_hour" in hours and "end_hour" in hours,
                    int(hours.get("start_hour", 0)),
                    int(hours.get("end_hour", 24)),
                    float(merch.get("avg_transaction_amount", 0.0)),
                    is_suspicious_merchant_name(merch.get("name")),
                )
            merch_memo[mid] = row
        return row

    pm_memo: Dict[str, tuple] = {}
    _EMPTY_VEL: Dict[str, Mapping[str, float]] = {}
    _EMPTY_W: Dict[str, float] = {}
    a = rows  # short alias for the loop body

    for rec in records:
        get = rec.get
        geo = get("geolocation") or {}
        mgeo = get("merchant_location") or {}
        a["amount"].append(float(get("amount", 0.0)))
        a["hour_of_day"].append(int(get("hour_of_day", 12)))
        a["day_of_week"].append(int(get("day_of_week", 1)))
        a["day_of_month"].append(int(get("day_of_month", 1)))
        a["is_weekend"].append(bool(get("is_weekend", False)))
        a["has_geo"].append(bool(geo) and geo.get("lat") is not None)
        a["lat"].append(float(geo.get("lat", 0.0) or 0.0))
        a["lon"].append(float(geo.get("lon", 0.0) or 0.0))
        a["has_merchant_geo"].append(bool(mgeo) and mgeo.get("lat") is not None)
        a["merchant_lat"].append(float(mgeo.get("lat", 0.0) or 0.0))
        a["merchant_lon"].append(float(mgeo.get("lon", 0.0) or 0.0))
        pm = get("payment_method")
        pm_row = pm_memo.get(pm)
        if pm_row is None:
            pm_memo[pm] = pm_row = (
                _dcode(_PM_CODE, pm), is_high_risk_payment(pm))
        a["payment_method_code"].append(pm_row[0])
        a["high_risk_payment"].append(pm_row[1])
        a["transaction_type_code"].append(
            _dcode(_TT_CODE, get("transaction_type")))
        a["card_type_code"].append(_dcode(_CT_CODE, get("card_type")))
        a["suspicious_user_agent"].append(
            is_suspicious_user_agent(get("user_agent")))
        ip = get("ip_address")
        private = is_private_ip(ip)
        a["private_ip"].append(private)
        # inlined ip_risk_score(): private 0.1, everything else 0.3
        a["ip_risk"].append(0.1 if private else 0.3)
        a["prior_fraud_score"].append(float(get("fraud_score", 0.0)))
        fp = get("device_fingerprint")
        a["has_txn_fingerprint"].append(fp is not None)

        uid = str(get("user_id", ""))
        (has_user, risk, age, verified, kyc, avg, freq, has_pref, ps, pe,
         weekend, has_intl, intl, online, has_devlist,
         fingerprints) = _user_row(uid)
        a["has_user"].append(has_user)
        a["user_risk_score"].append(risk)
        a["account_age_days"].append(age)
        a["user_verified"].append(verified)
        a["kyc_code"].append(kyc)
        a["user_avg_amount"].append(avg)
        a["user_txn_frequency"].append(freq)
        a["has_preferred_hours"].append(has_pref)
        a["preferred_start"].append(ps)
        a["preferred_end"].append(pe)
        a["weekend_activity"].append(weekend)
        a["has_intl_ratio"].append(has_intl)
        a["intl_ratio"].append(intl)
        a["online_preference"].append(online)
        a["has_device_list"].append(has_devlist)
        a["known_device"].append(fp is not None and fp in fingerprints)

        mid = str(get("merchant_id", ""))
        (has_merch, mrisk, frate, blist, mcat, mhigh, has_hours, op_s, op_e,
         mavg, sus_name) = _merch_row(mid)
        a["has_merchant"].append(has_merch)
        a["merchant_risk_code"].append(mrisk)
        a["merchant_fraud_rate"].append(frate)
        a["merchant_blacklisted"].append(blist)
        a["merchant_category_code"].append(mcat)
        a["merchant_high_risk_category"].append(mhigh)
        a["has_op_hours"].append(has_hours)
        a["merchant_op_start"].append(op_s)
        a["merchant_op_end"].append(op_e)
        a["merchant_avg_amount"].append(mavg)
        a["suspicious_merchant_name"].append(sus_name)

        vel = velocities.get(uid) or _EMPTY_VEL
        w = vel.get("5min") or _EMPTY_W
        a["velocity_5min_count"].append(float(w.get("count", 0.0)))
        a["velocity_5min_amount"].append(float(w.get("amount", 0.0)))
        w = vel.get("1hour") or _EMPTY_W
        a["velocity_1hour_count"].append(float(w.get("count", 0.0)))
        a["velocity_1hour_amount"].append(float(w.get("amount", 0.0)))
        w = vel.get("24hour") or _EMPTY_W
        a["velocity_24hour_count"].append(float(w.get("count", 0.0)))
        a["velocity_24hour_amount"].append(float(w.get("amount", 0.0)))

    return TransactionBatch(**{
        name: np.array(rows[name], dtype=_dtype_for(name))
        for name in field_names
    })


_BOOL_FIELDS = {
    "is_weekend", "has_geo", "has_merchant_geo", "high_risk_payment",
    "suspicious_user_agent", "private_ip", "has_txn_fingerprint", "has_user",
    "user_verified",
    "has_preferred_hours", "has_intl_ratio", "known_device", "has_device_list",
    "has_merchant", "merchant_blacklisted", "merchant_high_risk_category",
    "has_op_hours", "suspicious_merchant_name",
}
_INT_FIELDS = {
    "hour_of_day", "day_of_week", "day_of_month", "payment_method_code",
    "transaction_type_code", "card_type_code", "kyc_code", "preferred_start",
    "preferred_end", "merchant_risk_code", "merchant_category_code",
    "merchant_op_start", "merchant_op_end",
}


def _dtype_for(name: str):
    if name in _BOOL_FIELDS:
        return np.bool_
    if name in _INT_FIELDS:
        return np.int32
    return np.float32

"""Transaction batch schema: struct-of-arrays + categorical encodings.

The reference passes transactions around as JSON dicts (simulator.py:78-101)
and Java POJOs (the reconstructed ``Transaction``/``UserProfile``/
``MerchantProfile`` of SURVEY.md section 2.10). A TPU program wants dense,
statically-shaped tensors, so ingest converts a list of transaction records +
profile lookups into a ``TransactionBatch``: one flat array per field, with
presence flags standing in for the reference's null checks.

Everything string-shaped (regex merchant-name analysis
FeatureExtractor.java:427-432, IP/user-agent analysis :434-451, device
fingerprint membership :307-313) is resolved host-side here, so the
device-side feature extractor is pure arithmetic.
"""

from __future__ import annotations

import re
from dataclasses import fields
from typing import Any, Dict, List, Mapping, Sequence

import jax
import numpy as np
from flax import struct

# --- categorical vocabularies (closed sets from the simulator,
#     simulator.py:255-266,330-332) -------------------------------------------
PAYMENT_METHODS = ("credit_card", "debit_card", "digital_wallet", "bank_transfer",
                   "crypto", "gift_card", "prepaid_card", "wire_transfer")
TRANSACTION_TYPES = ("purchase", "refund", "authorization")
CARD_TYPES = ("visa", "mastercard", "amex", "discover")
MERCHANT_CATEGORIES = ("retail", "grocery", "gas_station", "restaurant",
                       "online_retail", "gambling", "adult_entertainment",
                       "pharmacy", "jewelry", "electronics")
KYC_STATUSES = ("verified", "pending", "rejected")
RISK_LEVELS = ("low", "medium", "high")
# Categories the reference treats as high-risk (simulator risk_level='high')
HIGH_RISK_CATEGORIES = frozenset({"gambling", "adult_entertainment", "jewelry"})

UNKNOWN = -1  # encoding for absent/unknown categorical values


def _code(vocab: Sequence[str], value: Any) -> int:
    if value is None:
        return UNKNOWN
    try:
        return vocab.index(str(value))
    except ValueError:
        return UNKNOWN


# --- host-side string analysis (FeatureExtractor.java:30-41,427-451) ---------
_SUSPICIOUS_NAME_RE = re.compile(
    r"(?i)(bitcoin|crypto|coinbase|binance|blockchain|wallet|mining|exchange"
    r"|gift\s*card|prepaid|reload|vanilla|amazon\s*gift|itunes"
    r"|western\s*union|moneygram|remit|transfer|wire|paypal|venmo"
    r"|casino|gambling|betting|lottery|forex|trading|investment|loan)"
)


def is_suspicious_merchant_name(name: str | None) -> bool:
    return bool(name) and _SUSPICIOUS_NAME_RE.search(name) is not None


def is_private_ip(ip: str | None) -> bool:
    # FeatureExtractor.java:434-438 (note: the reference only checks 172.16.)
    return bool(ip) and (
        ip.startswith("192.168.") or ip.startswith("10.") or ip.startswith("172.16.")
    )


def ip_risk_score(ip: str | None) -> float:
    # FeatureExtractor.java:440-445
    if not ip:
        return 0.3
    return 0.1 if is_private_ip(ip) else 0.3


def is_suspicious_user_agent(ua: str | None) -> bool:
    # FeatureExtractor.java:447-451
    if ua is None:
        return False
    return "bot" in ua or "crawler" in ua or len(ua) < 20


def is_high_risk_payment(method: str | None) -> bool:
    # FeatureExtractor.java:486-493
    if not method:
        return False
    lower = method.lower()
    return any(tok in lower for tok in ("prepaid", "gift", "crypto", "wire"))


@struct.dataclass
class TransactionBatch:
    """Dense batch of transactions + joined profile state.

    All arrays share leading dim B. ``has_*`` flags encode the reference's
    null checks; when a flag is False the corresponding value fields hold
    neutral defaults and must be ignored by consumers.
    """

    # transaction core
    amount: jax.Array               # f32[B]
    hour_of_day: jax.Array          # i32[B]
    day_of_week: jax.Array          # i32[B]  ISO 1=Mon..7=Sun (Java getValue)
    day_of_month: jax.Array         # i32[B]
    is_weekend: jax.Array           # bool[B]
    lat: jax.Array                  # f32[B]
    lon: jax.Array                  # f32[B]
    has_geo: jax.Array              # bool[B]
    merchant_lat: jax.Array         # f32[B]
    merchant_lon: jax.Array         # f32[B]
    has_merchant_geo: jax.Array     # bool[B]
    payment_method_code: jax.Array  # i32[B]
    transaction_type_code: jax.Array  # i32[B]
    card_type_code: jax.Array       # i32[B]
    high_risk_payment: jax.Array    # bool[B] (host-analyzed)
    suspicious_user_agent: jax.Array  # bool[B] (host-analyzed)
    private_ip: jax.Array           # bool[B] (host-analyzed)
    ip_risk: jax.Array              # f32[B] (host-analyzed)
    prior_fraud_score: jax.Array    # f32[B] (simulator label channel)

    # user profile join (presence = profile found in store)
    has_user: jax.Array             # bool[B]
    user_risk_score: jax.Array      # f32[B]
    account_age_days: jax.Array     # f32[B]
    user_verified: jax.Array        # bool[B]
    kyc_code: jax.Array             # i32[B]
    user_avg_amount: jax.Array      # f32[B]
    user_txn_frequency: jax.Array   # f32[B]
    preferred_start: jax.Array      # i32[B]
    preferred_end: jax.Array        # i32[B]
    has_preferred_hours: jax.Array  # bool[B]
    weekend_activity: jax.Array     # f32[B]
    intl_ratio: jax.Array           # f32[B]
    has_intl_ratio: jax.Array       # bool[B]
    online_preference: jax.Array    # f32[B]
    known_device: jax.Array         # bool[B] (host membership check)
    has_device_list: jax.Array      # bool[B] (profile carries fingerprints)
    has_txn_fingerprint: jax.Array  # bool[B] (transaction carried a fingerprint)

    # merchant profile join
    has_merchant: jax.Array         # bool[B]
    merchant_risk_code: jax.Array   # i32[B] (RISK_LEVELS index or UNKNOWN)
    merchant_fraud_rate: jax.Array  # f32[B]
    merchant_blacklisted: jax.Array  # bool[B]
    merchant_category_code: jax.Array  # i32[B]
    merchant_high_risk_category: jax.Array  # bool[B]
    merchant_op_start: jax.Array    # i32[B]
    merchant_op_end: jax.Array      # i32[B]
    has_op_hours: jax.Array         # bool[B]
    merchant_avg_amount: jax.Array  # f32[B]
    suspicious_merchant_name: jax.Array  # bool[B] (host regex)

    # velocity state join (5min / 1hour / 24hour windows,
    # RedisService.java:178-207 key schema)
    velocity_5min_count: jax.Array   # f32[B]
    velocity_5min_amount: jax.Array  # f32[B]
    velocity_1hour_count: jax.Array  # f32[B]
    velocity_1hour_amount: jax.Array  # f32[B]
    velocity_24hour_count: jax.Array  # f32[B]
    velocity_24hour_amount: jax.Array  # f32[B]

    @property
    def batch_size(self) -> int:
        return self.amount.shape[0]


def encode_transactions(
    records: Sequence[Mapping[str, Any]],
    user_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    merchant_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    velocities: Mapping[str, Mapping[str, Mapping[str, float]]] | None = None,
) -> TransactionBatch:
    """Encode transaction JSON records + profile joins into a dense batch.

    ``records`` follow the simulator schema (simulator.py:78-101).
    ``user_profiles``/``merchant_profiles`` map ids to profile dicts
    (simulator.py:40-75 schema). ``velocities`` maps user_id ->
    {"5min"|"1hour"|"24hour" -> {"count": n, "amount": a}}.
    """
    user_profiles = user_profiles or {}
    merchant_profiles = merchant_profiles or {}
    velocities = velocities or {}
    n = len(records)

    cols: Dict[str, np.ndarray] = {
        f.name: np.zeros((n,), _dtype_for(f.name)) for f in fields(TransactionBatch)
    }

    for i, rec in enumerate(records):
        geo = rec.get("geolocation") or {}
        mgeo = rec.get("merchant_location") or {}
        cols["amount"][i] = float(rec.get("amount", 0.0))
        cols["hour_of_day"][i] = int(rec.get("hour_of_day", 12))
        cols["day_of_week"][i] = int(rec.get("day_of_week", 1))
        cols["day_of_month"][i] = int(rec.get("day_of_month", 1))
        cols["is_weekend"][i] = bool(rec.get("is_weekend", False))
        cols["has_geo"][i] = bool(geo) and geo.get("lat") is not None
        cols["lat"][i] = float(geo.get("lat", 0.0) or 0.0)
        cols["lon"][i] = float(geo.get("lon", 0.0) or 0.0)
        cols["has_merchant_geo"][i] = bool(mgeo) and mgeo.get("lat") is not None
        cols["merchant_lat"][i] = float(mgeo.get("lat", 0.0) or 0.0)
        cols["merchant_lon"][i] = float(mgeo.get("lon", 0.0) or 0.0)
        cols["payment_method_code"][i] = _code(PAYMENT_METHODS, rec.get("payment_method"))
        cols["transaction_type_code"][i] = _code(TRANSACTION_TYPES, rec.get("transaction_type"))
        cols["card_type_code"][i] = _code(CARD_TYPES, rec.get("card_type"))
        cols["high_risk_payment"][i] = is_high_risk_payment(rec.get("payment_method"))
        cols["suspicious_user_agent"][i] = is_suspicious_user_agent(rec.get("user_agent"))
        cols["private_ip"][i] = is_private_ip(rec.get("ip_address"))
        cols["ip_risk"][i] = ip_risk_score(rec.get("ip_address"))
        cols["prior_fraud_score"][i] = float(rec.get("fraud_score", 0.0))
        cols["has_txn_fingerprint"][i] = rec.get("device_fingerprint") is not None

        user = user_profiles.get(str(rec.get("user_id", "")))
        cols["has_user"][i] = user is not None
        if user is not None:
            patterns = user.get("behavioral_patterns") or {}
            cols["user_risk_score"][i] = float(user.get("risk_score", 0.5))
            cols["account_age_days"][i] = float(user.get("account_age_days", 0.0))
            cols["user_verified"][i] = str(user.get("kyc_status", "")) == "verified"
            cols["kyc_code"][i] = _code(KYC_STATUSES, user.get("kyc_status"))
            cols["user_avg_amount"][i] = float(user.get("avg_transaction_amount", 0.0))
            cols["user_txn_frequency"][i] = float(user.get("transaction_frequency", 0.0))
            ps, pe = patterns.get("preferred_time_start"), patterns.get("preferred_time_end")
            cols["has_preferred_hours"][i] = ps is not None and pe is not None
            cols["preferred_start"][i] = int(ps if ps is not None else 0)
            cols["preferred_end"][i] = int(pe if pe is not None else 23)
            cols["weekend_activity"][i] = float(patterns.get("weekend_activity", 0.5))
            intl = patterns.get("international_transactions")
            cols["has_intl_ratio"][i] = intl is not None
            cols["intl_ratio"][i] = float(intl if intl is not None else 0.0)
            cols["online_preference"][i] = float(patterns.get("online_preference", 0.7))
            fingerprints = user.get("device_fingerprints") or []
            cols["has_device_list"][i] = bool(fingerprints)
            fp = rec.get("device_fingerprint")
            cols["known_device"][i] = fp is not None and fp in fingerprints
        else:
            # unknown-user defaults (FeatureExtractor.java:244-251)
            cols["user_risk_score"][i] = 0.8
            cols["kyc_code"][i] = UNKNOWN
            cols["preferred_end"][i] = 23
            cols["weekend_activity"][i] = 0.5
            cols["online_preference"][i] = 0.7

        merch = merchant_profiles.get(str(rec.get("merchant_id", "")))
        cols["has_merchant"][i] = merch is not None
        if merch is not None:
            cols["merchant_risk_code"][i] = _code(RISK_LEVELS, merch.get("risk_level"))
            cols["merchant_fraud_rate"][i] = float(merch.get("fraud_rate", 0.05))
            cols["merchant_blacklisted"][i] = bool(merch.get("is_blacklisted", False))
            cols["merchant_category_code"][i] = _code(MERCHANT_CATEGORIES, merch.get("category"))
            cols["merchant_high_risk_category"][i] = (
                str(merch.get("category")) in HIGH_RISK_CATEGORIES
                or str(merch.get("risk_level")) == "high"
            )
            hours = merch.get("operating_hours") or {}
            cols["has_op_hours"][i] = "start_hour" in hours and "end_hour" in hours
            cols["merchant_op_start"][i] = int(hours.get("start_hour", 0))
            cols["merchant_op_end"][i] = int(hours.get("end_hour", 24))
            cols["merchant_avg_amount"][i] = float(merch.get("avg_transaction_amount", 0.0))
            cols["suspicious_merchant_name"][i] = is_suspicious_merchant_name(merch.get("name"))
        else:
            # unknown-merchant defaults (FeatureExtractor.java:288-295)
            cols["merchant_risk_code"][i] = UNKNOWN
            cols["merchant_fraud_rate"][i] = 0.1
            cols["merchant_category_code"][i] = UNKNOWN
            cols["merchant_op_end"][i] = 24

        vel = velocities.get(str(rec.get("user_id", ""))) or {}
        for window, prefix in (("5min", "velocity_5min"), ("1hour", "velocity_1hour"),
                               ("24hour", "velocity_24hour")):
            w = vel.get(window) or {}
            cols[f"{prefix}_count"][i] = float(w.get("count", 0.0))
            cols[f"{prefix}_amount"][i] = float(w.get("amount", 0.0))

    return TransactionBatch(**cols)


_BOOL_FIELDS = {
    "is_weekend", "has_geo", "has_merchant_geo", "high_risk_payment",
    "suspicious_user_agent", "private_ip", "has_txn_fingerprint", "has_user",
    "user_verified",
    "has_preferred_hours", "has_intl_ratio", "known_device", "has_device_list",
    "has_merchant", "merchant_blacklisted", "merchant_high_risk_category",
    "has_op_hours", "suspicious_merchant_name",
}
_INT_FIELDS = {
    "hour_of_day", "day_of_week", "day_of_month", "payment_method_code",
    "transaction_type_code", "card_type_code", "kyc_code", "preferred_start",
    "preferred_end", "merchant_risk_code", "merchant_category_code",
    "merchant_op_start", "merchant_op_end",
}


def _dtype_for(name: str):
    if name in _BOOL_FIELDS:
        return np.bool_
    if name in _INT_FIELDS:
        return np.int32
    return np.float32

"""Transaction batch schema: struct-of-arrays + categorical encodings.

The reference passes transactions around as JSON dicts (simulator.py:78-101)
and Java POJOs (the reconstructed ``Transaction``/``UserProfile``/
``MerchantProfile`` of SURVEY.md section 2.10). A TPU program wants dense,
statically-shaped tensors, so ingest converts a list of transaction records +
profile lookups into a ``TransactionBatch``: one flat array per field, with
presence flags standing in for the reference's null checks.

Everything string-shaped (regex merchant-name analysis
FeatureExtractor.java:427-432, IP/user-agent analysis :434-451, device
fingerprint membership :307-313) is resolved host-side here, so the
device-side feature extractor is pure arithmetic.
"""

from __future__ import annotations

import re
from dataclasses import fields
from typing import Any, Dict, List, Mapping, Sequence

import jax
import numpy as np
from flax import struct

# --- categorical vocabularies (closed sets from the simulator,
#     simulator.py:255-266,330-332) -------------------------------------------
PAYMENT_METHODS = ("credit_card", "debit_card", "digital_wallet", "bank_transfer",
                   "crypto", "gift_card", "prepaid_card", "wire_transfer")
TRANSACTION_TYPES = ("purchase", "refund", "authorization")
CARD_TYPES = ("visa", "mastercard", "amex", "discover")
MERCHANT_CATEGORIES = ("retail", "grocery", "gas_station", "restaurant",
                       "online_retail", "gambling", "adult_entertainment",
                       "pharmacy", "jewelry", "electronics")
KYC_STATUSES = ("verified", "pending", "rejected")
RISK_LEVELS = ("low", "medium", "high")
# Categories the reference treats as high-risk (simulator risk_level='high')
HIGH_RISK_CATEGORIES = frozenset({"gambling", "adult_entertainment", "jewelry"})

UNKNOWN = -1  # encoding for absent/unknown categorical values


def _code(vocab: Sequence[str], value: Any) -> int:
    if value is None:
        return UNKNOWN
    try:
        return vocab.index(str(value))
    except ValueError:
        return UNKNOWN


# dict-form vocab lookups for the encode hot loop (O(1) vs index() scans)
_PM_CODE = {v: i for i, v in enumerate(PAYMENT_METHODS)}
_TT_CODE = {v: i for i, v in enumerate(TRANSACTION_TYPES)}
_CT_CODE = {v: i for i, v in enumerate(CARD_TYPES)}
_MC_CODE = {v: i for i, v in enumerate(MERCHANT_CATEGORIES)}
_KYC_CODE = {v: i for i, v in enumerate(KYC_STATUSES)}
_RL_CODE = {v: i for i, v in enumerate(RISK_LEVELS)}


def _dcode(codes: Dict[str, int], value: Any) -> int:
    if value is None:
        return UNKNOWN
    return codes.get(value if type(value) is str else str(value), UNKNOWN)


# --- host-side string analysis (FeatureExtractor.java:30-41,427-451) ---------
_SUSPICIOUS_NAME_RE = re.compile(
    r"(?i)(bitcoin|crypto|coinbase|binance|blockchain|wallet|mining|exchange"
    r"|gift\s*card|prepaid|reload|vanilla|amazon\s*gift|itunes"
    r"|western\s*union|moneygram|remit|transfer|wire|paypal|venmo"
    r"|casino|gambling|betting|lottery|forex|trading|investment|loan)"
)


def is_suspicious_merchant_name(name: str | None) -> bool:
    return bool(name) and _SUSPICIOUS_NAME_RE.search(name) is not None


def is_private_ip(ip: str | None) -> bool:
    # FeatureExtractor.java:434-438 (note: the reference only checks 172.16.)
    return bool(ip) and (
        ip.startswith("192.168.") or ip.startswith("10.") or ip.startswith("172.16.")
    )


def ip_risk_score(ip: str | None) -> float:
    # FeatureExtractor.java:440-445
    if not ip:
        return 0.3
    return 0.1 if is_private_ip(ip) else 0.3


def is_suspicious_user_agent(ua: str | None) -> bool:
    # FeatureExtractor.java:447-451
    if ua is None:
        return False
    return "bot" in ua or "crawler" in ua or len(ua) < 20


def is_high_risk_payment(method: str | None) -> bool:
    # FeatureExtractor.java:486-493
    if not method:
        return False
    lower = method.lower()
    return any(tok in lower for tok in ("prepaid", "gift", "crypto", "wire"))


@struct.dataclass
class TransactionBatch:
    """Dense batch of transactions + joined profile state.

    All arrays share leading dim B. ``has_*`` flags encode the reference's
    null checks; when a flag is False the corresponding value fields hold
    neutral defaults and must be ignored by consumers.
    """

    # transaction core
    amount: jax.Array               # f32[B]
    hour_of_day: jax.Array          # i32[B]
    day_of_week: jax.Array          # i32[B]  ISO 1=Mon..7=Sun (Java getValue)
    day_of_month: jax.Array         # i32[B]
    is_weekend: jax.Array           # bool[B]
    lat: jax.Array                  # f32[B]
    lon: jax.Array                  # f32[B]
    has_geo: jax.Array              # bool[B]
    merchant_lat: jax.Array         # f32[B]
    merchant_lon: jax.Array         # f32[B]
    has_merchant_geo: jax.Array     # bool[B]
    payment_method_code: jax.Array  # i32[B]
    transaction_type_code: jax.Array  # i32[B]
    card_type_code: jax.Array       # i32[B]
    high_risk_payment: jax.Array    # bool[B] (host-analyzed)
    suspicious_user_agent: jax.Array  # bool[B] (host-analyzed)
    private_ip: jax.Array           # bool[B] (host-analyzed)
    ip_risk: jax.Array              # f32[B] (host-analyzed)
    prior_fraud_score: jax.Array    # f32[B] (simulator label channel)

    # user profile join (presence = profile found in store)
    has_user: jax.Array             # bool[B]
    user_risk_score: jax.Array      # f32[B]
    account_age_days: jax.Array     # f32[B]
    user_verified: jax.Array        # bool[B]
    kyc_code: jax.Array             # i32[B]
    user_avg_amount: jax.Array      # f32[B]
    user_txn_frequency: jax.Array   # f32[B]
    preferred_start: jax.Array      # i32[B]
    preferred_end: jax.Array        # i32[B]
    has_preferred_hours: jax.Array  # bool[B]
    weekend_activity: jax.Array     # f32[B]
    intl_ratio: jax.Array           # f32[B]
    has_intl_ratio: jax.Array       # bool[B]
    online_preference: jax.Array    # f32[B]
    known_device: jax.Array         # bool[B] (host membership check)
    has_device_list: jax.Array      # bool[B] (profile carries fingerprints)
    has_txn_fingerprint: jax.Array  # bool[B] (transaction carried a fingerprint)

    # merchant profile join
    has_merchant: jax.Array         # bool[B]
    merchant_risk_code: jax.Array   # i32[B] (RISK_LEVELS index or UNKNOWN)
    merchant_fraud_rate: jax.Array  # f32[B]
    merchant_blacklisted: jax.Array  # bool[B]
    merchant_category_code: jax.Array  # i32[B]
    merchant_high_risk_category: jax.Array  # bool[B]
    merchant_op_start: jax.Array    # i32[B]
    merchant_op_end: jax.Array      # i32[B]
    has_op_hours: jax.Array         # bool[B]
    merchant_avg_amount: jax.Array  # f32[B]
    suspicious_merchant_name: jax.Array  # bool[B] (host regex)

    # velocity state join (5min / 1hour / 24hour windows,
    # RedisService.java:178-207 key schema)
    velocity_5min_count: jax.Array   # f32[B]
    velocity_5min_amount: jax.Array  # f32[B]
    velocity_1hour_count: jax.Array  # f32[B]
    velocity_1hour_amount: jax.Array  # f32[B]
    velocity_24hour_count: jax.Array  # f32[B]
    velocity_24hour_amount: jax.Array  # f32[B]

    @property
    def batch_size(self) -> int:
        return self.amount.shape[0]


def encode_transactions(
    records: Sequence[Mapping[str, Any]],
    user_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    merchant_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    velocities: Mapping[str, Mapping[str, Mapping[str, float]]] | None = None,
) -> TransactionBatch:
    """Encode transaction JSON records + profile joins into a dense batch.

    ``records`` follow the simulator schema (simulator.py:78-101).
    ``user_profiles``/``merchant_profiles`` map ids to profile dicts
    (simulator.py:40-75 schema). ``velocities`` maps user_id ->
    {"5min"|"1hour"|"24hour" -> {"count": n, "amount": a}}.
    """
    user_profiles = user_profiles or {}
    merchant_profiles = merchant_profiles or {}
    velocities = velocities or {}

    # Hot path for the 50k-TPS budget: build per-field Python lists and
    # convert ONCE (bulk np.array beats 53xN scalar setitem by ~3x), with
    # per-batch memoization of the profile-derived field groups — merchant
    # and user joins repeat heavily inside a microbatch.
    field_names = [f.name for f in fields(TransactionBatch)]
    rows: Dict[str, list] = {name: [] for name in field_names}

    # unknown-user defaults (FeatureExtractor.java:244-251):
    # (present, risk, age, verified, kyc, avg, freq, has_pref, ps, pe,
    #  weekend, has_intl, intl, online, has_devlist, fingerprints)
    _NO_USER = (False, 0.8, 0.0, False, UNKNOWN, 0.0, 0.0, False, 0, 23,
                0.5, False, 0.0, 0.7, False, ())
    # unknown-merchant defaults (FeatureExtractor.java:288-295)
    _NO_MERCH = (False, UNKNOWN, 0.1, False, UNKNOWN, False, False, 0, 24,
                 0.0, False)
    user_memo: Dict[str, tuple] = {}
    merch_memo: Dict[str, tuple] = {}

    def _user_row(uid: str) -> tuple:
        row = user_memo.get(uid)
        if row is None:
            user = user_profiles.get(uid)
            if user is None:
                row = _NO_USER
            else:
                patterns = user.get("behavioral_patterns") or {}
                ps = patterns.get("preferred_time_start")
                pe = patterns.get("preferred_time_end")
                intl = patterns.get("international_transactions")
                kyc = user.get("kyc_status")
                row = (
                    True,
                    float(user.get("risk_score", 0.5)),
                    float(user.get("account_age_days", 0.0)),
                    str(kyc or "") == "verified",
                    _dcode(_KYC_CODE, kyc),
                    float(user.get("avg_transaction_amount", 0.0)),
                    float(user.get("transaction_frequency", 0.0)),
                    ps is not None and pe is not None,
                    int(ps if ps is not None else 0),
                    int(pe if pe is not None else 23),
                    float(patterns.get("weekend_activity", 0.5)),
                    intl is not None,
                    float(intl if intl is not None else 0.0),
                    float(patterns.get("online_preference", 0.7)),
                    bool(user.get("device_fingerprints")),
                    user.get("device_fingerprints") or (),
                )
            user_memo[uid] = row
        return row

    def _merch_row(mid: str) -> tuple:
        row = merch_memo.get(mid)
        if row is None:
            merch = merchant_profiles.get(mid)
            if merch is None:
                row = _NO_MERCH
            else:
                cat, risk = merch.get("category"), merch.get("risk_level")
                hours = merch.get("operating_hours") or {}
                row = (
                    True,
                    _dcode(_RL_CODE, risk),
                    float(merch.get("fraud_rate", 0.05)),
                    bool(merch.get("is_blacklisted", False)),
                    _dcode(_MC_CODE, cat),
                    (str(cat) in HIGH_RISK_CATEGORIES or str(risk) == "high"),
                    "start_hour" in hours and "end_hour" in hours,
                    int(hours.get("start_hour", 0)),
                    int(hours.get("end_hour", 24)),
                    float(merch.get("avg_transaction_amount", 0.0)),
                    is_suspicious_merchant_name(merch.get("name")),
                )
            merch_memo[mid] = row
        return row

    pm_memo: Dict[str, tuple] = {}
    _EMPTY_VEL: Dict[str, Mapping[str, float]] = {}
    _EMPTY_W: Dict[str, float] = {}
    a = rows  # short alias for the loop body

    for rec in records:
        get = rec.get
        geo = get("geolocation") or {}
        mgeo = get("merchant_location") or {}
        a["amount"].append(float(get("amount", 0.0)))
        a["hour_of_day"].append(int(get("hour_of_day", 12)))
        a["day_of_week"].append(int(get("day_of_week", 1)))
        a["day_of_month"].append(int(get("day_of_month", 1)))
        a["is_weekend"].append(bool(get("is_weekend", False)))
        a["has_geo"].append(bool(geo) and geo.get("lat") is not None)
        a["lat"].append(float(geo.get("lat", 0.0) or 0.0))
        a["lon"].append(float(geo.get("lon", 0.0) or 0.0))
        a["has_merchant_geo"].append(bool(mgeo) and mgeo.get("lat") is not None)
        a["merchant_lat"].append(float(mgeo.get("lat", 0.0) or 0.0))
        a["merchant_lon"].append(float(mgeo.get("lon", 0.0) or 0.0))
        pm = get("payment_method")
        pm_row = pm_memo.get(pm)
        if pm_row is None:
            pm_memo[pm] = pm_row = (
                _dcode(_PM_CODE, pm), is_high_risk_payment(pm))
        a["payment_method_code"].append(pm_row[0])
        a["high_risk_payment"].append(pm_row[1])
        a["transaction_type_code"].append(
            _dcode(_TT_CODE, get("transaction_type")))
        a["card_type_code"].append(_dcode(_CT_CODE, get("card_type")))
        a["suspicious_user_agent"].append(
            is_suspicious_user_agent(get("user_agent")))
        ip = get("ip_address")
        private = is_private_ip(ip)
        a["private_ip"].append(private)
        # inlined ip_risk_score(): private 0.1, everything else 0.3
        a["ip_risk"].append(0.1 if private else 0.3)
        a["prior_fraud_score"].append(float(get("fraud_score", 0.0)))
        fp = get("device_fingerprint")
        a["has_txn_fingerprint"].append(fp is not None)

        uid = str(get("user_id", ""))
        (has_user, risk, age, verified, kyc, avg, freq, has_pref, ps, pe,
         weekend, has_intl, intl, online, has_devlist,
         fingerprints) = _user_row(uid)
        a["has_user"].append(has_user)
        a["user_risk_score"].append(risk)
        a["account_age_days"].append(age)
        a["user_verified"].append(verified)
        a["kyc_code"].append(kyc)
        a["user_avg_amount"].append(avg)
        a["user_txn_frequency"].append(freq)
        a["has_preferred_hours"].append(has_pref)
        a["preferred_start"].append(ps)
        a["preferred_end"].append(pe)
        a["weekend_activity"].append(weekend)
        a["has_intl_ratio"].append(has_intl)
        a["intl_ratio"].append(intl)
        a["online_preference"].append(online)
        a["has_device_list"].append(has_devlist)
        a["known_device"].append(fp is not None and fp in fingerprints)

        mid = str(get("merchant_id", ""))
        (has_merch, mrisk, frate, blist, mcat, mhigh, has_hours, op_s, op_e,
         mavg, sus_name) = _merch_row(mid)
        a["has_merchant"].append(has_merch)
        a["merchant_risk_code"].append(mrisk)
        a["merchant_fraud_rate"].append(frate)
        a["merchant_blacklisted"].append(blist)
        a["merchant_category_code"].append(mcat)
        a["merchant_high_risk_category"].append(mhigh)
        a["has_op_hours"].append(has_hours)
        a["merchant_op_start"].append(op_s)
        a["merchant_op_end"].append(op_e)
        a["merchant_avg_amount"].append(mavg)
        a["suspicious_merchant_name"].append(sus_name)

        vel = velocities.get(uid) or _EMPTY_VEL
        w = vel.get("5min") or _EMPTY_W
        a["velocity_5min_count"].append(float(w.get("count", 0.0)))
        a["velocity_5min_amount"].append(float(w.get("amount", 0.0)))
        w = vel.get("1hour") or _EMPTY_W
        a["velocity_1hour_count"].append(float(w.get("count", 0.0)))
        a["velocity_1hour_amount"].append(float(w.get("amount", 0.0)))
        w = vel.get("24hour") or _EMPTY_W
        a["velocity_24hour_count"].append(float(w.get("count", 0.0)))
        a["velocity_24hour_amount"].append(float(w.get("amount", 0.0)))

    return TransactionBatch(**{
        name: np.array(rows[name], dtype=_dtype_for(name))
        for name in field_names
    })


# --- columnar encode: the host-assembly hot path ---------------------------
# Unknown-entity default rows for the columnar path, split by dtype group in
# the exact field order the gathers below consume. Values mirror _NO_USER /
# _NO_MERCH (FeatureExtractor.java:244-251, :288-295).
_NO_USER_F32 = (0.8, 0.0, 0.0, 0.0, 0.5, 0.0, 0.7)
_NO_USER_I32 = (UNKNOWN, 0, 23)
_NO_USER_BOOL = (False, False, False, False, False)
_NO_MERCH_F32 = (0.1, 0.0)
_NO_MERCH_I32 = (UNKNOWN, UNKNOWN, 0, 24)
_NO_MERCH_BOOL = (False, False, False, False, False)


class EntityRowCache:
    """Cross-batch cache of encode-time join rows, generation-stamped.

    The per-entity profile joins are pure functions of the profile dict, so
    their encoded rows (dtype-grouped scalar tuples) are cached across
    microbatches and invalidated wholesale when the backing ProfileStore's
    ``generation`` moves (any profile write). A store without a
    ``generation`` attribute (the shared RESP tier — remote writers are
    invisible) gets per-batch memoization only: ``sync`` clears on every
    call. ``max_entries`` bounds each side (steady-state write-back never
    touches profiles, so without a cap a long-running service would grow
    one row per distinct id forever); at the cap the side is cleared
    wholesale — misses are cheap rebuilds and the hot ids repopulate
    within a batch. ``hits``/``misses`` feed the host-assembly Prometheus
    series.
    """

    def __init__(self, max_entries: int = 131_072) -> None:
        self.generation: Any = object()     # never equal to a store's int
        self.max_entries = max(1, int(max_entries))
        self.users: Dict[str, tuple] = {}
        self.merchants: Dict[str, tuple] = {}
        self.hits = 0
        self.misses = 0

    def sync(self, profile_store: Any) -> None:
        gen = getattr(profile_store, "generation", None)
        if gen is None or gen != self.generation:
            self.users.clear()
            self.merchants.clear()
        else:
            if len(self.users) > self.max_entries:
                self.users.clear()
            if len(self.merchants) > self.max_entries:
                self.merchants.clear()
        self.generation = gen if gen is not None else object()

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self.users) + len(self.merchants)}


def _user_row_cols(user: Mapping[str, Any] | None) -> tuple:
    """(f32 row, i32 row, bool row, fingerprints) for one user profile —
    scalar-for-scalar the values _user_row produces for the serial path."""
    if user is None:
        return (_NO_USER_F32, _NO_USER_I32, _NO_USER_BOOL, ())
    patterns = user.get("behavioral_patterns") or {}
    ps = patterns.get("preferred_time_start")
    pe = patterns.get("preferred_time_end")
    intl = patterns.get("international_transactions")
    kyc = user.get("kyc_status")
    return (
        (float(user.get("risk_score", 0.5)),
         float(user.get("account_age_days", 0.0)),
         float(user.get("avg_transaction_amount", 0.0)),
         float(user.get("transaction_frequency", 0.0)),
         float(patterns.get("weekend_activity", 0.5)),
         float(intl if intl is not None else 0.0),
         float(patterns.get("online_preference", 0.7))),
        (_dcode(_KYC_CODE, kyc),
         int(ps if ps is not None else 0),
         int(pe if pe is not None else 23)),
        (True,
         str(kyc or "") == "verified",
         ps is not None and pe is not None,
         intl is not None,
         bool(user.get("device_fingerprints"))),
        user.get("device_fingerprints") or (),
    )


def _merch_row_cols(merch: Mapping[str, Any] | None) -> tuple:
    """(f32 row, i32 row, bool row) for one merchant profile — the columnar
    twin of _merch_row."""
    if merch is None:
        return (_NO_MERCH_F32, _NO_MERCH_I32, _NO_MERCH_BOOL)
    cat, risk = merch.get("category"), merch.get("risk_level")
    hours = merch.get("operating_hours") or {}
    return (
        (float(merch.get("fraud_rate", 0.05)),
         float(merch.get("avg_transaction_amount", 0.0))),
        (_dcode(_RL_CODE, risk),
         _dcode(_MC_CODE, cat),
         int(hours.get("start_hour", 0)),
         int(hours.get("end_hour", 24))),
        (True,
         bool(merch.get("is_blacklisted", False)),
         (str(cat) in HIGH_RISK_CATEGORIES or str(risk) == "high"),
         "start_hour" in hours and "end_hour" in hours,
         is_suspicious_merchant_name(merch.get("name"))),
    )


def encode_transactions_columnar(
    records: Sequence[Mapping[str, Any]],
    user_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    merchant_profiles: Mapping[str, Mapping[str, Any]] | None = None,
    velocities: Mapping[str, Mapping[str, Mapping[str, float]]] | None = None,
    cache: EntityRowCache | None = None,
) -> TransactionBatch:
    """Columnar twin of ``encode_transactions``: bit-identical output.

    The per-record Python loop shrinks to the ~20 transaction-core fields;
    every profile/velocity join becomes one dense gather — unique entities
    are resolved to dtype-grouped row tables (cached across batches via
    ``cache``; see EntityRowCache) and fancy-indexed back out to records.
    The equivalence tests (tests/test_host_pipeline.py) pin columnar ==
    serial on randomized records, including after profile rewrites.
    """
    if not records:
        return encode_transactions(records, user_profiles,
                                   merchant_profiles, velocities)
    user_profiles = user_profiles or {}
    merchant_profiles = merchant_profiles or {}
    velocities = velocities or {}
    if cache is None:
        cache = EntityRowCache()
    n = len(records)

    cols: Dict[str, Any] = {}
    # ---- transaction-core fields: the one remaining per-record loop
    amount: list = []
    hour_of_day: list = []
    day_of_week: list = []
    day_of_month: list = []
    is_weekend: list = []
    has_geo: list = []
    lat: list = []
    lon: list = []
    has_mgeo: list = []
    mlat: list = []
    mlon: list = []
    pm_code: list = []
    high_risk_pm: list = []
    tt_code: list = []
    ct_code: list = []
    sus_ua: list = []
    private_ip: list = []
    ip_risk: list = []
    prior_score: list = []
    has_fp: list = []
    fps: list = []                       # device fingerprint (or None)
    uid_of: list = []
    mid_of: list = []
    pm_memo: Dict[Any, tuple] = {}
    for rec in records:
        get = rec.get
        geo = get("geolocation") or {}
        mgeo = get("merchant_location") or {}
        amount.append(float(get("amount", 0.0)))
        hour_of_day.append(int(get("hour_of_day", 12)))
        day_of_week.append(int(get("day_of_week", 1)))
        day_of_month.append(int(get("day_of_month", 1)))
        is_weekend.append(bool(get("is_weekend", False)))
        has_geo.append(bool(geo) and geo.get("lat") is not None)
        lat.append(float(geo.get("lat", 0.0) or 0.0))
        lon.append(float(geo.get("lon", 0.0) or 0.0))
        has_mgeo.append(bool(mgeo) and mgeo.get("lat") is not None)
        mlat.append(float(mgeo.get("lat", 0.0) or 0.0))
        mlon.append(float(mgeo.get("lon", 0.0) or 0.0))
        pm = get("payment_method")
        pm_row = pm_memo.get(pm)
        if pm_row is None:
            pm_memo[pm] = pm_row = (
                _dcode(_PM_CODE, pm), is_high_risk_payment(pm))
        pm_code.append(pm_row[0])
        high_risk_pm.append(pm_row[1])
        tt_code.append(_dcode(_TT_CODE, get("transaction_type")))
        ct_code.append(_dcode(_CT_CODE, get("card_type")))
        sus_ua.append(is_suspicious_user_agent(get("user_agent")))
        private = is_private_ip(get("ip_address"))
        private_ip.append(private)
        ip_risk.append(0.1 if private else 0.3)
        prior_score.append(float(get("fraud_score", 0.0)))
        fp = get("device_fingerprint")
        has_fp.append(fp is not None)
        fps.append(fp)
        uid_of.append(str(get("user_id", "")))
        mid_of.append(str(get("merchant_id", "")))

    cols["amount"] = np.array(amount, np.float32)
    cols["hour_of_day"] = np.array(hour_of_day, np.int32)
    cols["day_of_week"] = np.array(day_of_week, np.int32)
    cols["day_of_month"] = np.array(day_of_month, np.int32)
    cols["is_weekend"] = np.array(is_weekend, np.bool_)
    cols["has_geo"] = np.array(has_geo, np.bool_)
    cols["lat"] = np.array(lat, np.float32)
    cols["lon"] = np.array(lon, np.float32)
    cols["has_merchant_geo"] = np.array(has_mgeo, np.bool_)
    cols["merchant_lat"] = np.array(mlat, np.float32)
    cols["merchant_lon"] = np.array(mlon, np.float32)
    cols["payment_method_code"] = np.array(pm_code, np.int32)
    cols["high_risk_payment"] = np.array(high_risk_pm, np.bool_)
    cols["transaction_type_code"] = np.array(tt_code, np.int32)
    cols["card_type_code"] = np.array(ct_code, np.int32)
    cols["suspicious_user_agent"] = np.array(sus_ua, np.bool_)
    cols["private_ip"] = np.array(private_ip, np.bool_)
    cols["ip_risk"] = np.array(ip_risk, np.float32)
    cols["prior_fraud_score"] = np.array(prior_score, np.float32)
    cols["has_txn_fingerprint"] = np.array(has_fp, np.bool_)

    # ---- user join: unique -> cached rows -> stacked tables -> gather
    u_index: Dict[str, int] = {}
    u_rows: list = []
    u_inv = np.empty((n,), np.int64)
    for i, uid in enumerate(uid_of):
        j = u_index.get(uid)
        if j is None:
            j = len(u_rows)
            u_index[uid] = j
            row = cache.users.get(uid)
            if row is None:
                cache.misses += 1
                row = _user_row_cols(user_profiles.get(uid))
                cache.users[uid] = row
            else:
                cache.hits += 1
            u_rows.append(row)
        u_inv[i] = j
    uf = np.array([r[0] for r in u_rows], np.float32)[u_inv]
    ui = np.array([r[1] for r in u_rows], np.int32)[u_inv]
    ub = np.array([r[2] for r in u_rows], np.bool_)[u_inv]
    cols["user_risk_score"] = uf[:, 0]
    cols["account_age_days"] = uf[:, 1]
    cols["user_avg_amount"] = uf[:, 2]
    cols["user_txn_frequency"] = uf[:, 3]
    cols["weekend_activity"] = uf[:, 4]
    cols["intl_ratio"] = uf[:, 5]
    cols["online_preference"] = uf[:, 6]
    cols["kyc_code"] = ui[:, 0]
    cols["preferred_start"] = ui[:, 1]
    cols["preferred_end"] = ui[:, 2]
    cols["has_user"] = ub[:, 0]
    cols["user_verified"] = ub[:, 1]
    cols["has_preferred_hours"] = ub[:, 2]
    cols["has_intl_ratio"] = ub[:, 3]
    cols["has_device_list"] = ub[:, 4]
    cols["known_device"] = np.array(
        [fp is not None and fp in u_rows[u_inv[i]][3]
         for i, fp in enumerate(fps)], np.bool_)

    # ---- merchant join
    m_index: Dict[str, int] = {}
    m_rows: list = []
    m_inv = np.empty((n,), np.int64)
    for i, mid in enumerate(mid_of):
        j = m_index.get(mid)
        if j is None:
            j = len(m_rows)
            m_index[mid] = j
            row = cache.merchants.get(mid)
            if row is None:
                cache.misses += 1
                row = _merch_row_cols(merchant_profiles.get(mid))
                cache.merchants[mid] = row
            else:
                cache.hits += 1
            m_rows.append(row)
        m_inv[i] = j
    mf = np.array([r[0] for r in m_rows], np.float32)[m_inv]
    mi = np.array([r[1] for r in m_rows], np.int32)[m_inv]
    mb = np.array([r[2] for r in m_rows], np.bool_)[m_inv]
    cols["merchant_fraud_rate"] = mf[:, 0]
    cols["merchant_avg_amount"] = mf[:, 1]
    cols["merchant_risk_code"] = mi[:, 0]
    cols["merchant_category_code"] = mi[:, 1]
    cols["merchant_op_start"] = mi[:, 2]
    cols["merchant_op_end"] = mi[:, 3]
    cols["has_merchant"] = mb[:, 0]
    cols["merchant_blacklisted"] = mb[:, 1]
    cols["merchant_high_risk_category"] = mb[:, 2]
    cols["has_op_hours"] = mb[:, 3]
    cols["suspicious_merchant_name"] = mb[:, 4]

    # ---- velocity join: one row per unique user this batch (windows move
    # every write-back, so these rows are per-batch, never cross-batch)
    v_rows = np.empty((len(u_rows), 6), np.float32)
    _EMPTY_VEL: Dict[str, Mapping[str, float]] = {}
    _EMPTY_W: Dict[str, float] = {}
    for uid, j in u_index.items():
        vel = velocities.get(uid) or _EMPTY_VEL
        w5 = vel.get("5min") or _EMPTY_W
        w1 = vel.get("1hour") or _EMPTY_W
        w24 = vel.get("24hour") or _EMPTY_W
        v_rows[j] = (float(w5.get("count", 0.0)), float(w5.get("amount", 0.0)),
                     float(w1.get("count", 0.0)), float(w1.get("amount", 0.0)),
                     float(w24.get("count", 0.0)),
                     float(w24.get("amount", 0.0)))
    vg = v_rows[u_inv]
    cols["velocity_5min_count"] = vg[:, 0]
    cols["velocity_5min_amount"] = vg[:, 1]
    cols["velocity_1hour_count"] = vg[:, 2]
    cols["velocity_1hour_amount"] = vg[:, 3]
    cols["velocity_24hour_count"] = vg[:, 4]
    cols["velocity_24hour_amount"] = vg[:, 5]

    return TransactionBatch(**cols)


_BOOL_FIELDS = {
    "is_weekend", "has_geo", "has_merchant_geo", "high_risk_payment",
    "suspicious_user_agent", "private_ip", "has_txn_fingerprint", "has_user",
    "user_verified",
    "has_preferred_hours", "has_intl_ratio", "known_device", "has_device_list",
    "has_merchant", "merchant_blacklisted", "merchant_high_risk_category",
    "has_op_hours", "suspicious_merchant_name",
}
_INT_FIELDS = {
    "hour_of_day", "day_of_week", "day_of_month", "payment_method_code",
    "transaction_type_code", "card_type_code", "kyc_code", "preferred_start",
    "preferred_end", "merchant_risk_code", "merchant_category_code",
    "merchant_op_start", "merchant_op_end",
}


def _dtype_for(name: str):
    if name in _BOOL_FIELDS:
        return np.bool_
    if name in _INT_FIELDS:
        return np.int32
    return np.float32

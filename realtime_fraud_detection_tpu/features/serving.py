"""Serving-side feature validation / derivation.

Mirror of the reference ``FeatureProcessor`` (feature_processor.py:30-402):
typed feature definitions with bounds/defaults, validation and NaN handling,
and derived features. Unlike the reference (one dict at a time, per-request
Python), this processes a whole microbatch vectorized in NumPy on the host;
its output feeds ``encode_request_features`` -> the (B, 64) model vector.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Sequence, Tuple

import numpy as np

NUMERICAL = "numerical"
BINARY = "binary"


@dataclass(frozen=True)
class FeatureDef:
    """Feature definition (feature_processor.py:30-41)."""

    name: str
    kind: str = NUMERICAL
    required: bool = False
    min_value: float | None = None
    max_value: float | None = None
    default: float = 0.0


def _defs() -> Dict[str, FeatureDef]:
    """Serving feature definitions (feature_processor.py:66-147)."""
    table: List[FeatureDef] = [
        # amount
        FeatureDef("amount", required=True, min_value=0.0),
        FeatureDef("amount_log"),
        FeatureDef("amount_percentile", min_value=0.0, max_value=100.0),
        FeatureDef("amount_zscore"),
        FeatureDef("rounded_amount_frequency", min_value=0.0),
        # temporal
        FeatureDef("hour_of_day", min_value=0, max_value=23, default=12),
        FeatureDef("day_of_week", min_value=0, max_value=6, default=1),
        FeatureDef("is_weekend", kind=BINARY),
        FeatureDef("is_holiday", kind=BINARY),
        FeatureDef("time_since_last_transaction", min_value=0.0),
        # geographic
        FeatureDef("distance_from_home", min_value=0.0),
        FeatureDef("location_risk_score", min_value=0.0, max_value=1.0),
        FeatureDef("country_risk_score", min_value=0.0, max_value=1.0, default=0.5),
        FeatureDef("timezone_mismatch", kind=BINARY),
        # user behavior
        FeatureDef("user_transaction_count_1h", min_value=0),
        FeatureDef("user_transaction_count_24h", min_value=0),
        FeatureDef("user_total_amount_24h", min_value=0.0),
        FeatureDef("user_avg_amount", min_value=0.0),
        FeatureDef("user_unique_merchants_24h", min_value=0),
        FeatureDef("user_account_age_days", min_value=0),
        # merchant
        FeatureDef("merchant_transaction_count_1h", min_value=0),
        FeatureDef("merchant_fraud_rate", min_value=0.0, max_value=1.0),
        FeatureDef("merchant_avg_amount", min_value=0.0),
        FeatureDef("merchant_risk_score", min_value=0.0, max_value=1.0, default=0.5),
        FeatureDef("merchant_category_risk", min_value=0.0, max_value=1.0, default=0.5),
        # device / network
        FeatureDef("device_risk_score", min_value=0.0, max_value=1.0, default=0.5),
        FeatureDef("is_new_device", kind=BINARY),
        FeatureDef("ip_risk_score", min_value=0.0, max_value=1.0, default=0.5),
        FeatureDef("is_tor_ip", kind=BINARY),
        FeatureDef("is_vpn_ip", kind=BINARY),
        # velocity
        FeatureDef("velocity_score", min_value=0.0, max_value=1.0),
        FeatureDef("amount_velocity_1h", min_value=0.0),
        FeatureDef("transaction_velocity_5m", min_value=0.0),
        # contextual
        FeatureDef("payment_method_risk", min_value=0.0, max_value=1.0, default=0.5),
        FeatureDef("card_type_risk", min_value=0.0, max_value=1.0, default=0.5),
        FeatureDef("is_crypto_merchant", kind=BINARY),
        FeatureDef("is_gift_card_merchant", kind=BINARY),
        FeatureDef("cross_border_transaction", kind=BINARY),
        # encoded categoricals
        FeatureDef("payment_method_encoded", min_value=0, max_value=10),
        FeatureDef("merchant_category_encoded", min_value=0, max_value=20),
        FeatureDef("card_type_encoded", min_value=0, max_value=5),
    ]
    return {d.name: d for d in table}


_METADATA_KEYS = ("transaction_id", "user_id", "merchant_id", "timestamp",
                  "currency", "payment_method")


class ServingFeatureProcessor:
    """Validates raw request features and derives the serving feature set."""

    def __init__(self) -> None:
        self.feature_definitions = _defs()

    # -- single request (API-compatible with the reference) ----------------
    def process_features(self, raw: Mapping[str, Any]) -> Dict[str, Any]:
        """Validate + derive for one request (feature_processor.py:161-194)."""
        return self.process_batch([raw])[0]

    # -- vectorized batch path ---------------------------------------------
    def process_batch(self, raws: Sequence[Mapping[str, Any]]) -> List[Dict[str, Any]]:
        results = []
        for raw in raws:
            flink = raw.get("features", {}) if isinstance(raw.get("features"), dict) else {}
            out: Dict[str, Any] = {}
            for name, d in self.feature_definitions.items():
                if name in raw:
                    value = raw[name]
                elif name in flink:
                    value = flink[name]
                elif d.required:
                    raise ValueError(f"Required feature '{name}' not found")
                else:
                    value = d.default
                out[name] = self._validate(value, d)
            out.update(self._derive(out))
            for key in _METADATA_KEYS:
                out[key] = raw.get(key, "USD" if key == "currency" else
                                   ("unknown" if key == "payment_method" else ""))
            # final finite sweep (feature_processor.py:375-402)
            for k, v in out.items():
                if isinstance(v, float) and not math.isfinite(v):
                    d = self.feature_definitions.get(k)
                    out[k] = d.default if d else 0.0
            results.append(out)
        return results

    def _validate(self, value: Any, d: FeatureDef) -> float:
        """Bounds/NaN/bool handling (feature_processor.py:224-275)."""
        try:
            if d.kind == BINARY:
                if isinstance(value, bool):
                    return 1.0 if value else 0.0
                if isinstance(value, str):
                    return 1.0 if value.lower() in ("true", "1", "yes") else 0.0
                return 1.0 if float(value) > 0.5 else 0.0
            v = float(value) if value is not None else 0.0
            if math.isnan(v) or math.isinf(v):
                return d.default
            if d.min_value is not None:
                v = max(v, d.min_value)
            if d.max_value is not None:
                v = min(v, d.max_value)
            return v
        except (ValueError, TypeError):
            return d.default

    def _derive(self, f: Dict[str, float]) -> Dict[str, float]:
        """Derived features (feature_processor.py:330-373).

        Unlike the reference, every derived key is ALWAYS emitted (0.0 when
        the inputs are absent/non-positive) so each row of a batch has an
        identical key set — otherwise ``to_model_matrix`` columns would mean
        different features for different rows.
        """
        out: Dict[str, float] = {}
        amount = f.get("amount", 0.0)
        out["amount_log"] = math.log1p(amount) if amount > 0 else 0.0
        out["amount_sqrt"] = math.sqrt(amount) if amount > 0 else 0.0
        user_avg = f.get("user_avg_amount", 1.0)
        out["amount_to_user_avg_ratio"] = amount / user_avg if user_avg > 0 else 0.0
        merchant_avg = f.get("merchant_avg_amount", 1.0)
        out["amount_to_merchant_avg_ratio"] = (
            amount / merchant_avg if merchant_avg > 0 else 0.0
        )
        c1, c24 = f.get("user_transaction_count_1h", 0), f.get("user_transaction_count_24h", 0)
        out["hourly_velocity_ratio"] = c1 / (c24 / 24) if c24 > 0 else 0.0
        out["combined_device_ip_risk"] = (
            f.get("device_risk_score", 0.5) + f.get("ip_risk_score", 0.5)
        ) / 2
        hour = f.get("hour_of_day", 12)
        out["is_business_hours"] = 1.0 if 9 <= hour <= 17 else 0.0
        out["is_late_night"] = 1.0 if hour < 6 or hour > 22 else 0.0
        return out

    # -- model input --------------------------------------------------------
    def to_model_matrix(self, processed: Sequence[Mapping[str, Any]], width: int = 64) -> np.ndarray:
        """Flatten processed dicts into the clipped (B, >=64) model input.

        Numeric fields (metadata excluded) in definition order + derived,
        zero-padded to ``width`` and clipped to +-10
        (ensemble_predictor.py:221-250).
        """
        rows = []
        for p in processed:
            vals = [float(v) for k, v in p.items()
                    if k not in _METADATA_KEYS and isinstance(v, (int, float))]
            vals = (vals + [0.0] * width)[: max(width, len(vals))]
            rows.append(vals)
        n = max(len(r) for r in rows)
        mat = np.zeros((len(rows), n), np.float32)
        for i, r in enumerate(rows):
            mat[i, : len(r)] = r
        return np.clip(mat, -10.0, 10.0)

    def get_feature_names(self) -> List[str]:
        return list(self.feature_definitions)

    def validate_feature_schema(self, features: Mapping[str, Any]) -> Tuple[bool, List[str]]:
        """Schema check (feature_processor.py:415-442)."""
        errors = []
        missing = [n for n, d in self.feature_definitions.items()
                   if d.required and n not in features]
        if missing:
            errors.append(f"Missing required features: {missing}")
        for name, value in features.items():
            d = self.feature_definitions.get(name)
            if d is None or d.kind != NUMERICAL:
                continue
            try:
                v = float(value)
                if d.min_value is not None and v < d.min_value:
                    errors.append(f"Feature {name} below minimum: {v} < {d.min_value}")
                if d.max_value is not None and v > d.max_value:
                    errors.append(f"Feature {name} above maximum: {v} > {d.max_value}")
            except (ValueError, TypeError):
                errors.append(f"Feature {name} has invalid type: {type(value)}")
        return len(errors) == 0, errors

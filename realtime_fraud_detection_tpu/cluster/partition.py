"""Key-partitioned state: the stores, sliced by the transport's hash.

All host state the stream job mutates per transaction — profiles,
velocity windows, the txn dedup cache, per-user history, the labeled
example buffer — is keyed by ``user_id``, and the transactions topic is
partitioned by the SAME key (``transport.select_partition``). So the
partition is the natural unit of state ownership: a worker that consumes
partition ``p`` owns exactly the state of the users hashing to ``p``, and
state handoff on rebalance moves whole partitions, never individual keys.

- :class:`PartitionState` — one partition's store bundle, snapshottable
  (pickle) and content-digestable (the shard drill's oracle-equality
  check).
- :class:`PartitionedStore` — the owned-partition map plus store FACADES
  (``.profiles`` / ``.velocity`` / ``.txn_cache`` / ``.history``) that
  route every call by user key, presenting the exact store interfaces
  ``FraudScorer`` and ``StreamJob`` already consume — a scorer built over
  these facades (``FraudScorer(stores=...)``) is partition-parallel
  without knowing it.

Merchant profiles are deliberately NOT partitioned: they are read-mostly
reference data every worker needs for any user's transaction (a user in
partition 3 buys from a merchant whose id hashes anywhere), so they
replicate fleet-wide like model params do, outside the handoff path. The
partitioned dimension is the high-cardinality mutable one — users
(arXiv:2109.09541's key-affine state).
"""

from __future__ import annotations

import hashlib
import json
import pickle
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from realtime_fraud_detection_tpu.cluster.hashring import partition_for_key
from realtime_fraud_detection_tpu.graph.store import (
    EDGE_TYPES,
    TypedEntityGraph,
    merge_neighbor_lists,
)
from realtime_fraud_detection_tpu.state.history import UserHistoryStore
from realtime_fraud_detection_tpu.state.labeled import LabeledExampleBuffer
from realtime_fraud_detection_tpu.state.stores import (
    ProfileStore,
    TransactionCache,
    VelocityStore,
)

__all__ = ["PartitionState", "PartitionedStore", "PartitionNotOwned"]


class PartitionNotOwned(KeyError):
    """A key routed to a partition this store does not own — a routing
    bug (router/fleet disagreement) surfacing loudly, never as silently
    missing state."""


class PartitionState:
    """One partition's complete mutable-state bundle."""

    def __init__(self, seq_len: int = 10, feature_dim: int = 64,
                 labeled_capacity: int = 1024,
                 cache_kwargs: Optional[Mapping[str, Any]] = None,
                 graph_fanout: int = 16):
        self.seq_len = int(seq_len)
        self.feature_dim = int(feature_dim)
        self.labeled_capacity = int(labeled_capacity)
        self.cache_kwargs = dict(cache_kwargs or {})
        self.graph_fanout = int(graph_fanout)
        self.profiles = ProfileStore()
        self.velocity = VelocityStore()
        self.txn_cache = TransactionCache(**self.cache_kwargs)
        self.history = UserHistoryStore(self.seq_len, self.feature_dim)
        self.labeled = LabeledExampleBuffer(
            capacity=max(self.labeled_capacity, 10))
        # typed entity graph (graph/store.py): edge data partitioned by
        # the TRANSACTION's user key, so graph writes are always local to
        # the owning worker and the bundle rides handoff snapshot /
        # SIGKILL replay / the drill digests exactly like the other stores
        self.graph = TypedEntityGraph(self.graph_fanout)

    def __setstate__(self, state: Dict[str, Any]) -> None:
        """Checkpoint migration: pre-graph-plane snapshots (PR ≤ 13 handoff
        blobs) carry no graph bundle — restore with an empty one; the
        committed-gap replay repopulates recent edges through the normal
        ingest path."""
        self.__dict__.update(state)
        if "graph" not in state:
            self.graph_fanout = int(state.get("graph_fanout", 16))
            self.graph = TypedEntityGraph(self.graph_fanout)

    # ------------------------------------------------------------- handoff
    def snapshot_bytes(self) -> bytes:
        """Serialized copy for the handoff store. A VALUE copy: the live
        stores keep mutating after the snapshot; the blob stays pinned to
        the offsets it was keyed to."""
        return pickle.dumps(self, protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def restore_bytes(blob: bytes) -> "PartitionState":
        state = pickle.loads(blob)
        if not isinstance(state, PartitionState):
            raise ValueError(
                f"handoff blob decoded to {type(state).__name__}, "
                f"not PartitionState")
        return state

    # -------------------------------------------------------------- digest
    def digest(self, now: Optional[float] = None) -> str:
        """Deterministic content hash over everything the oracle-equality
        check cares about: user profiles, velocity windows, per-user
        history rings, and the txn cache's (id → score/decision) map.
        Stable across pickling round trips and across different BATCHINGS
        of the same per-partition record sequence (state updates are
        keyed to event time, so batch boundaries leave no residue).
        ``now`` is the TTL clock for the cache listing — pass the run's
        virtual end time on a virtual timeline (the default would expire
        virtual-time entries against the wall clock)."""
        h = hashlib.sha256()

        def feed(obj: Any) -> None:
            h.update(json.dumps(obj, sort_keys=True,
                                default=str).encode())

        feed({"users": self.profiles.users})
        feed(self.velocity.entries())
        feed([(tid, round(float(v.get("fraud_score", -1.0)), 6),
               str(v.get("decision", "")))
              for tid, v in self.txn_cache.entries(now)])
        uids = sorted(self.history.user_ids())
        feed(uids)
        if uids:
            hist, lens = self.history.gather(uids)
            h.update(np.ascontiguousarray(
                np.round(hist, 5).astype(np.float32)).tobytes())
            h.update(np.ascontiguousarray(lens.astype(np.int64)).tobytes())
        feed({"labeled": self.labeled.stats()})
        feed({"graph": self.graph.digest()})
        return h.hexdigest()


# ---------------------------------------------------------------- facades


class _ProfilesFacade:
    """ProfileStore interface over the owned-partition map. User profiles
    route by key; merchant profiles live in the shared replicated store."""

    def __init__(self, store: "PartitionedStore"):
        self._store = store

    @property
    def generation(self) -> int:
        # columnar-assembly cache coherence (features/schema.EntityRowCache
        # compares this stamp): sum of per-partition generations — any
        # partition's write (or a handoff swapping a whole partition in)
        # changes the sum
        return (sum(s.profiles.generation
                    for s in self._store.states().values())
                + self._store.merchants_generation)

    def seed(self, users: Optional[Mapping[str, Mapping[str, Any]]] = None,
             merchants: Optional[Mapping[str, Mapping[str, Any]]] = None,
             ) -> None:
        if users:
            for uid, prof in users.items():
                self._store.state_for_user(uid).profiles.seed(
                    users={uid: prof})
        if merchants:
            self._store.shared_merchants.update(merchants)
            self._store.merchants_generation += 1

    def get_user(self, user_id: str) -> Optional[Mapping[str, Any]]:
        return self._store.state_for_user(user_id).profiles.get_user(user_id)

    def put_user(self, user_id: str, profile: Mapping[str, Any]) -> None:
        self._store.state_for_user(user_id).profiles.put_user(user_id,
                                                              profile)

    def get_merchant(self, merchant_id: str) -> Optional[Mapping[str, Any]]:
        return self._store.shared_merchants.get(merchant_id)

    def put_merchant(self, merchant_id: str,
                     profile: Mapping[str, Any]) -> None:
        self._store.shared_merchants[merchant_id] = profile
        self._store.merchants_generation += 1


class _VelocityFacade:
    def __init__(self, store: "PartitionedStore"):
        self._store = store

    def update(self, user_id: str, amount: float, now: float) -> None:
        self._store.state_for_user(user_id).velocity.update(
            user_id, amount, now)

    def update_batch(self, user_ids, amounts, now: float) -> None:
        for uid, amt in zip(user_ids, amounts):
            self.update(uid, float(amt), now)

    def get(self, user_id: str, window: str,
            now: Optional[float] = None) -> Dict[str, float]:
        return self._store.state_for_user(user_id).velocity.get(
            user_id, window, now)

    def get_all(self, user_id: str,
                now: Optional[float] = None) -> Dict[str, Dict[str, float]]:
        return self._store.state_for_user(user_id).velocity.get_all(
            user_id, now)


class _TxnCacheFacade:
    """TransactionCache interface. Writes route by the transaction's own
    user key; id-only reads scan the owned partitions (a user's records
    always land in one partition, so a hit is unique; the scan is a
    handful of dict lookups)."""

    def __init__(self, store: "PartitionedStore"):
        self._store = store

    def cache_transaction(self, txn: Mapping[str, Any],
                          now: Optional[float] = None) -> None:
        uid = str(txn.get("user_id", ""))
        self._store.state_for_user(uid).txn_cache.cache_transaction(
            txn, now=now)

    def get_transaction(self, txn_id: str,
                        now: Optional[float] = None) -> Any:
        for state in self._store.states().values():
            hit = state.txn_cache.get_transaction(txn_id, now=now)
            if hit is not None:
                return hit
        return None

    def store_features(self, txn_id: str, features: Any,
                       now: Optional[float] = None) -> None:
        # features are keyed by txn id alone; store them with the txn's
        # user partition when the txn is cached. For an unknown txn the
        # id hashes to an arbitrary partition this worker almost surely
        # does NOT own — fall back to an owned partition picked by the
        # id hash (get_features scans every owned partition, so reads
        # still hit; the blob is worker-local best-effort cache, not
        # handed-off truth)
        txn = self.get_transaction(txn_id, now=now)
        if txn is not None:
            state = self._store.state_for_user(str(txn.get("user_id", "")))
        else:
            owned = self._store.owned()
            if not owned:
                raise PartitionNotOwned(
                    f"cannot store features for {txn_id!r}: no owned "
                    f"partitions")
            state = self._store.state(
                owned[partition_for_key(str(txn_id), len(owned))])
        state.txn_cache.store_features(txn_id, features, now=now)

    def get_features(self, txn_id: str, now: Optional[float] = None) -> Any:
        for state in self._store.states().values():
            hit = state.txn_cache.get_features(txn_id, now=now)
            if hit is not None:
                return hit
        return None

    def get_user_transactions(self, user_id: str,
                              limit: int = 100) -> List[str]:
        return self._store.state_for_user(
            user_id).txn_cache.get_user_transactions(user_id, limit)

    def get_merchant_transactions(self, merchant_id: str,
                                  limit: int = 500) -> List[str]:
        out: List[str] = []
        for state in self._store.states().values():
            out.extend(state.txn_cache.get_merchant_transactions(
                merchant_id, limit))
        return out[:limit]


class _HistoryFacade:
    """UserHistoryStore interface with per-user routing. Batch calls are
    regrouped by partition and scattered back in input order, preserving
    the store's sequential per-user semantics (a user's rows all live in
    one partition, so in-batch duplicate handling is unchanged)."""

    def __init__(self, store: "PartitionedStore"):
        self._store = store

    @property
    def seq_len(self) -> int:
        return self._store.seq_len

    @property
    def feature_dim(self) -> int:
        return self._store.feature_dim

    def _group(self, user_ids: Sequence[str]) -> Dict[int, List[int]]:
        groups: Dict[int, List[int]] = {}
        for i, uid in enumerate(user_ids):
            groups.setdefault(self._store.partition_for(uid), []).append(i)
        return groups

    def append_batch(self, user_ids: Sequence[str],
                     features: np.ndarray) -> None:
        if not len(user_ids):
            return
        features = np.asarray(features, np.float32)
        for p, idxs in self._group(user_ids).items():
            self._store.state(p).history.append_batch(
                [user_ids[i] for i in idxs], features[idxs])

    def append_and_gather(self, user_ids: Sequence[str],
                          features: np.ndarray):
        b = len(user_ids)
        out = np.zeros((b, self.seq_len, self.feature_dim), np.float32)
        lens = np.zeros((b,), np.int32)
        if not b:
            return out, lens
        features = np.asarray(features, np.float32)
        for p, idxs in self._group(user_ids).items():
            sub_out, sub_lens = self._store.state(p).history.append_and_gather(
                [user_ids[i] for i in idxs], features[idxs])
            out[idxs], lens[idxs] = sub_out, sub_lens
        return out, lens

    def gather(self, user_ids: Sequence[str]):
        b = len(user_ids)
        out = np.zeros((b, self.seq_len, self.feature_dim), np.float32)
        lens = np.zeros((b,), np.int32)
        if not b:
            return out, lens
        for p, idxs in self._group(user_ids).items():
            sub_out, sub_lens = self._store.state(p).history.gather(
                [user_ids[i] for i in idxs])
            out[idxs], lens[idxs] = sub_out, sub_lens
        return out, lens

    def __len__(self) -> int:
        return sum(len(s.history) for s in self._store.states().values())


class _GraphFacade:
    """TypedEntityGraph interface over the owned-partition map.

    Writes route by the transaction's USER key — the same affinity rule
    as every other store, so graph mutation is always partition-local
    and the bundle hands off with its partition. Reads for user-keyed
    edge types (``user->*``) route the same way; entity-keyed reads
    (``device->user`` etc.) merge the OWNED partitions' rings (a device
    shared by users of several owned partitions has its adjacency spread
    across them); non-owned shares are the fetch plane's job
    (graph/fetch.py), not this facade's."""

    def __init__(self, store: "PartitionedStore"):
        self._store = store

    @property
    def fanout(self) -> int:
        return self._store.graph_fanout

    @property
    def generation(self) -> int:
        # observability stamp (stats()/graph_snapshot): any partition's
        # ingest changes the sum. Coherence is drain_dirty +
        # ownership_epoch, not this counter.
        return sum(s.graph.generation
                   for s in self._store.states().values())

    @property
    def ownership_epoch(self) -> int:
        # wholesale-invalidation signal: acquire/release swap whole
        # graphs without per-id dirt (NeighborSampler.sync clears on it)
        return self._store.ownership_epoch

    def add_batch(self, user_ids: Sequence[str],
                  merchant_ids: Sequence[str],
                  device_ids: Sequence[str], ips: Sequence[str]) -> None:
        groups: Dict[int, List[int]] = {}
        for i, uid in enumerate(user_ids):
            groups.setdefault(self._store.partition_for(str(uid)),
                              []).append(i)
        for p, idxs in groups.items():
            self._store.state(p).graph.add_batch(
                [user_ids[i] for i in idxs],
                [merchant_ids[i] for i in idxs],
                [device_ids[i] for i in idxs],
                [ips[i] for i in idxs])

    def neighbors(self, edge_type: str, ids: Sequence[str],
                  fanout: Optional[int] = None) -> List[List[str]]:
        if edge_type not in EDGE_TYPES:
            raise ValueError(f"unknown edge type {edge_type!r}")
        k = self.fanout if fanout is None else max(1, int(fanout))
        if edge_type.startswith("user->"):
            out: List[List[str]] = [[] for _ in ids]
            groups: Dict[int, List[int]] = {}
            for i, uid in enumerate(ids):
                groups.setdefault(self._store.partition_for(str(uid)),
                                  []).append(i)
            for p, idxs in groups.items():
                state = self._store.states().get(p)
                if state is None:
                    continue      # non-owned user: cold locally, not a bug
                rings = state.graph.neighbors(
                    edge_type, [ids[i] for i in idxs], k)
                for i, ring in zip(idxs, rings):
                    out[i] = ring
            return out
        # entity-keyed: merge the owned partitions' rings in sorted
        # partition order (deterministic; cross-partition shares arrive
        # via the fetch plane)
        maps = [self._store.state(p).graph.neighbor_map(edge_type, ids, k)
                for p in self._store.owned()]
        if not maps:
            return [[] for _ in ids]
        merged = merge_neighbor_lists(maps[0], maps[1:], ids, k)
        return [merged[str(i)] for i in ids]

    def neighbor_map(self, edge_type: str, ids: Sequence[str],
                     fanout: Optional[int] = None) -> Dict[str, List[str]]:
        """Local merged view ({id: neighbors}, empties omitted) — the
        GraphFetchServer's read seam: exactly what THIS worker's owned
        partitions know, never a recursive remote fetch."""
        out: Dict[str, List[str]] = {}
        for i, ring in zip(ids, self.neighbors(edge_type, ids, fanout)):
            if ring:
                out[str(i)] = ring
        return out

    def degree(self, edge_type: str, ids: Sequence[str]) -> List[int]:
        return [len(r) for r in self.neighbors(edge_type, ids)]

    def drain_dirty(self) -> List[str]:
        dirty: set = set()
        for s in self._store.states().values():
            dirty.update(s.graph.drain_dirty())
        return sorted(dirty)

    def stats(self) -> Dict[str, Any]:
        per = [s.graph.stats() for s in self._store.states().values()]
        nodes = {t: sum(p["nodes"][t] for p in per) for t in
                 ("user", "device", "merchant", "ip")} if per else {}
        edges = {et: sum(p["edges"][et] for p in per)
                 for et in EDGE_TYPES} if per else {}
        return {"fanout": self.fanout, "generation": self.generation,
                "edges_added": sum(p["edges_added"] for p in per),
                "nodes": nodes, "edges": edges}


# ----------------------------------------------------------------- store


class PartitionedStore:
    """Owned-partition state map + routing facades.

    One instance per worker. The fleet acquires/releases partitions on
    rebalance (`acquire`/`release`); every facade call on an un-owned key
    raises :class:`PartitionNotOwned` — the affinity contract is enforced,
    not assumed.
    """

    def __init__(self, n_partitions: int, seq_len: int = 10,
                 feature_dim: int = 64, labeled_capacity: int = 1024,
                 cache_kwargs: Optional[Mapping[str, Any]] = None,
                 graph_fanout: int = 16):
        if n_partitions < 1:
            raise ValueError(
                f"n_partitions must be >= 1, got {n_partitions}")
        self.n_partitions = int(n_partitions)
        self.seq_len = int(seq_len)
        self.feature_dim = int(feature_dim)
        self.labeled_capacity = int(labeled_capacity)
        self.cache_kwargs = dict(cache_kwargs or {})
        self.graph_fanout = int(graph_fanout)
        self._states: Dict[int, PartitionState] = {}
        # bumped on every acquire/release: a handoff swaps WHOLE graphs
        # in/out without marking per-id dirt, so ownership changes are the
        # sampler cache's wholesale-invalidation signal
        self.ownership_epoch = 0
        # read-mostly reference data replicated to every worker (never in
        # a handoff blob): merchant profiles
        self.shared_merchants: Dict[str, Mapping[str, Any]] = {}
        self.merchants_generation = 0
        self.profiles = _ProfilesFacade(self)
        self.velocity = _VelocityFacade(self)
        self.txn_cache = _TxnCacheFacade(self)
        self.history = _HistoryFacade(self)
        self.graph = _GraphFacade(self)

    # ------------------------------------------------------------- routing
    def partition_for(self, key: str) -> int:
        return partition_for_key(str(key), self.n_partitions)

    def owned(self) -> List[int]:
        return sorted(self._states)

    def owns(self, partition: int) -> bool:
        return partition in self._states

    def states(self) -> Dict[int, PartitionState]:
        return self._states

    def state(self, partition: int) -> PartitionState:
        try:
            return self._states[partition]
        except KeyError:
            raise PartitionNotOwned(
                f"partition {partition} not owned "
                f"(owned: {self.owned()})") from None

    def state_for_user(self, user_id: str) -> PartitionState:
        return self.state(self.partition_for(user_id))

    # ------------------------------------------------------------ ownership
    def fresh_state(self) -> PartitionState:
        return PartitionState(self.seq_len, self.feature_dim,
                              self.labeled_capacity, self.cache_kwargs,
                              graph_fanout=self.graph_fanout)

    def acquire(self, partition: int,
                state: Optional[PartitionState] = None) -> PartitionState:
        """Take ownership of a partition, adopting a restored state (the
        handoff path) or a fresh one."""
        if not 0 <= partition < self.n_partitions:
            raise ValueError(
                f"partition {partition} outside [0, {self.n_partitions})")
        if partition in self._states:
            raise ValueError(f"partition {partition} already owned")
        st = state if state is not None else self.fresh_state()
        self._states[partition] = st
        self.ownership_epoch += 1
        return st

    def release(self, partition: int) -> PartitionState:
        """Give up a partition, returning its (live) state for snapshot."""
        st = self._states.pop(partition)
        self.ownership_epoch += 1
        return st

    # -------------------------------------------------------------- summary
    def stats(self) -> Dict[str, Any]:
        return {
            "n_partitions": self.n_partitions,
            "owned": self.owned(),
            "users": sum(len(s.profiles.users)
                         for s in self._states.values()),
            "history_users": len(self.history),
            "merchants": len(self.shared_merchants),
        }

    def digests(self, now: Optional[float] = None) -> Dict[int, str]:
        """Per-owned-partition content digests (oracle-equality checks)."""
        return {p: s.digest(now) for p, s in sorted(self._states.items())}

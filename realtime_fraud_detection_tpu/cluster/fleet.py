"""Partition-parallel worker fleet: N StreamJob workers, key-sharded state,
checkpointed handoff.

One ``WorkerFleet`` = one consumer group over the transactions topic. Each
:class:`ClusterWorker` wraps a real ``stream/job.StreamJob`` whose consumer
is SCOPED to the partitions the fleet's hash ring assigns it
(``transport.Consumer(partitions=...)``) and whose scorer reads/writes a
:class:`cluster.partition.PartitionedStore` owning exactly those
partitions — broker-partition affinity implies state affinity, so no two
workers ever write one user's state.

**Checkpointed handoff.** Every ``checkpoint_every`` completed batches a
worker snapshots ONE owned partition's state (round-robin, so the cost
is amortized and snapshot ages stagger) into the shared
:class:`HandoffStore`, keyed to that partition's COMMITTED offset at the
instant of the snapshot (state write-back happens before commit, so state
⇔ committed-offset consistency holds by the job's own ordering). On
worker loss the ring reassigns only the dead worker's partitions
(consistent hashing — survivors' partitions never move); each inheritor:

1. restores the latest snapshot (state as of offset ``O_s``),
2. **state-replays** the committed gap ``[O_s, O_c)`` — the records the
   dead worker scored, emitted, and committed AFTER its last snapshot —
   through the scorer's ``replay_state`` seam: state updates (velocity,
   profiles, history, dedup cache) are re-applied through the existing
   dedup path, but nothing is re-emitted, because those predictions
   already reached the output topics (commit-after-fan-out guarantees
   it). Zero double-scored transactions, state caught up to ``O_c``.
3. resumes normal consumption from ``O_c`` — the genuinely uncommitted
   tail (dispatched-but-never-committed work died with the worker) now
   replays through the normal scoring path, exactly once.

The acceptance artifact is ``rtfd shard-drill`` (cluster/drill.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from realtime_fraud_detection_tpu.cluster.hashring import (
    HashRing,
    ShardRouter,
)
from realtime_fraud_detection_tpu.cluster.partition import (
    PartitionedStore,
    PartitionState,
)
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
from realtime_fraud_detection_tpu.stream.microbatch import MicrobatchAssembler
from realtime_fraud_detection_tpu.serving.validation import sanitize_for_stream

__all__ = ["HandoffStore", "ClusterWorker", "WorkerFleet"]


class HandoffStore:
    """Shared snapshot ledger: partition → (committed offset, state blob).

    The durable rendezvous between a dying worker's past checkpoints and
    its partitions' inheritors. In-process it is a locked dict; the
    network-served form — same ``put``/``get`` surface, crash-safe
    atomic blobs, sha256-verified restore, zombie fencing — is
    ``cluster.handoff.HandoffServer``/``HandoffClient`` (the process-mode
    fleet's store; a ``ClusterWorker`` takes either interchangeably).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._snaps: Dict[int, Tuple[int, bytes]] = {}
        self.snapshots_taken = 0

    def put(self, partition: int, offset: int, blob: bytes) -> None:
        with self._lock:
            self._snaps[int(partition)] = (int(offset), blob)
            self.snapshots_taken += 1

    def get(self, partition: int) -> Optional[Tuple[int, bytes]]:
        with self._lock:
            return self._snaps.get(int(partition))

    def offsets(self) -> Dict[int, int]:
        with self._lock:
            return {p: off for p, (off, _) in sorted(self._snaps.items())}


class ClusterWorker:
    """One partition-scoped StreamJob worker inside a fleet."""

    def __init__(self, worker_id: str, broker: Any, scorer: Any,
                 store: PartitionedStore, handoff: HandoffStore,
                 group_id: str, topic: str = T.TRANSACTIONS,
                 clock: Optional[Callable[[], float]] = None,
                 max_batch: int = 128, max_delay_ms: float = 20.0,
                 checkpoint_every: int = 8, autotune: Any = None,
                 tracing: Any = None, expect_carrier: bool = False):
        self.worker_id = worker_id
        self.broker = broker
        self.scorer = scorer
        self.store = store
        self.handoff = handoff
        self.group_id = group_id
        self.topic = topic
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.alive = True
        self.job = StreamJob(broker, scorer, JobConfig(
            group_id=group_id, max_batch=max_batch,
            max_delay_ms=max_delay_ms, emit_features=False,
            emit_enriched=False, transactions_topic=topic,
            autotune=autotune, tracing=tracing,
            expect_carrier=expect_carrier))
        # partition-scoped consumer + (virtual-clock capable) assembler
        # replace the job's defaults — the drill idiom every plane uses.
        # The job's tuning plane (if any) stays attached as the new
        # assembler's close controller, so a process-mode worker's batch
        # closes are arrival-aware and its in-flight depth tuner-driven.
        self.consumer = broker.consumer([topic], group_id,
                                        partitions={topic: []})
        self.job.consumer = self.consumer
        kw = {"clock": clock} if clock is not None else {}
        self.assembler = MicrobatchAssembler(
            self.consumer, max_batch=max_batch,
            max_delay_ms=max_delay_ms, controller=self.job.tuning, **kw)
        self.job.assembler = self.assembler
        # virtual in-flight window (ctx, done_time), managed by the drive
        # loop; busy_until models the worker's serial compute resource
        self.in_flight: deque = deque()
        self.busy_until = 0.0
        self.completions = 0
        self.checkpoints = 0
        self.replayed_total = 0
        self.handoffs_in = 0
        self._since_checkpoint = 0
        self._ckpt_rr = 0

    # ------------------------------------------------------------ ownership
    def set_assignment(self, partitions: Sequence[int],
                       now: Optional[float] = None) -> Dict[str, int]:
        """Adopt a new partition set: released partitions are snapshotted
        then dropped; acquired ones restore + state-replay (the handoff
        path). Returns counters for the fleet ledger."""
        target = sorted(int(p) for p in partitions)
        current = set(self.store.owned())
        released = acquired = replayed = 0
        for p in sorted(current - set(target)):
            self._checkpoint_partition(p)
            self.store.release(p)
            released += 1
        for p in (q for q in target if q not in current):
            replayed += self._acquire_partition(p, now)
            acquired += 1
        self.consumer.set_assignment({self.topic: target})
        if acquired:
            self.handoffs_in += acquired
        self.replayed_total += replayed
        return {"released": released, "acquired": acquired,
                "replayed": replayed}

    def _acquire_partition(self, p: int, now: Optional[float]) -> int:
        """Restore the partition's last snapshot and state-replay the
        committed gap; returns the replay depth (records)."""
        snap = self.handoff.get(p)
        state: Optional[PartitionState] = None
        from_off = 0
        if snap is not None:
            from_off, blob = snap
            state = PartitionState.restore_bytes(blob)
        self.store.acquire(p, state)
        committed = self.broker.committed(self.group_id, self.topic, p)
        replayed = 0
        off = from_off
        while off < committed:
            recs = self.broker.read(self.topic, p, off,
                                    min(2048, committed - off))
            if not recs:
                break
            off = recs[-1].offset + 1
            batch = []
            for r in recs:
                txn, errors = sanitize_for_stream(r.value)
                if errors:
                    continue
                # the existing dedup path: anything the restored snapshot
                # already covers (or a producer duplicate) is skipped
                if self.store.txn_cache.get_transaction(
                        str(txn["transaction_id"]), now=now) is not None:
                    continue
                batch.append(txn)
            if batch:
                self.scorer.replay_state(batch, now=now)
                replayed += len(batch)
        return replayed

    # ----------------------------------------------------------- checkpoint
    def _checkpoint_partition(self, p: int) -> None:
        # offset FIRST, snapshot second: a commit landing between the two
        # would key the (newer) state to an older offset, and the replay
        # would re-apply records the snapshot already contains. Within a
        # single-threaded worker the order is moot; keep the safe one.
        committed = self.broker.committed(self.group_id, self.topic, p)
        self.handoff.put(p, committed,
                         self.store.state(p).snapshot_bytes())

    def checkpoint(self) -> int:
        """Snapshot every owned partition keyed to its committed offset."""
        for p in self.store.owned():
            self._checkpoint_partition(p)
        self.checkpoints += 1
        return len(self.store.owned())

    def abandon(self) -> int:
        """Fenced-writer recovery: drop every owned partition WITHOUT
        checkpointing. This worker lost its partitions in a rebalance it
        never observed (asymmetric partition, session expiry) — the
        inheritors restored from the last good checkpoint and replayed
        the committed gap, so THEIR state is the truth; a checkpoint from
        here would carry a stale epoch (refused by the handoff fence) and
        must not even be attempted. Pending assembler records are
        discarded too: nothing for a lost partition may be dispatched.
        Returns the number of partitions dropped; the worker re-enters
        the fleet as a fresh member (hello → rebalance → restore)."""
        while True:
            batch = self.assembler.next_batch(block=False) \
                or self.assembler.flush()
            if not batch:
                break
        dropped = 0
        for p in list(self.store.owned()):
            self.store.release(p)
            dropped += 1
        self.consumer.set_assignment({self.topic: []})
        self.in_flight.clear()
        return dropped

    def on_batch_complete(self) -> None:
        """Drive-loop hook after each ``complete_batch``: every
        ``checkpoint_every`` completions, snapshot ONE owned partition
        (round-robin). Amortized, not burst: a worker owning P partitions
        never pays P pickles in one completion, and the staggered
        snapshot ages mean a worker loss at ANY instant leaves most
        partitions with a committed gap for the state-replay path — the
        recovery cost is bounded by cadence × P, not by luck."""
        self.completions += 1
        self._since_checkpoint += 1
        if self._since_checkpoint >= self.checkpoint_every:
            self._since_checkpoint = 0
            owned = self.store.owned()
            if owned:
                self._checkpoint_partition(
                    owned[self._ckpt_rr % len(owned)])
                self._ckpt_rr += 1
                self.checkpoints += 1


class WorkerFleet:
    """N partition-scoped workers + ring placement + handoff + router."""

    def __init__(self, broker: Any, n_workers: int, n_partitions: int,
                 scorer_factory: Callable[[str, PartitionedStore], Any],
                 group_id: str = "fraud-cluster",
                 topic: str = T.TRANSACTIONS,
                 clock: Optional[Callable[[], float]] = None,
                 max_batch: int = 128, max_delay_ms: float = 20.0,
                 checkpoint_every: int = 8, virtual_nodes: int = 256,
                 store_kwargs: Optional[Dict[str, Any]] = None):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.broker = broker
        self.n_partitions = int(n_partitions)
        self.topic = topic
        self.group_id = group_id
        self.handoff = HandoffStore()
        ids = [f"w{i}" for i in range(n_workers)]
        self.ring = HashRing(ids, virtual_nodes=virtual_nodes)
        self.router = ShardRouter(n_partitions, ids,
                                  virtual_nodes=virtual_nodes)
        self.generation = 1
        self.handoffs_total = 0
        self.replayed_total = 0
        self.last_replay_depth = 0
        self.kills = 0
        self.events: List[Dict[str, Any]] = []
        self.workers: Dict[str, ClusterWorker] = {}
        assignment = self.ring.assignment(self.n_partitions)
        for wid in ids:
            store = PartitionedStore(self.n_partitions,
                                     **(store_kwargs or {}))
            worker = ClusterWorker(
                wid, broker, scorer_factory(wid, store), store,
                self.handoff, group_id, topic=topic, clock=clock,
                max_batch=max_batch, max_delay_ms=max_delay_ms,
                checkpoint_every=checkpoint_every)
            worker.set_assignment(assignment[wid], now=0.0)
            self.workers[wid] = worker

    # -------------------------------------------------------------- queries
    def alive_workers(self) -> List[ClusterWorker]:
        return [w for w in self.workers.values() if w.alive]

    def owner_of_partition(self, p: int) -> str:
        return self.ring.owner_of_partition(p)

    def worker_for_user(self, user_id: str) -> ClusterWorker:
        return self.workers[self.router.route(user_id)]

    # ---------------------------------------------------------------- kill
    def kill_worker(self, worker_id: str,
                    now: Optional[float] = None) -> Dict[str, Any]:
        """Process-death semantics: the worker's live state and in-flight
        batches are GONE (no graceful flush, no final snapshot); its
        partitions move to the survivors via restore + state-replay."""
        w = self.workers.get(worker_id)
        if w is None or not w.alive:
            return {"killed": False}
        w.alive = False
        w.in_flight.clear()
        dead_parts = list(w.store.owned())
        for p in dead_parts:
            # the dead process's live state is GONE — drop it, so the
            # fleet snapshot (and the cluster_partitions_owned mirror)
            # never shows a corpse still owning partitions the survivors
            # now hold; inheritors recover from HandoffStore, never from
            # this store
            w.store.release(p)
        self.ring.remove(worker_id)
        survivors = [sw for sw in self.workers.values() if sw.alive]
        if not survivors:
            raise RuntimeError("cannot kill the last alive worker")
        self.generation += 1
        self.kills += 1
        assignment = self.ring.assignment(self.n_partitions)
        replayed = 0
        for sw in survivors:
            counts = sw.set_assignment(assignment[sw.worker_id], now=now)
            replayed += counts["replayed"]
        moved = self.router.set_membership(
            [sw.worker_id for sw in survivors])
        self.handoffs_total += len(dead_parts)
        self.replayed_total += replayed
        self.last_replay_depth = replayed
        self.events.append({
            "event": "worker_kill", "worker": worker_id,
            "ts": now, "partitions": sorted(dead_parts),
            "partitions_moved": len(dead_parts),
            "router_moved": moved, "replayed": replayed,
            "generation": self.generation,
        })
        return {"killed": True, "partitions_moved": dead_parts,
                "replayed": replayed, "router_moved": moved}

    # -------------------------------------------------------------- summary
    def assignment(self) -> Dict[str, List[int]]:
        return {wid: w.store.owned() for wid, w in self.workers.items()
                if w.alive}

    def counters(self) -> Dict[str, int]:
        c = {"scored": 0, "shed": 0, "duplicates_skipped": 0, "errors": 0,
             "batches": 0, "alerts": 0}
        for w in self.workers.values():
            for k in c:
                c[k] += w.job.counters.get(k, 0)
        return c

    def lag(self) -> int:
        return sum(w.consumer.lag() for w in self.alive_workers())

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able fleet state shaped for
        ``obs.metrics.MetricsCollector.sync_cluster``."""
        return {
            "generation": self.generation,
            "workers_alive": len(self.alive_workers()),
            "workers": {
                wid: {
                    "alive": w.alive,
                    "partitions_owned": len(w.store.owned()),
                    "completions": w.completions,
                    "checkpoints": w.checkpoints,
                    "replayed": w.replayed_total,
                } for wid, w in sorted(self.workers.items())
            },
            "handoffs_total": self.handoffs_total,
            "replayed_total": self.replayed_total,
            "last_replay_depth": self.last_replay_depth,
            "checkpoints_total": self.handoff.snapshots_taken,
            "kills": self.kills,
            "router": self.router.snapshot(),
            "events": list(self.events),
        }

"""Network-served handoff: checkpoint blobs that survive any worker's death.

PR 10's ``HandoffStore`` was a locked dict shared by threads — the blob
format (``PartitionState.snapshot_bytes`` keyed to committed offsets) was
already what a networked object store would hold, and this module makes it
one. The fleet's workers become OS processes (cluster/procfleet.py), so a
worker's SIGKILL must not take its partitions' recovery state with it:

- :class:`HandoffServer` — a TCP server (the netbroker's length-prefixed
  JSON framing) owning the snapshot ledger, durable on disk with
  **crash-safe atomic commit**: every blob is written to a temp file,
  fsync'd, then renamed into place, and the previous checkpoint file is
  RETAINED until the new one is committed. A restore verifies the blob
  against its recorded sha256 — a torn/truncated file (server crash
  mid-write, disk corruption) is detected and the PREVIOUS checkpoint is
  served instead, with the committed-gap replay covering the difference
  (the gap is just larger). Torn blobs are counted, never silently used.
- **offset-epoch fencing**: the fleet coordinator fences a partition at a
  new epoch on every rebalance; a checkpoint ``put`` carrying a stale
  epoch — a zombie worker that lost the partition but kept running — is
  refused loudly (``FENCED``), so a slow old owner can never overwrite an
  inheritor's newer state (the classic split-brain writer, closed the same
  way Kafka fences zombie producers).
- :class:`HandoffClient` — the worker-side client, implementing the exact
  ``put``/``get`` surface ``cluster.fleet.ClusterWorker`` consumes, with
  bounded ``DeterministicBackoff`` reconnect: a handoff-server restart
  mid-restore is retried against the same address, not surfaced as a
  worker crash.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import socketserver
import threading
import time
from pathlib import Path
from typing import Any, Dict, Mapping, Optional, Tuple

from realtime_fraud_detection_tpu.stream.netbroker import (
    _recv_frame,
    _send_frame,
)

__all__ = ["HandoffServer", "HandoffClient", "FencedEpochError"]


class FencedEpochError(RuntimeError):
    """A checkpoint put carried an epoch older than the partition's fence —
    the writer lost ownership in a rebalance it has not observed yet (a
    zombie). The put is refused; the zombie must re-read its assignment."""


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        server: HandoffServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server._conns.add(sock)
        try:
            while True:
                try:
                    req = _recv_frame(sock)
                except (ConnectionError, ValueError, OSError):
                    return
                if req is None:
                    return
                try:
                    resp = server.dispatch(req)
                except Exception as e:  # noqa: BLE001 - per-request isolation
                    resp = {"error": f"{type(e).__name__}: {e}"}
                try:
                    _send_frame(sock, resp)
                except (ConnectionError, OSError):
                    return
        finally:
            server._conns.discard(sock)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class HandoffServer:
    """Serve the partition-snapshot ledger over TCP, durably.

    Disk layout (``blob_dir``): one committed file per checkpoint,
    ``p{partition}-{offset}-{epoch}.blob``, whose first 65 bytes are the
    hex sha256 of the payload plus a newline. Writes go temp→fsync→rename
    (atomic on POSIX), and the previous committed file for the partition
    is kept until the NEXT checkpoint lands — so at any crash instant a
    partition has at least one fully-committed, checksum-verifiable blob
    on disk. ``blob_dir=None`` keeps everything in memory (unit tests).
    """

    KEEP_PER_PARTITION = 2      # current + previous (torn-blob fallback)

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 blob_dir: Optional[str] = None):
        self.blob_dir = Path(blob_dir) if blob_dir else None
        self._lock = threading.Lock()
        # partition -> newest-first [(offset, epoch, sha, blob|None, path)]
        self._ledger: Dict[int, list] = {}
        self._fence: Dict[int, int] = {}
        self._conns: set = set()
        self.checkpoints_total = 0
        self.restores_total = 0
        self.torn_blobs_total = 0
        self.fenced_rejects_total = 0
        if self.blob_dir is not None:
            self.blob_dir.mkdir(parents=True, exist_ok=True)
            self._scan()
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="handoff-server",
            daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HandoffServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        for sock in list(self._conns):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ------------------------------------------------------------ durability
    def _scan(self) -> None:
        """Rebuild the ledger from committed files (server restart). Files
        are trusted only as far as their embedded checksum — verification
        happens at restore time, so a torn file found here still falls
        back to its predecessor."""
        for path in self.blob_dir.glob("p*-*-*.blob"):
            try:
                p_s, off_s, ep_s = path.stem[1:].split("-")
                p, off, ep = int(p_s), int(off_s), int(ep_s)
            except ValueError:
                continue
            self._ledger.setdefault(p, []).append((off, ep, None, None, path))
        for entries in self._ledger.values():
            # newest first: highest (epoch, offset) wins
            entries.sort(key=lambda e: (e[1], e[0]), reverse=True)

    def _commit_blob(self, p: int, offset: int, epoch: int,
                     sha: str, blob: bytes) -> Optional[Path]:
        if self.blob_dir is None:
            return None
        path = self.blob_dir / f"p{p}-{offset}-{epoch}.blob"
        tmp = self.blob_dir / f".p{p}-{offset}-{epoch}.tmp"
        with open(tmp, "wb") as f:
            f.write(sha.encode() + b"\n" + blob)
            f.flush()
            os.fsync(f.fileno())
        tmp.replace(path)          # atomic: a reader sees old file or new
        return path

    @staticmethod
    def _read_blob(entry: tuple) -> Optional[Tuple[str, bytes]]:
        """(sha, payload) from a ledger entry, or None when the committed
        file is torn (checksum mismatch / truncation)."""
        off, ep, sha, blob, path = entry
        if blob is not None:
            return sha, blob
        try:
            raw = path.read_bytes()
        except OSError:
            return None
        head, _, payload = raw.partition(b"\n")
        want = head.decode(errors="replace")
        if len(want) != 64:
            return None
        if hashlib.sha256(payload).hexdigest() != want:
            return None
        return want, payload

    # -------------------------------------------------------------- ledger
    def put(self, p: int, offset: int, blob: bytes, epoch: int = 0) -> None:
        p, offset, epoch = int(p), int(offset), int(epoch)
        sha = hashlib.sha256(blob).hexdigest()
        with self._lock:
            fence = self._fence.get(p, 0)
            if epoch < fence:
                self.fenced_rejects_total += 1
                raise FencedEpochError(
                    f"partition {p} fenced at epoch {fence}; stale writer "
                    f"at epoch {epoch} refused")
            path = self._commit_blob(p, offset, epoch, sha, blob)
            entries = self._ledger.setdefault(p, [])
            # a client-retried put (response lost, request resent) must
            # REPLACE its twin, not duplicate it: a duplicate would alias
            # the same committed file and the retention pass below would
            # unlink the genuine previous checkpoint through the alias —
            # silently destroying the torn-blob fallback this store
            # exists to provide
            entries[:] = [e for e in entries
                          if (e[0], e[1]) != (offset, epoch)]
            entries.insert(0, (offset, epoch, sha,
                               blob if path is None else None, path))
            # retain current + previous; drop (and unlink) older — but
            # never a file a retained entry still references
            keep_paths = {e[4] for e in entries[:self.KEEP_PER_PARTITION]
                          if e[4] is not None}
            for off2, ep2, _, _, path2 in entries[self.KEEP_PER_PARTITION:]:
                if path2 is not None and path2 not in keep_paths:
                    try:
                        path2.unlink()
                    except OSError:
                        pass
            del entries[self.KEEP_PER_PARTITION:]
            self.checkpoints_total += 1

    def get(self, p: int) -> Optional[Tuple[int, bytes, int]]:
        """Latest VERIFIED (offset, blob, epoch) for a partition: a torn
        newest blob is counted and the previous checkpoint served — the
        committed-gap replay covers the difference."""
        with self._lock:
            entries = list(self._ledger.get(int(p), ()))
        for i, entry in enumerate(entries):
            got = self._read_blob(entry)
            if got is None:
                with self._lock:
                    self.torn_blobs_total += 1
                continue
            with self._lock:
                self.restores_total += 1
            return entry[0], got[1], entry[1]
        return None

    def fence(self, p: int, epoch: int) -> None:
        with self._lock:
            self._fence[int(p)] = max(self._fence.get(int(p), 0), int(epoch))

    def offsets(self) -> Dict[int, int]:
        with self._lock:
            return {p: entries[0][0]
                    for p, entries in sorted(self._ledger.items())
                    if entries}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "checkpoints_total": self.checkpoints_total,
                "restores_total": self.restores_total,
                "torn_blobs_total": self.torn_blobs_total,
                "fenced_rejects_total": self.fenced_rejects_total,
                "partitions": len(self._ledger),
            }

    # ------------------------------------------------------------- dispatch
    def dispatch(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "put":
            self.put(req["p"], req["offset"],
                     base64.b64decode(req["blob"]),
                     epoch=req.get("epoch", 0))
            return {}
        if op == "get":
            got = self.get(req["p"])
            if got is None:
                return {"found": False}
            offset, blob, epoch = got
            return {"found": True, "offset": offset, "epoch": epoch,
                    "blob": base64.b64encode(blob).decode()}
        if op == "fence":
            self.fence(req["p"], req["epoch"])
            return {}
        if op == "offsets":
            return {"offsets": {str(p): off
                                for p, off in self.offsets().items()}}
        if op == "stats":
            return self.stats()
        if op == "ping":
            return {"pong": True}
        raise ValueError(f"unknown op {op!r}")


class HandoffClient:
    """Worker-side handoff client: the ``HandoffStore`` surface
    (``put``/``get``/``offsets``) over one TCP connection, plus ``fence``
    for the coordinator.

    ``epoch`` is the mutable writer epoch stamped onto every ``put`` —
    the worker's run loop sets it to the fleet generation each time it
    adopts an assignment, so the server's fence can refuse a zombie
    (:class:`FencedEpochError` surfaces as a loud RuntimeError, never a
    silent stale write). Connection loss retries against the SAME address
    with ``DeterministicBackoff`` — a handoff-server restart mid-restore
    is a bounded wait, not a failure.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 9095,
                 timeout_s: float = 30.0, reconnect_attempts: int = 6,
                 retry_sleep=None, link=None):
        from realtime_fraud_detection_tpu.utils.backoff import (
            DeterministicBackoff,
            instance_seed,
        )

        self._addr = (host, int(port))
        self._timeout_s = timeout_s
        self._sock = socket.create_connection(self._addr, timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._reconnect_attempts = max(0, int(reconnect_attempts))
        # optional in-path chaos link (chaos/netfaults.py) — None in
        # production; the partition drill degrades/partitions this
        # connection exactly like the broker one
        self._link = link
        self.backoff = DeterministicBackoff(
            base_s=0.05, mult=2.0, max_s=1.0,
            seed=instance_seed(f"handoff:{port}"), sleep=retry_sleep)
        self.epoch = 0
        self.snapshots_taken = 0      # HandoffStore counter parity

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        resp = None
        last: Optional[Exception] = None
        for attempt in range(self._reconnect_attempts + 1):
            resp = None
            try:
                with self._lock:
                    if self._link is not None:
                        # frame size for byte-paced throttling (the
                        # double serialization is paid only while a
                        # chaos link is attached)
                        self._link.before_send(
                            req, len(json.dumps(
                                req, separators=(",", ":")).encode()))
                    _send_frame(self._sock, req)
                    # bounded whole-frame read: a SIGSTOP'd handoff
                    # server cannot wedge a restoring worker forever
                    deadline = time.monotonic() + self._timeout_s  # rtfd-lint: allow[wall-clock] socket I/O deadline is genuinely wall-bound
                    try:
                        resp = _recv_frame(self._sock, deadline=deadline)
                    finally:
                        # restore the full op timeout: the deadline path
                        # shrinks it to the residual budget
                        try:
                            self._sock.settimeout(self._timeout_s)
                        except OSError:
                            pass
                if resp is None:
                    raise ConnectionError("handoff server closed connection")
                if self._link is not None:
                    self._link.after_recv(req)
                break
            except (ConnectionError, OSError) as e:
                last = e
                if attempt >= self._reconnect_attempts:
                    raise
                self.backoff.sleep(attempt)
                try:
                    with self._lock:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = socket.create_connection(
                            self._addr, timeout=self._timeout_s)
                        self._sock.setsockopt(socket.IPPROTO_TCP,
                                              socket.TCP_NODELAY, 1)
                except OSError as e2:
                    last = e2          # still down: next attempt backs off
        if resp is None:
            raise ConnectionError(f"handoff server unreachable: {last}")
        if "error" in resp:
            msg = str(resp["error"])
            if msg.startswith("FencedEpochError"):
                # typed re-raise: the fenced-writer path (a worker that
                # lost its partitions in an unobserved rebalance) must be
                # distinguishable from a genuine server error — the
                # worker's response is abandon-and-rejoin, not crash
                raise FencedEpochError(f"handoff refused: {msg}")
            raise RuntimeError(f"handoff error: {msg}")
        return resp

    # -------------------------------------------------- HandoffStore surface
    def put(self, partition: int, offset: int, blob: bytes) -> None:
        self._call({"op": "put", "p": int(partition), "offset": int(offset),
                    "epoch": int(self.epoch),
                    "blob": base64.b64encode(blob).decode()})
        self.snapshots_taken += 1

    def get(self, partition: int) -> Optional[Tuple[int, bytes]]:
        resp = self._call({"op": "get", "p": int(partition)})
        if not resp.get("found"):
            return None
        return int(resp["offset"]), base64.b64decode(resp["blob"])

    def offsets(self) -> Dict[int, int]:
        resp = self._call({"op": "offsets"})
        return {int(p): int(off) for p, off in resp["offsets"].items()}

    # ------------------------------------------------------- coordinator ops
    def fence(self, partition: int, epoch: int) -> None:
        self._call({"op": "fence", "p": int(partition), "epoch": int(epoch)})

    def stats(self) -> Dict[str, int]:
        return self._call({"op": "stats"})

    def ping(self) -> bool:
        return bool(self._call({"op": "ping"}).get("pong"))

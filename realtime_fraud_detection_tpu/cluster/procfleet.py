"""The worker fleet across the process boundary: real OS processes,
elastically autoscaled, surviving real SIGKILLs.

PR 10's ``WorkerFleet`` proved effectively-once sharded scoring with
workers as THREADS — one OS failure still took down the whole fleet, and
the chaos ``WorkerKill`` was a cooperative in-process stop. This module
promotes every seam that was already network-shaped:

- **workers are spawned subprocesses** (``rtfd cluster-worker``), one
  consumer group over the TCP netbroker (``stream/netbroker.py``), each
  running its partition-scoped ``StreamJob`` against its own
  ``PartitionedStore`` slice (the ``ClusterWorker`` core, unchanged);
- **handoff is network-served** (``cluster/handoff.py``): checkpoint
  blobs survive any worker's death, sha256-verified, zombie-fenced;
- **membership is coordinated over the broker itself**: one control
  topic (coordinator → workers) and one events topic (workers →
  coordinator) — no extra RPC plane, and the broker's ordering is the
  protocol's ordering;
- **rebalances are two-phase**: releasers checkpoint + stop consuming
  moved partitions and ack BEFORE the coordinator fences those
  partitions at the new generation and acquirers restore + replay. The
  barrier closes the cross-process race where an acquirer restores while
  the releaser still has a batch in flight (state would double-apply);
  partitions that do not move never stop (cooperative, not
  stop-the-world);
- **death is detected, not signalled**: the coordinator reaps child
  processes; a SIGKILL'd worker is just a dead pid whose partitions are
  fenced and re-acquired from its last network checkpoint + committed-gap
  replay — the exact recovery path ``rtfd elastic-drill`` proves;
- **elasticity**: an :class:`~realtime_fraud_detection_tpu.cluster.
  autoscale.AutoscaleController` target is executed as spawn (scale-up:
  checkpoint restore + committed-gap replay) or graceful drain
  (scale-down: final checkpoint + offset commit before exit), with
  consistent-hash placement keeping each rebalance to ~K/N keys.

Scoring inside a worker is the shard drill's deterministic
``ShardScorer`` stand-in (event-time-keyed state updates), optionally
with a wall-time service-cost model standing in for device compute — the
same honesty contract as the in-process drills, now paid in real seconds.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from realtime_fraud_detection_tpu.cluster.handoff import HandoffClient
from realtime_fraud_detection_tpu.cluster.hashring import HashRing
from realtime_fraud_detection_tpu.stream import topics as T

__all__ = ["ProcessFleet", "worker_main", "CONTROL_TOPIC", "EVENTS_TOPIC",
           "DIGEST_NOW"]

CONTROL_TOPIC = "cluster-control"
EVENTS_TOPIC = "cluster-events"

# the fixed "now" every state digest is computed at (workers at shutdown,
# the drill's oracle in-process): state TTLs are configured far beyond it,
# so the digest is a pure content hash on any clock base
DIGEST_NOW = 1.0e9


def _wall() -> float:
    # rtfd-lint: allow[wall-clock] the process plane is genuinely wall-clock: real OS processes over real TCP
    return time.time()


def _mono() -> float:
    # rtfd-lint: allow[wall-clock] coordinator timeouts/pacing are wall-bound by definition
    return time.monotonic()


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


class ProcessFleet:
    """Coordinator for a fleet of ``rtfd cluster-worker`` subprocesses.

    Owns membership (the consistent-hash ring), the two-phase rebalance
    protocol over the control/events topics, death detection (process
    reaping), and autoscale-target execution. The coordinator holds NO
    scoring state — the broker log, the handoff server, and the workers'
    own stores are the only state planes, which is what makes a worker's
    SIGKILL recoverable and the coordinator restartable.
    """

    def __init__(self, broker_addr: str, handoff_addr: str,
                 n_partitions: int = 12, group_id: str = "fraud-cluster",
                 topic: str = T.TRANSACTIONS, virtual_nodes: int = 256,
                 worker_spec: Optional[Dict[str, Any]] = None,
                 python: str = sys.executable,
                 ack_timeout_s: float = 90.0,
                 spawn_env: Optional[Dict[str, str]] = None,
                 session_timeout_s: float = 30.0,
                 per_worker_spec: Optional[Dict[str, Dict[str, Any]]] = None):
        from realtime_fraud_detection_tpu.stream.netbroker import (
            NetBrokerClient,
        )

        self.broker_addr = broker_addr
        self.handoff_addr = handoff_addr
        self.n_partitions = int(n_partitions)
        self.group_id = group_id
        self.topic = topic
        self.python = python
        self.ack_timeout_s = float(ack_timeout_s)
        self.spawn_env = spawn_env
        from realtime_fraud_detection_tpu.obs.fleetmetrics import (
            FleetMetrics,
            FleetTraceStore,
        )

        bh, _, bp = broker_addr.rpartition(":")
        self.client = NetBrokerClient(host=bh or "127.0.0.1", port=int(bp))
        hh, _, hp = handoff_addr.rpartition(":")
        self.handoff = HandoffClient(host=hh or "127.0.0.1", port=int(hp))
        # fleet observability plane (obs/fleetmetrics.py): workers stream
        # counter-delta ``metrics`` events (seq-deduped) into one honest
        # aggregation, and their bye frames ship flight-recorder rings the
        # coordinator stitches into fleet-level critical-path analysis
        self.fleet_metrics = FleetMetrics()
        self.fleet_traces = FleetTraceStore()
        # worker id -> "host:port" of its graph-fetch server (published
        # as ``fetch_addr`` events; broadcast_peers hands the full map to
        # every worker so serve-time neighbor fetches cross the fleet)
        self.fetch_addrs: Dict[str, str] = {}
        self.client.create_topic(CONTROL_TOPIC, 1)
        self.client.create_topic(EVENTS_TOPIC, 1)
        self._ev_pos = 0
        self.ring = HashRing([], virtual_nodes=virtual_nodes)
        self.generation = 0
        self.worker_spec = dict(worker_spec or {})
        # per-worker overlays on top of worker_spec (keyed by worker id):
        # the partition drill stamps each target's scheduled link-fault
        # windows + phase windows into exactly that worker's spec
        self.per_worker_spec = {k: dict(v)
                                for k, v in (per_worker_spec or {}).items()}
        # wid -> {"proc", "pid", "alive", "ready", "summary"}
        self.workers: Dict[str, Dict[str, Any]] = {}
        self._next_idx = 0
        self._acks: Dict[tuple, Dict[str, Any]] = {}
        self._byes: Dict[str, Dict[str, Any]] = {}
        self._last_assignment: Dict[str, List[int]] = {}
        self._pending_deaths: List[str] = []
        self._pending_rejoins: List[str] = []
        self._pending_evictions: List[str] = []
        self._in_rebalance = False
        self.events: List[Dict[str, Any]] = []
        self.kills = 0
        self.spawns = 0
        self.evictions = 0
        self.rejoins = 0
        self.handoffs_total = 0
        self.replayed_total = 0
        self.last_replay_depth = 0
        self.rebalance_pauses_s: List[float] = []
        # liveness: a worker whose heartbeats (or any event) go silent
        # past session_timeout_s is EVICTED from the ring — its process
        # may be alive but deaf (the asymmetric-partition zombie); its
        # partitions are fenced + reassigned, and when it can reach the
        # control plane again it rejoins as a fresh member (hello).
        # This is the Kafka session-expiry analog on the broker-carried
        # membership plane; process reaping stays the fast path for
        # actual deaths.
        self.session_timeout_s = float(session_timeout_s)

    # ------------------------------------------------------------ membership
    def alive_ids(self) -> List[str]:
        return sorted(w for w, st in self.workers.items() if st["alive"])

    def ready_ids(self) -> List[str]:
        # an evicted worker is alive-but-deaf: never expected to ack,
        # never counted toward the serving fleet until it rejoins
        return sorted(w for w, st in self.workers.items()
                      if st["alive"] and st["ready"]
                      and not st.get("evicted"))

    def assignment(self) -> Dict[str, List[int]]:
        if not self.ring.members():
            return {}
        return self.ring.assignment(self.n_partitions)

    def spawn_worker(self, wid: Optional[str] = None) -> str:
        wid = wid or f"w{self._next_idx}"
        self._next_idx = max(self._next_idx,
                             int(wid[1:]) + 1 if wid[1:].isdigit() else 0)
        spec = dict(self.worker_spec)
        spec.update(self.per_worker_spec.get(wid, {}))
        spec.update(broker=self.broker_addr, handoff=self.handoff_addr,
                    worker_id=wid, group_id=self.group_id,
                    topic=self.topic, n_partitions=self.n_partitions)
        proc = subprocess.Popen(
            [self.python, "-m", "realtime_fraud_detection_tpu",
             "cluster-worker", "--spec", json.dumps(spec)],
            env=self.spawn_env)
        self.workers[wid] = {"proc": proc, "pid": proc.pid, "alive": True,
                             "ready": False, "summary": None,
                             "joined_gen": None, "evicted": False,
                             "last_hb": _mono()}
        self.spawns += 1
        return wid

    def _join_ring(self, wid: str) -> None:
        """Admit a worker to the ring, stamping the generation it joined
        at (the chaos plane's ``busiest`` kill targets the most SENIOR
        cohort — a freshly-joined worker's checkpoints are seconds old,
        and a kill that moves no state proves nothing)."""
        self.ring.add(wid)
        if self.workers[wid]["joined_gen"] is None:
            self.workers[wid]["joined_gen"] = self.generation

    def start(self, n_workers: int,
              now: Optional[float] = None) -> List[str]:
        """Spawn the initial fleet and run the first rebalance once every
        worker has said hello."""
        ids = [self.spawn_worker() for _ in range(n_workers)]
        self.wait_ready(ids)
        for wid in ids:
            self._join_ring(wid)
        self._rebalance(reason="start", now=now)
        return ids

    def wait_ready(self, ids: Sequence[str],
                   timeout_s: Optional[float] = None) -> None:
        deadline = _mono() + (timeout_s or self.ack_timeout_s)
        while not all(self.workers[w]["ready"] for w in ids):
            self.poll_events()
            self._note_deaths()
            for w in ids:
                if not self.workers[w]["alive"]:
                    raise RuntimeError(f"worker {w} died before ready")
            if _mono() > deadline:
                raise RuntimeError(
                    f"workers not ready in time: "
                    f"{[w for w in ids if not self.workers[w]['ready']]}")
            time.sleep(0.02)

    # --------------------------------------------------------------- events
    def poll_events(self) -> None:
        recs = self.client.read(EVENTS_TOPIC, 0, self._ev_pos, 256)
        for r in recs:
            self._ev_pos = r.offset + 1
            ev = r.value if isinstance(r.value, dict) else {}
            kind = ev.get("type")
            wid = str(ev.get("worker", ""))
            st = self.workers.get(wid)
            if st is not None and kind in ("hello", "hb", "ack", "bye",
                                           "metrics", "fetch_addr"):
                # ANY event is proof of life on the control plane
                st["last_hb"] = _mono()
            if kind == "hello" and st is not None:
                st["ready"] = True
                self.fleet_metrics.set_worker_info(
                    wid, pid=ev.get("pid", st.get("pid", "")),
                    version=ev.get("version", ""))
                if st.get("evicted") and st["alive"] \
                        and wid not in self._pending_rejoins:
                    # an evicted worker that can reach the control plane
                    # again rejoins as a FRESH member: queued (never
                    # executed from inside a rebalance's ack wait) and
                    # batched into one rebalance by _process_rejoins
                    self._pending_rejoins.append(wid)
            elif kind == "ack":
                self._acks[(wid, int(ev.get("generation", -1)),
                            str(ev.get("phase", "")))] = ev
            elif kind == "metrics":
                # counter-delta snapshot: seq-deduped, exactly-once fold
                self.fleet_metrics.ingest_delta(ev)
            elif kind == "fetch_addr":
                self.fetch_addrs[wid] = str(ev.get("addr", ""))
            elif kind == "bye":
                self._byes[wid] = ev
                if st is not None:
                    st["summary"] = ev
                ring = ev.get("trace_ring")
                if ring:
                    # the worker's flight recorder, stitched verbatim
                    self.fleet_traces.ingest(
                        wid, ring,
                        pid=int(ev.get("pid", 0) or
                                (st or {}).get("pid", 0) or 0))

    def _publish(self, msg: Dict[str, Any]) -> None:
        self.client.produce(CONTROL_TOPIC, msg, key="ctl")

    def _wait_acks(self, ids: Sequence[str], generation: int,
                   phase: str,
                   now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Collect (worker, generation, phase) acks; a worker that DIES
        while we wait is dropped from the expectation — its partitions
        recover through the death path (queued, run after this
        rebalance), not this rebalance's."""
        deadline = _mono() + self.ack_timeout_s
        pending = set(ids)
        while pending:
            self.poll_events()
            self._note_deaths()
            for wid in list(pending):
                if (wid, generation, phase) in self._acks:
                    pending.discard(wid)
                elif not self.workers[wid]["alive"] \
                        or self.workers[wid].get("evicted"):
                    # dead OR evicted mid-wait: the fence (not this
                    # worker's cooperation) is what protects the moved
                    # partitions — drop it from the expectation
                    pending.discard(wid)
            if not pending:
                break
            # a releaser that goes SILENT while we wait is expired here
            # (mark-only — the ring change + recovery rebalance defer to
            # _recover_evictions), so one deaf worker cannot wedge the
            # whole fleet's rebalance until the ack timeout; the caller's
            # clock rides along so the eviction event keeps its timestamp
            self._expire_sessions(now)
            if _mono() > deadline:
                raise RuntimeError(
                    f"rebalance gen {generation} phase {phase}: no ack "
                    f"from {sorted(pending)}")
            time.sleep(0.02)
        return [self._acks[(w, generation, phase)] for w in ids
                if (w, generation, phase) in self._acks]

    # ------------------------------------------------------------ rebalance
    def _rebalance(self, reason: str,
                   now: Optional[float] = None) -> Dict[str, Any]:
        """Two-phase move to the ring's current assignment. Release phase
        only targets workers that actually lose partitions; moved
        partitions are fenced at the NEW generation between the phases so
        a zombie writer (a releaser that never saw the message) cannot
        overwrite an inheritor's checkpoint."""
        t0 = _mono()
        self._in_rebalance = True
        try:
            owner_old = {p: w
                         for w, ps in self._last_assignment.items()
                         for p in ps}
            self.generation += 1
            gen = self.generation
            new_assign = self.assignment()
            owner_new = {p: w for w, ps in new_assign.items() for p in ps}
            moved = sorted(p for p, w in owner_new.items()
                           if owner_old and owner_old.get(p) != w)
            releasers = sorted({owner_old[p] for p in moved
                                if owner_old.get(p) in self.workers
                                and self.workers[owner_old[p]]["alive"]
                                and not self.workers[
                                    owner_old[p]].get("evicted")})
            wire_assign = {w: sorted(ps) for w, ps in new_assign.items()}
            if releasers:
                self._publish({"type": "assign", "generation": gen,
                               "phase": "release",
                               "assignment": wire_assign})
                self._wait_acks(releasers, gen, "release", now=now)
            for p in moved:
                self.handoff.fence(p, gen)
            if moved:
                # the WRITE-seam half of the fence step: a releaser that
                # never saw (or never acked) the release — the asymmetric
                # -partition zombie — has its stamped produces AND offset
                # commits refused by the broker from this instant
                # (StaleGenerationError), for the moved transaction
                # partitions and their index-aligned prediction
                # partitions (both topics partition by the same crc32
                # user key, so partition p of one IS partition p of the
                # other; the alerts fan-out rides the same refusal
                # because predictions produce first in _finish_batch).
                self.client.fence_producers(self.topic, moved, gen)
                self.client.fence_producers(T.PREDICTIONS, moved, gen)
            self._publish({"type": "assign", "generation": gen,
                           "phase": "acquire", "assignment": wire_assign})
            acks = self._wait_acks(self.ready_ids(), gen, "acquire",
                                   now=now)
            replayed = sum(int(a.get("replayed", 0)) for a in acks)
            acquired = sum(int(a.get("acquired", 0)) for a in acks)
            pause = round(_mono() - t0, 4)
            self.rebalance_pauses_s.append(pause)
            self.handoffs_total += acquired
            self.replayed_total += replayed
            self.last_replay_depth = replayed
            self._last_assignment = wire_assign
            event = {"event": "rebalance", "reason": reason,
                     "generation": gen, "t": now,
                     "members": self.ring.members(),
                     "moved": moved, "moved_count": len(moved),
                     "replayed": replayed, "assignment": wire_assign,
                     "pause_s": pause}
            self.events.append(event)
        finally:
            self._in_rebalance = False
        return event

    # ------------------------------------------------ session expiry/rejoin
    def _expire_sessions(self, now: Optional[float]) -> None:
        """Mark ring members whose control plane went silent past
        ``session_timeout_s`` as EVICTED (heartbeats, acks, hellos and
        byes all count as life). Mark-only — safe from inside a
        rebalance's ack wait; the ring removal + recovery rebalance
        happen in :meth:`_recover_evictions` once no rebalance runs. The
        worker process may well be alive (asymmetric partition): its
        partitions are fenced at the new generation, so whatever it
        still produces is refused at the broker, and it rejoins as a
        fresh member when its hello gets through again."""
        for wid, st in self.workers.items():
            if st["alive"] and st["ready"] and not st.get("evicted") \
                    and wid in self.ring.members() \
                    and _mono() - st["last_hb"] > self.session_timeout_s:
                st["evicted"] = True
                self.evictions += 1
                self._pending_evictions.append(wid)
                self.events.append({
                    "event": "session_expired", "worker": wid, "t": now,
                    "silent_s": round(_mono() - st["last_hb"], 3)})

    def _recover_evictions(self, now: Optional[float]) -> None:
        if self._in_rebalance or not self._pending_evictions:
            return
        evicted = [w for w in self._pending_evictions
                   if w in self.ring.members()]
        self._pending_evictions.clear()
        if not evicted:
            return
        for wid in evicted:
            self.ring.remove(wid)
        if not self.ring.members():
            raise RuntimeError("all workers evicted or dead")
        self._rebalance(reason=f"session_timeout:{'+'.join(evicted)}",
                        now=now)

    def _process_rejoins(self, now: Optional[float]) -> None:
        """Admit evicted workers whose hello got through again — batched
        into ONE rebalance, never run from inside another rebalance. A
        rejoiner is a FRESH member: its seniority resets (the busiest-
        senior kill targeting must not treat a rejoin as tenure) and it
        restores every acquired partition from the handoff store exactly
        like a scale-up joiner."""
        if self._in_rebalance or not self._pending_rejoins:
            return
        rejoin = sorted({w for w in self._pending_rejoins
                         if self.workers[w]["alive"]
                         and self.workers[w].get("evicted")})
        self._pending_rejoins.clear()
        if not rejoin:
            return
        for wid in rejoin:
            st = self.workers[wid]
            st["evicted"] = False
            st["joined_gen"] = None     # fresh member, fresh seniority
            self._join_ring(wid)
            self.rejoins += 1
        self._rebalance(reason=f"rejoin:{'+'.join(rejoin)}", now=now)

    # ------------------------------------------------------- death handling
    def _note_deaths(self) -> None:
        """Mark dead worker processes (no recovery yet — safe to call from
        inside a rebalance's ack wait)."""
        for wid, st in self.workers.items():
            if st["alive"] and st["proc"].poll() is not None \
                    and st["summary"] is None:
                st["alive"] = False
                st["returncode"] = st["proc"].returncode
                self._pending_deaths.append(wid)

    def _reap(self, now: Optional[float]) -> List[str]:
        """Detect dead worker processes (SIGKILL, crash) and recover their
        partitions onto the survivors."""
        self._note_deaths()
        dead = list(self._pending_deaths)
        if dead and not self._in_rebalance:
            self._pending_deaths.clear()
            removed = []
            for wid in dead:
                if wid in self.ring.members():
                    self.ring.remove(wid)
                    removed.append(wid)
                    self.events.append({
                        "event": "worker_death", "worker": wid, "t": now,
                        "returncode": self.workers[wid]["returncode"]})
            # a worker that died before ever JOINING the ring (spawn
            # crash) owns nothing: no generation bump, no fleet-wide
            # acquire round, no misleading "death" rebalance event
            if removed:
                if not self.ring.members():
                    raise RuntimeError("all workers dead")
                self._rebalance(reason=f"death:{'+'.join(removed)}",
                                now=now)
        return dead

    def kill_worker(self, worker_id: str,
                    now: Optional[float] = None) -> Dict[str, Any]:
        """REAL process-death semantics: SIGKILL the worker's pid — no
        flush, no final snapshot, the OS reclaims everything — then
        recover through the fence + restore + committed-gap-replay path.
        ``worker_id="busiest"`` resolves to the most-partitions worker of
        the most SENIOR join cohort (deterministic tie-break by id), the
        chaos ``WorkerKill`` escalation target."""
        if worker_id == "busiest":
            # busiest of the most SENIOR cohort (earliest join
            # generation): a long-running worker's cadence checkpoints
            # necessarily lag its committed offsets, so the kill provably
            # exercises the committed-gap replay path — a freshly-joined
            # worker's checkpoints are seconds old (its release-phase
            # inheritance wrote them at exact committed offsets) and a
            # kill there can move state without replaying anything
            assign = self.assignment()
            in_ring = [w for w in self.ready_ids()
                       if w in self.ring.members()]
            if not in_ring:
                return {"killed": False}
            min_gen = min(self.workers[w]["joined_gen"] or 0
                          for w in in_ring)
            candidates = [(len(assign.get(w, ())), w) for w in in_ring
                          if (self.workers[w]["joined_gen"] or 0)
                          == min_gen]
            worker_id = max(candidates, key=lambda c: (c[0], c[1]))[1]
        st = self.workers.get(worker_id)
        if st is None or not st["alive"]:
            return {"killed": False}
        os.kill(st["pid"], signal.SIGKILL)
        st["proc"].wait(timeout=30)
        self.kills += 1
        before = len(self.events)
        self._reap(now)
        replayed = sum(e.get("replayed", 0)
                       for e in self.events[before:]
                       if e.get("event") == "rebalance")
        return {"killed": True, "worker": worker_id,
                "returncode": st["proc"].returncode, "replayed": replayed}

    # ------------------------------------------------------------ elasticity
    def scale_to(self, target: int,
                 now: Optional[float] = None) -> Dict[str, Any]:
        """Execute an autoscale target SYNCHRONOUSLY: spawn+join (restore
        + replay) or graceful drain (final checkpoint + offset commit
        before exit). Blocks until the fleet matches; the elastic drill's
        hot loop uses :meth:`ensure_target` instead so production never
        stalls behind a worker process's startup."""
        target = max(1, int(target))
        added: List[str] = []
        removed: List[str] = []
        alive = self.ready_ids()
        while len(alive) + len(added) < target:
            added.append(self.spawn_worker())
        if added:
            self.wait_ready(added)
            for wid in added:
                self._join_ring(wid)
            self._rebalance(reason=f"scale_up:{'+'.join(added)}", now=now)
        while len(self.ready_ids()) > target:
            victim = self.ready_ids()[-1]
            self.drain_worker(victim, now=now)
            removed.append(victim)
        return {"added": added, "removed": removed}

    def ensure_target(self, target: int,
                      now: Optional[float] = None) -> None:
        """Asynchronous autoscale execution for a hot coordinator loop:
        missing workers are SPAWNED immediately but joined (ring + one
        batched rebalance) only once they say hello — the spawn latency
        (interpreter + imports) is paid while production continues, which
        is exactly what the forecast lead buys. Scale-down waits until no
        joins are pending (a join-drain race would thrash the ring)."""
        target = max(1, int(target))
        in_ring = [w for w in self.ring.members()
                   if self.workers[w]["alive"]]
        pending = [w for w, st in self.workers.items()
                   if st["alive"] and not st.get("evicted")
                   and w not in self.ring.members()]
        for _ in range(target - len(in_ring) - len(pending)):
            pending.append(self.spawn_worker())
        joinable = [w for w in pending if self.workers[w]["ready"]]
        if joinable:
            for wid in joinable:
                self._join_ring(wid)
            self._rebalance(
                reason=f"scale_up:{'+'.join(sorted(joinable))}", now=now)
            pending = [w for w in pending if w not in joinable]
        if not pending:
            while len(self.ready_ids()) > target:
                self.drain_worker(self.ready_ids()[-1], now=now)

    def drain_worker(self, wid: str,
                     now: Optional[float] = None) -> Dict[str, Any]:
        """Graceful scale-down: the victim releases every partition
        (final checkpoint + offset commit) inside the rebalance's release
        phase, then exits on the shutdown message — its successors
        restore with ZERO committed-gap replay."""
        st = self.workers.get(wid)
        if st is None or not st["alive"]:
            return {"drained": False}
        self.ring.remove(wid)
        event = self._rebalance(reason=f"drain:{wid}", now=now)
        self._publish({"type": "shutdown", "worker": wid})
        self._await_bye(wid)
        st["alive"] = False
        self.events.append({"event": "worker_drained", "worker": wid,
                            "t": now})
        return {"drained": True, "rebalance": event}

    def _await_bye(self, wid: str) -> Dict[str, Any]:
        deadline = _mono() + self.ack_timeout_s
        while wid not in self._byes:
            self.poll_events()
            if self.workers[wid]["proc"].poll() is not None \
                    and wid not in self._byes:
                self.poll_events()
                if wid in self._byes:
                    break
                raise RuntimeError(f"worker {wid} exited without bye")
            if _mono() > deadline:
                raise RuntimeError(f"worker {wid} did not say bye")
            time.sleep(0.02)
        self.workers[wid]["proc"].wait(timeout=30)
        return self._byes[wid]

    def wait_fetch_addrs(self, ids: Sequence[str],
                         timeout_s: Optional[float] = None) -> Dict[str, str]:
        """Block until every worker in ``ids`` has published its graph-
        fetch server address (``fetch_addr`` event)."""
        deadline = _mono() + (timeout_s or self.ack_timeout_s)
        while not all(w in self.fetch_addrs for w in ids):
            self.poll_events()
            self._note_deaths()
            if _mono() > deadline:
                raise RuntimeError(
                    f"no fetch_addr from "
                    f"{[w for w in ids if w not in self.fetch_addrs]}")
            time.sleep(0.02)
        return {w: self.fetch_addrs[w] for w in ids}

    def broadcast_peers(self) -> None:
        """Publish the fleet's graph-fetch peer map over the control
        topic: every worker builds its ``GraphFetchClient`` against every
        OTHER worker's served address."""
        self._publish({"type": "peers", "addrs": dict(self.fetch_addrs)})

    def announce_epoch(self, t0: float) -> None:
        """Publish the shared fault-window epoch over the control topic:
        workers anchor their scheduled link faults (and latency phase
        classification) to it, so one wall instant is the whole fleet's
        window t=0 — announced BEFORE any window opens."""
        self._publish({"type": "epoch", "t0": float(t0)})

    def tick(self, now: Optional[float] = None) -> None:
        """One coordinator heartbeat: drain events, reap deaths, expire
        silent sessions, recover evictions, admit rejoins."""
        self.poll_events()
        self._reap(now)
        self._expire_sessions(now)
        self._recover_evictions(now)
        self._process_rejoins(now)

    def all_byes(self) -> Dict[str, Dict[str, Any]]:
        """Every bye ever received — drained workers' final summaries
        included, not just the ones alive at shutdown."""
        return dict(self._byes)

    # ------------------------------------------------------------- shutdown
    def shutdown_all(self, now: Optional[float] = None,
                     ) -> Dict[str, Dict[str, Any]]:
        """Drain-free final stop: every worker final-checkpoints its owned
        partitions, reports digests/counters in its bye, and exits."""
        self._reap(now)
        byes: Dict[str, Dict[str, Any]] = {}
        ids = self.ready_ids()
        for wid in ids:
            self._publish({"type": "shutdown", "worker": wid})
        for wid in ids:
            byes[wid] = self._await_bye(wid)
            self.workers[wid]["alive"] = False
        return byes

    def terminate(self) -> None:
        """Hard cleanup (test teardown): kill anything still running."""
        for st in self.workers.values():
            if st["proc"].poll() is None:
                try:
                    st["proc"].kill()
                except OSError:
                    pass
                st["proc"].wait(timeout=10)
        self.client.close()
        self.handoff.close()

    # -------------------------------------------------------------- summary
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able fleet state shaped like ``WorkerFleet.snapshot()``
        (the ``sync_cluster`` mirror accepts it), plus the process plane's
        own ledgers."""
        assign = self.assignment()
        return {
            "generation": self.generation,
            "workers_alive": len(self.alive_ids()),
            "workers": {
                wid: {"alive": st["alive"], "pid": st["pid"],
                      "evicted": bool(st.get("evicted")),
                      "partitions_owned": len(assign.get(wid, ()))}
                for wid, st in sorted(self.workers.items())
            },
            "handoffs_total": self.handoffs_total,
            "replayed_total": self.replayed_total,
            "last_replay_depth": self.last_replay_depth,
            "kills": self.kills,
            "spawns": self.spawns,
            "evictions": self.evictions,
            "rejoins": self.rejoins,
            "rebalance_pauses_s": list(self.rebalance_pauses_s),
            "events": list(self.events),
        }


# ---------------------------------------------------------------------------
# worker process main
# ---------------------------------------------------------------------------


def worker_main(spec: Dict[str, Any]) -> int:
    """Entry point of one ``rtfd cluster-worker`` subprocess.

    Runs the ``ClusterWorker`` core (partition-scoped StreamJob +
    PartitionedStore + checkpointed handoff) over the TCP netbroker and
    the network handoff store, driven by the control topic:

    - ``assign``/release: drain in-flight batches, commit, checkpoint the
      released partitions (still at the OLD epoch — the fence lands
      after the ack), ack;
    - ``assign``/acquire: adopt the new epoch, restore + committed-gap
      replay the acquired partitions, ack with the replay depth;
    - ``shutdown`` (or SIGTERM/SIGINT): graceful drain — complete
      in-flight microbatches, commit offsets, final-checkpoint every
      owned partition, report state digests + counters in the ``bye``
      event, exit 0. SIGKILL gets none of this, by definition — that is
      the failure mode the handoff plane exists for.

    The optional wall-time service-cost model (``base_ms``/``per_txn_ms``)
    stands in for device compute exactly like the in-process drills'
    virtual cost model, paid in real seconds so autoscaling and backlog
    are physically real.
    """
    from realtime_fraud_detection_tpu.cluster.drill import ShardScorer
    from realtime_fraud_detection_tpu.cluster.fleet import ClusterWorker
    from realtime_fraud_detection_tpu.cluster.handoff import (
        FencedEpochError,
    )
    from realtime_fraud_detection_tpu.cluster.partition import (
        PartitionedStore,
    )
    from realtime_fraud_detection_tpu.stream.netbroker import (
        NetBrokerClient,
        StaleGenerationError,
    )
    from realtime_fraud_detection_tpu.utils.backoff import (
        DeterministicBackoff,
        instance_seed,
    )

    wid = str(spec["worker_id"])
    bh, _, bp = str(spec["broker"]).rpartition(":")
    hh, _, hp = str(spec["handoff"]).rpartition(":")
    # optional scheduled link faults (chaos/netfaults.py): the drill
    # stamps this worker's fault windows into the spec; the shared epoch
    # (window t=0) arrives over the control topic before any window
    # opens, so until then the clock reads -inf and the plan never fires
    epoch = {"t0": None}

    def _fault_clock() -> float:
        t0 = epoch["t0"]
        return (_wall() - t0) if t0 is not None else float("-inf")

    link = None
    nf = spec.get("netfaults") or {}
    if nf.get("windows"):
        from realtime_fraud_detection_tpu.chaos.netfaults import (
            scheduled_link_from_spec,
        )

        link = scheduled_link_from_spec(
            nf["windows"], role=f"worker-{wid}", peer="broker",
            clock=_fault_clock, seed=int(nf.get("seed", 0)))
    client = NetBrokerClient(
        host=bh or "127.0.0.1", port=int(bp),
        reconnect_attempts=int(spec.get("reconnect_attempts", 5)),
        link=link)
    handoff = HandoffClient(host=hh or "127.0.0.1", port=int(hp))
    store = PartitionedStore(
        int(spec.get("n_partitions", 12)),
        seq_len=int(spec.get("seq_len", 4)),
        feature_dim=int(spec.get("feature_dim", 4)),
        # TTLs beyond DIGEST_NOW: dedup truth must never lapse between a
        # record's event-time write and a wall-clock replay read
        cache_kwargs={"txn_ttl_s": 1e12, "features_ttl_s": 1e12})
    base_ms = float(spec.get("base_ms", 0.0))
    per_txn_ms = float(spec.get("per_txn_ms", 0.0))
    scorer = ShardScorer(store, base_ms=base_ms, per_txn_ms=per_txn_ms)
    # distributed tracing (obs/tracing.py): spec["tracing"] attaches a
    # WALL-clock tracer stamped with this worker's id as its origin —
    # wall because stitched fleet traces need ONE shared time base
    # across processes (t_start values must align in the merged export)
    tracer = None
    if spec.get("tracing"):
        from realtime_fraud_detection_tpu.obs.tracing import Tracer
        from realtime_fraud_detection_tpu.utils.config import (
            TracingSettings,
        )

        tr_spec = spec["tracing"] if isinstance(spec["tracing"], dict) \
            else {}
        tracer = Tracer(
            TracingSettings(
                enabled=True,
                ring_size=int(tr_spec.get("ring_size", 4096)),
                origin=wid),
            clock=_wall, origin=wid)
    autotune = None
    if spec.get("autotune"):
        from realtime_fraud_detection_tpu.utils.config import TuningSettings

        # a short tuner epoch lets the in-flight-depth dimension actually
        # trial inside a drill-length run — the PR 6 follow-on: the depth
        # knob finally measured against a REAL overlapped multi-process
        # pipeline instead of a single-process simulation
        autotune = TuningSettings(
            enabled=True,
            tune_interval_batches=int(spec.get("autotune_interval", 50)))
    worker = ClusterWorker(
        wid, client, scorer, store, handoff,
        str(spec.get("group_id", "fraud-cluster")),
        topic=str(spec.get("topic", T.TRANSACTIONS)),
        max_batch=int(spec.get("batch", 128)),
        max_delay_ms=float(spec.get("max_delay_ms", 20.0)),
        checkpoint_every=int(spec.get("checkpoint_every", 8)),
        autotune=autotune, tracing=tracer,
        expect_carrier=bool(spec.get("expect_carrier")))
    job = worker.job

    # serve-time cross-partition graph fetch (spec["fetch"]): serve this
    # worker's local graph view to peers, and once the coordinator
    # broadcasts the fleet's peer map, resolve remote neighbor shares
    # per microbatch — each RPC records a remote_fetch child span on the
    # batch's trace, so the stitched trace shows the peer hop
    fetch_srv = None
    fetch_client_box: Dict[str, Any] = {"client": None}
    fetch_cfg = spec.get("fetch") if isinstance(spec.get("fetch"), dict) \
        else ({} if spec.get("fetch") else None)
    if fetch_cfg is not None:
        from realtime_fraud_detection_tpu.graph.fetch import (
            GraphFetchServer,
        )

        fetch_srv = GraphFetchServer(
            lambda: store.graph, worker_id=wid,
            host="127.0.0.1", port=0).start()

    def _remote_fetch(ctx, batch) -> None:
        """Resolve remote adjacency for this batch's users (budget- and
        deadline-bounded; degrade-to-local on any failure)."""
        fc = fetch_client_box["client"]
        if fc is None:
            return
        trace = getattr(ctx, "trace", None) if ctx is not None else None
        fc.begin_batch(trace=trace)
        ids = sorted({str(r.value.get("user_id", ""))
                      for r in batch if isinstance(r.value, dict)})
        ids = [i for i in ids if i][: int(fetch_cfg.get("ids", 16))]
        if ids:
            fc.fetch(str(fetch_cfg.get("edge", "user->device")), ids,
                     fanout=int(fetch_cfg.get("k", 4)))
        fc.end_batch()

    stop = {"reason": None}

    def _on_signal(signum, frame):  # noqa: ANN001 - signal contract
        stop["reason"] = signal.Signals(signum).name

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # control cursor starts at the topic END: assignments published before
    # this worker existed are history, not instructions
    ctl_pos = client.end_offsets(CONTROL_TOPIC)[0]
    from realtime_fraud_detection_tpu import __version__

    client.produce(EVENTS_TOPIC, {"type": "hello", "worker": wid,
                                  "pid": os.getpid(),
                                  "version": __version__}, key=wid)
    if fetch_srv is not None:
        client.produce(EVENTS_TOPIC, {
            "type": "fetch_addr", "worker": wid,
            "addr": f"127.0.0.1:{fetch_srv.port}"}, key=wid)

    in_flight: deque = deque()        # (ctx, done_at_wall, depth)
    busy_until = 0.0
    # per-depth admitted-latency feedback for the tuning plane (the PR 6
    # follow-on: a REAL overlapped multi-process run feeding the tuner's
    # in-flight-depth dimension); bounded, stride-decimated
    lat_by_depth: Dict[int, List[float]] = {}
    lat_seen = 0
    # per-phase latency (the partition drill's degraded_network story):
    # completions classified against the spec's named windows relative
    # to the shared epoch — the slow-link victim reports its in-window
    # p99 next to its own healthy p99
    phase_windows = {str(k): (float(v[0]), float(v[1]))
                     for k, v in (spec.get("phase_windows") or {}).items()}
    lat_by_phase: Dict[str, List[float]] = {}

    def _phase_of(t_done: float) -> str:
        t0 = epoch["t0"]
        if t0 is not None:
            rel = t_done - t0
            for label, (s, e) in phase_windows.items():
                if s <= rel < e:
                    return label
        return "healthy"

    def _complete(ctx, done_at: float, depth: int) -> None:
        nonlocal lat_seen
        wait = done_at - _wall()
        if wait > 0:
            time.sleep(wait)
        t_done = _wall()
        if ctx is not None:
            job.complete_batch(ctx, now=t_done)
            phase = _phase_of(t_done)
            for r in ctx.fresh:
                lat_seen += 1
                if lat_seen % 4 == 0 or len(ctx.fresh) < 8:
                    bucket = lat_by_depth.setdefault(depth, [])
                    if len(bucket) < 4096 and r.timestamp:
                        bucket.append((t_done - r.timestamp) * 1e3)
                if r.timestamp:
                    pbucket = lat_by_phase.setdefault(phase, [])
                    if len(pbucket) < 65536:
                        pbucket.append((t_done - r.timestamp) * 1e3)
        worker.on_batch_complete()

    def _drain_in_flight() -> None:
        while in_flight:
            _complete(*in_flight.popleft())

    def _drain_pending() -> None:
        """Score + commit everything already consumed (assembler pending
        included) — nothing consumed may be left uncommitted when a
        checkpoint claims the committed offset covers the state."""
        _drain_in_flight()
        while True:
            batch = worker.assembler.next_batch(block=False) \
                or worker.assembler.flush()
            if not batch:
                break
            ctx = job.dispatch_batch(batch, now=_wall())
            _remote_fetch(ctx, batch)
            _complete(ctx, _wall() + scorer.cost_s(len(batch)),
                      job._inflight_depth())

    fenced = {"abandons": 0, "stale_generation": 0, "fenced_epoch": 0,
              "partitions_dropped": 0}
    rejoin = {"pending": False, "next_try": 0.0}

    def _abandon(why: str) -> None:
        """Fenced-writer recovery: a rebalance we never observed moved
        our partitions (asymmetric partition → session expiry). Drop all
        local ownership WITHOUT checkpointing (the inheritors' restored
        state is the truth; our epoch is fenced anyway), then re-enter
        the fleet as a fresh member once a hello gets through."""
        nonlocal busy_until
        fenced["abandons"] += 1
        in_flight.clear()
        fenced["partitions_dropped"] += worker.abandon()
        busy_until = 0.0
        # unstamped until the next adopted assignment: an abandoned
        # worker's only writes are control-plane events, never fenced
        client.generation = None
        rejoin["pending"] = True
        rejoin["next_try"] = 0.0

    def _handle_control(msg: Dict[str, Any]) -> None:
        kind = msg.get("type")
        if kind == "epoch":
            # the drill coordinator's shared window epoch (netfault
            # schedules + phase classification are relative to it)
            epoch["t0"] = float(msg["t0"])
        elif kind == "peers" and fetch_cfg is not None:
            from realtime_fraud_detection_tpu.graph.fetch import (
                GraphFetchClient,
            )

            addrs = {str(p): a for p, a in (msg.get("addrs") or {}).items()
                     if str(p) != wid and a}
            peers = {}
            for p, a in addrs.items():
                h, _, prt = str(a).rpartition(":")
                peers[p] = (h or "127.0.0.1", int(prt))
            old = fetch_client_box["client"]
            if old is not None:
                old.close()
            fetch_client_box["client"] = GraphFetchClient(
                peers,
                deadline_ms=float(fetch_cfg.get("deadline_ms", 25.0)),
                node_budget=int(fetch_cfg.get("node_budget", 64)))
        elif kind == "assign":
            gen = int(msg.get("generation", 0))
            assignment = msg.get("assignment") or {}
            mine = sorted(int(p) for p in assignment.get(wid, ()))
            phase = msg.get("phase")
            if phase == "release":
                to_keep = [p for p in store.owned() if p in set(mine)]
                if to_keep != store.owned():
                    # this worker actually loses partitions: everything
                    # consumed so far must be scored + committed before
                    # the release checkpoint claims its offset; workers
                    # keeping their whole set never stop (cooperative)
                    _drain_pending()
                counts = worker.set_assignment(to_keep)
                client.produce(EVENTS_TOPIC, {
                    "type": "ack", "worker": wid, "generation": gen,
                    "phase": "release",
                    "released": counts["released"]}, key=wid)
            elif phase == "acquire":
                if wid not in assignment and store.owned():
                    # a rebalance we never released for: we were EVICTED
                    # (the coordinator stopped hearing us). Adopting this
                    # epoch and release-checkpointing here would race the
                    # inheritors' restores with stale state — abandon
                    # instead; the coordinator is not waiting for an ack
                    # from an evicted member.
                    _abandon("excluded-from-assignment")
                    return
                handoff.epoch = gen
                # stamp every later produce/commit with the adopted
                # generation: the broker refuses the stamp once a newer
                # rebalance fences our partitions (StaleGenerationError
                # -> _abandon), closing the zombie-writer window
                client.generation = gen
                if fetch_client_box["client"] is not None:
                    fetch_client_box["client"].set_generation(gen)
                counts = worker.set_assignment(mine)
                client.produce(EVENTS_TOPIC, {
                    "type": "ack", "worker": wid, "generation": gen,
                    "phase": "acquire", "acquired": counts["acquired"],
                    "released": counts["released"],
                    "replayed": counts["replayed"]}, key=wid)
        elif kind == "shutdown" and str(msg.get("worker")) == wid:
            stop["reason"] = "shutdown"

    # fleet-metrics publishing (obs/fleetmetrics.py ingests these): the
    # worker ships counter DELTAS with a monotonic seq, and advances its
    # last-sent baseline only AFTER the produce returns — a netfault-
    # dropped publish is retried as a larger delta next interval, never
    # lost, so the coordinator's fleet sums stay exact
    met: Dict[str, Any] = {"seq": 0, "last": {}}

    def _metric_counters() -> Dict[str, float]:
        cur: Dict[str, float] = {str(k): float(v)
                                 for k, v in job.counters.items()}
        if tracer is not None:
            for k, v in tracer.counters.items():
                cur[f"trace_{k}"] = float(v)
        fc = fetch_client_box["client"]
        if fc is not None:
            cur["remote_fetch"] = float(fc.remote_fetch_total)
            cur["remote_fetch_errors"] = float(fc.fetch_error_total)
        return cur

    def _publish_metrics() -> None:
        cur = _metric_counters()
        # the FIRST snapshot ships every key (zeros included) so the
        # fleet exposition carries the full series set from the start
        # and the final fold equals the bye counters key for key;
        # afterwards only changed keys ride each delta
        delta = cur if met["seq"] == 0 else {
            k: v - met["last"].get(k, 0.0)
            for k, v in cur.items()
            if k not in met["last"] or v != met["last"][k]}
        if not delta and met["seq"] > 0:
            return
        client.produce(EVENTS_TOPIC, {
            "type": "metrics", "worker": wid, "seq": met["seq"] + 1,
            "counters": delta}, key=wid)
        met["seq"] += 1
        met["last"] = cur

    def _say_bye() -> None:
        from realtime_fraud_detection_tpu.obs.profiling import (
            interpolated_percentile,
        )

        _drain_pending()
        n_ckpt = worker.checkpoint()
        digests = {str(p): d
                   for p, d in store.digests(now=DIGEST_NOW).items()}
        depth_stats = {}
        for depth, vals in sorted(lat_by_depth.items()):
            if vals:
                s = sorted(vals)
                depth_stats[str(depth)] = {
                    "n": len(s),
                    "p50_ms": round(interpolated_percentile(s, 0.50), 3),
                    "p99_ms": round(interpolated_percentile(s, 0.99), 3),
                }
        phase_stats = {}
        for label, vals in sorted(lat_by_phase.items()):
            if vals:
                s = sorted(vals)
                phase_stats[label] = {
                    "n": len(s),
                    "p50_ms": round(interpolated_percentile(s, 0.50), 3),
                    "p99_ms": round(interpolated_percentile(s, 0.99), 3),
                }
        # final delta BEFORE the bye: the coordinator's streamed fleet
        # sums equal these bye counters exactly (the obs-drill pin) —
        # best-effort; a dead broker here still gets the bye attempt
        try:
            _publish_metrics()
        except (ConnectionError, OSError):
            pass
        bye = {"type": "bye", "worker": wid, "graceful": True,
               "reason": stop["reason"], "final_checkpoints": n_ckpt,
               "pid": os.getpid(),
               "digests": digests, "counters": dict(job.counters),
               "checkpoints": worker.checkpoints,
               "replayed_total": worker.replayed_total,
               "latency_by_depth": depth_stats,
               "latency_phases": phase_stats,
               "fenced": dict(fenced),
               "link": (link.state.snapshot_entry()
                        if link is not None else None)}
        if job.tuning is not None:
            snap = job.tuning.snapshot()
            bye["autotune"] = {
                "inflight_depth": snap["tuner"]["inflight_depth"],
                "counters": snap["tuner"]["counters"]}
        if tracer is not None:
            # the flight recorder rides the bye verbatim: the coordinator
            # stitches every worker's ring into the fleet trace store
            bye["trace_ring"] = [ct.to_dict() for ct in tracer.traces()]
            bye["tracer_counters"] = dict(tracer.counters)
        fc = fetch_client_box["client"]
        if fc is not None:
            bye["fetch"] = fc.stats()
        if fetch_srv is not None:
            bye["fetch_served"] = fetch_srv.requests_total
        client.produce(EVENTS_TOPIC, bye, key=wid)

    hb_s = float(spec.get("heartbeat_s", 1.0))
    next_hb = 0.0
    next_ctl = 0.0
    # outer-loop resilience: the client's OWN reconnect retries are
    # bounded; past them the worker backs off deterministically and
    # stays alive until the link heals (full partition, broker restart,
    # SIGSTOP'd broker) — process death is for SIGKILL, not for weather
    conn_backoff = DeterministicBackoff(
        base_s=0.05, mult=2.0, max_s=1.0,
        seed=instance_seed(f"worker:{wid}"))
    conn_attempt = 0

    try:
        while True:
            try:
                # ---- control plane, fault-isolated: an asymmetric
                # partition (deaf to the coordinator, data path alive)
                # must not stall scoring — that IS the zombie scenario
                # the broker's generation fence closes
                if _wall() >= next_ctl:
                    try:
                        recs = client.read(CONTROL_TOPIC, 0, ctl_pos, 64)
                        for r in recs:
                            if isinstance(r.value, dict):
                                _handle_control(r.value)
                            # advance only past HANDLED messages: a
                            # transient failure mid-handler re-polls the
                            # same record instead of silently skipping
                            # an assignment
                            ctl_pos = r.offset + 1
                        next_ctl = 0.0
                    except (ConnectionError, OSError):
                        next_ctl = _wall() + 0.5
                if stop["reason"] is not None:
                    _say_bye()
                    return 0
                # ---- heartbeat (silence IS the eviction signal; a
                # partitioned worker keeps scoring regardless)
                if _wall() >= next_hb:
                    next_hb = _wall() + hb_s
                    try:
                        client.produce(EVENTS_TOPIC,
                                       {"type": "hb", "worker": wid},
                                       key=wid)
                    except (ConnectionError, OSError):
                        pass
                    try:
                        # rides the heartbeat cadence; baseline advances
                        # only on a successful produce (inside), so a
                        # fault window folds into the next delta
                        _publish_metrics()
                    except (ConnectionError, OSError):
                        pass
                # ---- fenced: rejoin as a fresh member once the control
                # plane lets a hello through (cursor jumps to the topic
                # END first — pre-eviction assignments are history)
                if rejoin["pending"] and _wall() >= rejoin["next_try"]:
                    try:
                        ctl_pos = client.end_offsets(CONTROL_TOPIC)[0]
                        client.produce(EVENTS_TOPIC,
                                       {"type": "hello", "worker": wid,
                                        "pid": os.getpid(),
                                        "rejoin": True}, key=wid)
                        rejoin["pending"] = False
                    except (ConnectionError, OSError):
                        rejoin["next_try"] = _wall() + 0.5
                # ---- data plane
                progressed = False
                while in_flight and in_flight[0][1] <= _wall():
                    _complete(*in_flight.popleft())
                    progressed = True
                if len(in_flight) < job._inflight_depth():
                    batch = worker.assembler.next_batch(block=False)
                    if batch:
                        now = _wall()
                        ctx = job.dispatch_batch(batch, now=now)
                        _remote_fetch(ctx, batch)
                        start = max(now, busy_until)
                        done = start + scorer.cost_s(len(batch))
                        busy_until = done
                        in_flight.append((ctx, done,
                                          job._inflight_depth()))
                        progressed = True
                if not progressed:
                    if in_flight:
                        _complete(*in_flight.popleft())
                    else:
                        time.sleep(0.005)
                conn_attempt = 0
            except StaleGenerationError:
                # the broker's producer-generation fence: a rebalance we
                # never observed moved our partitions — whatever we just
                # tried to write was refused whole, nothing landed
                fenced["stale_generation"] += 1
                _abandon("stale-generation")
            except FencedEpochError:
                # same story at the checkpoint seam (handoff epoch)
                fenced["fenced_epoch"] += 1
                _abandon("fenced-epoch")
            except (ConnectionError, OSError):
                conn_backoff.sleep(min(conn_attempt, 8))
                conn_attempt += 1
    finally:
        fc = fetch_client_box["client"]
        if fc is not None:
            fc.close()
        if fetch_srv is not None:
            fetch_srv.stop()
        client.close()
        handoff.close()

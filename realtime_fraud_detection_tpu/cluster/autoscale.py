"""Elastic autoscale: the arrival forecast drives the worker count.

The tuning plane's :class:`~realtime_fraud_detection_tpu.tuning.forecast.
ArrivalForecaster` (PR 6) already estimates the offered rate AND its trend
from admission timestamps — exactly the signal an autoscaler needs to act
*before* a diurnal peak instead of after the backlog does (arXiv:2109.09541
scales its serving fleet horizontally on the same logic: identical workers,
deterministic routing, capacity follows load). This controller closes that
loop for the process fleet (cluster/procfleet.py):

- **lead horizon**: the target is computed from the rate forecast
  ``lead_s`` seconds AHEAD (Holt level + trend extrapolation), so on a
  rising ramp the fleet grows while the backlog is still zero — worker
  spawn latency (a real OS process: interpreter + import + restore) is
  paid inside the forecast lead, not inside the latency budget;
- **asymmetric hysteresis**: scale-up applies immediately (under-capacity
  burns the latency budget now), scale-down waits ``down_patience``
  consecutive decisions below the current target (a burst trough must not
  thrash the fleet through drain/restore cycles);
- **deterministic decision ledger**: decisions are evaluated only at
  fixed ``decide_interval_s`` boundaries of the OBSERVATION clock (the
  drill's event timeline, wall time in production), so the ledger is a
  pure function of the arrival schedule — the elastic drill replays it
  bit-identically and includes it in the verdict digest while wall-clock
  execution timings stay excluded.

Movement stays cheap because placement is the consistent-hash ring
(cluster/hashring.py): a one-worker membership change moves ~K/N of K
partitions, each rebalance a bounded restore + committed-gap replay.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from realtime_fraud_detection_tpu.tuning.forecast import ArrivalForecaster

__all__ = ["AutoscaleController"]


class AutoscaleController:
    """Forecast-driven target worker count with a deterministic ledger."""

    def __init__(self, per_worker_tps: float, min_workers: int = 1,
                 max_workers: int = 8, headroom: float = 1.25,
                 lead_s: float = 2.0, decide_interval_s: float = 0.5,
                 down_patience: int = 3,
                 forecaster: Optional[ArrivalForecaster] = None):
        if per_worker_tps <= 0:
            raise ValueError(
                f"per_worker_tps must be > 0, got {per_worker_tps}")
        if not 1 <= min_workers <= max_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{min_workers}..{max_workers}")
        if headroom < 1.0 or lead_s < 0 or decide_interval_s <= 0 \
                or down_patience < 1:
            raise ValueError(
                "autoscale requires headroom >= 1, lead_s >= 0, "
                "decide_interval_s > 0, down_patience >= 1")
        self.per_worker_tps = float(per_worker_tps)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.headroom = float(headroom)
        self.lead_s = float(lead_s)
        self.decide_interval_s = float(decide_interval_s)
        self.down_patience = int(down_patience)
        self.forecaster = forecaster or ArrivalForecaster(bucket_s=0.25)
        self.target = self.min_workers
        self.events: Dict[str, int] = {"up": 0, "down": 0}
        self.decisions: List[Dict[str, Any]] = []   # changes only
        self._next_decide: Optional[float] = None
        self._below_streak = 0
        self._last_rate = 0.0

    # -------------------------------------------------------------- forecast
    def lead_rate(self, now: float) -> float:
        """Offered-rate forecast ``lead_s`` ahead of ``now``: the Holt
        one-step rate extrapolated along its trend — the rising-ramp lead
        that lets the fleet grow before the peak arrives. Floored at the
        current rate so a noisy negative trend never under-provisions an
        already-observed load."""
        f = self.forecaster
        rate = f.rate(now)
        trend_per_s = f.trend / f.bucket_s
        return max(rate, rate + trend_per_s * self.lead_s)

    def _target_for(self, lead_rate: float) -> int:
        raw = math.ceil(lead_rate * self.headroom / self.per_worker_tps)
        return max(self.min_workers, min(self.max_workers, raw))

    # --------------------------------------------------------------- observe
    def observe(self, now: float, n: int = 1) -> Optional[Dict[str, Any]]:
        """Feed ``n`` arrivals at observation-clock ``now``; returns the
        ledger entry when a boundary decision CHANGED the target, else
        None.

        Decisions fire only at ``decide_interval_s`` boundaries, and a
        boundary ``B`` is decided BEFORE an arrival at ``t > B`` is fed —
        so as long as the caller's ``now`` values are non-decreasing
        (arrivals in schedule order, idle polls in between), the ledger
        is a pure function of the arrival schedule: independent of call
        chunking, wall pacing, and poll frequency. That is what lets the
        elastic drill put the ledger inside its replay digest.
        """
        if self._next_decide is None:
            self._next_decide = (math.floor(now / self.decide_interval_s)
                                 + 1) * self.decide_interval_s
        changed = None
        while now >= self._next_decide:
            changed = self._decide(self._next_decide) or changed
            self._next_decide += self.decide_interval_s
        if n > 0:
            self.forecaster.observe(now, n)
        return changed

    def _decide(self, t: float) -> Optional[Dict[str, Any]]:
        lead = self.lead_rate(t)
        self._last_rate = self.forecaster.rate(t)
        want = self._target_for(lead)
        if want > self.target:
            entry = {"t": round(t, 6), "rate": round(self._last_rate, 3),
                     "lead_rate": round(lead, 3), "target": want,
                     "from": self.target, "direction": "up"}
            self.target = want
            self._below_streak = 0
            self.events["up"] += 1
            self.decisions.append(entry)
            return entry
        if want < self.target:
            self._below_streak += 1
            if self._below_streak >= self.down_patience:
                entry = {"t": round(t, 6),
                         "rate": round(self._last_rate, 3),
                         "lead_rate": round(lead, 3), "target": want,
                         "from": self.target, "direction": "down"}
                self.target = want
                self._below_streak = 0
                self.events["down"] += 1
                self.decisions.append(entry)
                return entry
        else:
            self._below_streak = 0
        return None

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able state shaped for
        ``obs.metrics.MetricsCollector.sync_autoscale``."""
        return {
            "target_workers": self.target,
            "forecast_rate": round(self._last_rate, 3),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "per_worker_tps": self.per_worker_tps,
            "events": dict(self.events),
            "decisions": list(self.decisions),
        }

"""Shard drill: prove the partition-parallel worker plane end to end.

``rtfd shard-drill`` is the cluster plane's acceptance artifact. One
seeded, virtual-clock timeline drives a simulated user population (1M
users at the full config) through a :class:`cluster.fleet.WorkerFleet` of
≥4 partition-scoped StreamJob workers over one shared broker log, kills a
worker mid-stream (the chaos plane's ``WorkerKill`` injector on a
``ChaosPlan`` window), and checks the whole contract:

- **zero lost / double-scored** — every produced transaction appears on
  the predictions topic exactly once (the committed gap is STATE-replayed
  on handoff, never re-emitted; the uncommitted tail is scored exactly
  once by the inheritor);
- **gap-free committed offsets** — the cluster group's committed offsets
  reach every partition's end with no holes;
- **per-key ordering** — each user's predictions appear in its event
  order, across the kill;
- **state equality** — after the drain, the fleet's merged per-partition
  profile/velocity/history/dedup state is digest-identical to a
  single-worker oracle run over the same schedule, and every served
  score equals the oracle's (scores are deliberately STATE-COUPLED, so a
  lost velocity update or a double-applied profile write flips scores —
  the equality check is falsifiable, not cosmetic);
- **affinity + routing** — every batch a worker scores holds only
  records of partitions it owns, and the consistent-hash serving router
  agrees with fleet ownership for every user, before and after the kill
  with only the dead worker's partitions moving;
- **bit-identical replay** — a second fully fresh run produces the same
  sha256 digest.

Scoring is a deterministic host-side stand-in (:class:`ShardScorer`, the
qos-drill ``DrillScorer`` idiom) with a virtual service-cost model, so
the drill runs on any CPU in seconds; all state updates are keyed to
each record's EVENT time, which is what makes per-partition state
independent of batch boundaries — the property the oracle comparison
rests on. Convention matches the six sibling drills: full summary JSON,
then a compact (<2 KB) verdict as the FINAL stdout line.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.cluster.fleet import WorkerFleet
from realtime_fraud_detection_tpu.cluster.partition import PartitionedStore
from realtime_fraud_detection_tpu.stream import topics as T

__all__ = ["ShardDrillConfig", "ShardScorer", "run_shard_drill",
           "run_shard_scaling", "compact_shard_summary"]


@dataclasses.dataclass
class ShardDrillConfig:
    """Drill sizes. Defaults = the full drill (1M users); ``fast()`` =
    the tier-1 smoke — same workers, same kill, smaller population."""

    seed: int = 7
    n_workers: int = 4
    n_partitions: int = 12          # the transactions topic's contract
    num_users: int = 1_000_000
    num_merchants: int = 1_000
    n_txns: int = 24_576
    batch: int = 128
    max_delay_ms: float = 25.0      # virtual assembler deadline
    inflight_depth: int = 2
    # deterministic service-cost model (virtual ms per dispatched batch)
    base_ms: float = 4.0
    per_txn_ms: float = 0.16
    # offered load (txn/s of virtual time)
    tps: float = 6_000.0
    # handoff cadence (completed batches between partition snapshots):
    # deliberately > 1 so the kill lands with snapshots OLDER than the
    # committed offsets and the state-replay path is actually exercised
    checkpoint_every: int = 6
    # "auto" = the worker owning the most partitions (deterministic
    # tie-break by id) — the kill must actually move state, not hit a
    # worker the ring left empty-handed
    kill_worker: str = "auto"
    kill_frac: float = 0.45         # kill at this fraction of the stream
    virtual_nodes: int = 256
    # partition-state dimensions (the stand-in scorer's feature rows)
    seq_len: int = 4
    feature_dim: int = 4
    # second, fully fresh run compared digest-for-digest with the first
    replay_check: bool = True

    @classmethod
    def fast(cls) -> "ShardDrillConfig":
        """Tier-1 smoke: every phase (including the kill + handoff +
        replay) still runs; the population and stream shrink."""
        return cls(num_users=20_000, num_merchants=400, n_txns=5_120,
                   checkpoint_every=4)

    def cost_s(self, n: int) -> float:
        return (self.base_ms + n * self.per_txn_ms) / 1e3

    def capacity_tps(self) -> float:
        """One worker's sustainable rate at the configured batch size."""
        return self.batch / self.cost_s(self.batch)


# --------------------------------------------------------------- scorer


class _ShardPending:
    def __init__(self, records: List[Dict[str, Any]],
                 now: Optional[float], trace: Any = None):
        self.records = records
        self.now = now
        self.features = None
        self.trace = trace


class ShardScorer:
    """Deterministic FraudScorer stand-in over a PartitionedStore.

    The score is a pure function of the transaction id AND the user's
    partition state at scoring time (velocity count + profile txn count),
    and every state update is keyed to the record's embedded event time —
    so two runs that process each partition's records in offset order
    produce identical state and identical scores REGARDLESS of how the
    records were batched across workers. That is exactly the invariant
    the shard drill's oracle comparison verifies.

    ``replay_state`` re-applies the same per-record arithmetic without
    producing results — the checkpointed-handoff path's state-only
    replay of the committed gap.
    """

    def __init__(self, store: PartitionedStore, base_ms: float = 4.0,
                 per_txn_ms: float = 0.16):
        self.store = store
        self.base_ms = float(base_ms)
        self.per_txn_ms = float(per_txn_ms)
        self.txn_cache = store.txn_cache       # the job's dedupe seam

    def cost_s(self, n: int) -> float:
        return (self.base_ms + n * self.per_txn_ms) / 1e3

    # ------------------------------------------------- dispatch / finalize
    def dispatch(self, records, now: Optional[float] = None,
                 trace: Any = None) -> _ShardPending:
        # trace-drill mark convention: each mark labels the interval
        # STARTING at it; device_wait is marked at dispatch-return so it
        # labels the in-flight dwell until finalize's mark
        if trace is not None:
            trace.mark("assemble")
            trace.mark("pack")
            trace.mark("dispatch")
        pending = _ShardPending(list(records), now, trace)
        if trace is not None:
            trace.mark("device_wait")
        return pending

    def finalize(self, pending: _ShardPending,
                 now: Optional[float] = None,
                 lock=None) -> List[Dict[str, Any]]:
        if pending.trace is not None:
            pending.trace.mark("finalize")
        return [self._score_and_update(txn) for txn in pending.records]

    def replay_state(self, records, now: Optional[float] = None) -> None:
        """State-only replay of already-emitted records (handoff): same
        arithmetic, results discarded — nothing is re-produced."""
        for txn in records:
            self._score_and_update(txn)

    # ---------------------------------------------------------- per record
    @staticmethod
    def _event_ts(txn: Dict[str, Any]) -> float:
        # the drill embeds the arrival instant; records without it (e.g.
        # hand-built tests) fall back to 0.0 — still deterministic
        return float(txn.get("event_ts", 0.0))

    def _score_and_update(self, txn: Dict[str, Any]) -> Dict[str, Any]:
        ts = self._event_ts(txn)
        uid = str(txn.get("user_id", ""))
        tid = str(txn.get("transaction_id", ""))
        amount = float(txn.get("amount", 0.0))
        # reads BEFORE writes, in a fixed order
        vcount = float(self.store.velocity.get(uid, "5min", ts)
                       .get("count", 0))
        prof = self.store.profiles.get_user(uid) or {}
        pcount = float(prof.get("txn_count", 0))
        h = (zlib.crc32(tid.encode()) % 1000) / 1000.0
        score = round(0.5 * h + 0.3 * min(vcount, 8.0) / 8.0
                      + 0.2 * min(pcount, 16.0) / 16.0, 6)
        decision = ("APPROVE" if score < 0.5 else
                    "APPROVE_WITH_MONITORING" if score < 0.7 else
                    "REVIEW" if score < 0.85 else "DECLINE")
        risk = ("LOW" if score < 0.5 else "MEDIUM" if score < 0.7
                else "HIGH")
        # write-back, event-time keyed (batch-boundary independent)
        self.store.velocity.update(uid, amount, ts)
        self.store.profiles.put_user(uid, {
            "user_id": uid,
            "txn_count": int(pcount) + 1,
            "total_amount": round(float(prof.get("total_amount", 0.0))
                                  + amount, 2),
        })
        feat = np.asarray([[round(amount % 97.0 / 97.0, 6), h,
                            min(vcount, 8.0) / 8.0,
                            min(pcount, 16.0) / 16.0]], np.float32)
        self.store.history.append_batch([uid], feat)
        merged = dict(txn)
        merged.update(fraud_score=score, decision=decision,
                      risk_level=risk, confidence=0.9)
        self.store.txn_cache.cache_transaction(merged, now=ts)
        return {
            "transaction_id": tid,
            "fraud_probability": score,
            "fraud_score": score,
            "risk_level": risk,
            "decision": decision,
            "model_predictions": {},
            "confidence": 0.9,
            "processing_time_ms": 0.0,
            "explanation": {"shard": True},
        }


# ----------------------------------------------------------------- drive


def _build_schedule(cfg: ShardDrillConfig,
                    ) -> List[Tuple[float, Dict[str, Any]]]:
    """The seeded arrival timeline: uniform spacing at ``cfg.tps``, each
    record stamped with its event instant (the clock every state update
    keys to)."""
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed, tps=cfg.tps)
    sched: List[Tuple[float, Dict[str, Any]]] = []
    t = 0.0
    remaining = cfg.n_txns
    while remaining > 0:
        for txn in gen.generate_batch(min(2048, remaining)):
            txn["event_ts"] = round(t, 9)
            sched.append((t, txn))
            t += 1.0 / cfg.tps
        remaining = cfg.n_txns - len(sched)
    return sched


def _run_fleet(cfg: ShardDrillConfig,
               sched: List[Tuple[float, Dict[str, Any]]],
               n_workers: int, kill: bool) -> Dict[str, Any]:
    """Drive one fleet over the schedule on a fresh broker; returns the
    raw outcome (ledger + state digests + fleet snapshot + digest)."""
    from realtime_fraud_detection_tpu.chaos.faults import (
        ChaosPlan,
        FaultWindow,
        WorkerKill,
    )
    from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker

    broker = InMemoryBroker()
    clock = [0.0]
    vclock = lambda: clock[0]                                  # noqa: E731

    def factory(worker_id: str, store: PartitionedStore) -> ShardScorer:
        return ShardScorer(store, base_ms=cfg.base_ms,
                           per_txn_ms=cfg.per_txn_ms)

    fleet = WorkerFleet(
        broker, n_workers, cfg.n_partitions, factory,
        topic=T.TRANSACTIONS, clock=vclock, max_batch=cfg.batch,
        max_delay_ms=cfg.max_delay_ms,
        checkpoint_every=cfg.checkpoint_every,
        virtual_nodes=cfg.virtual_nodes,
        store_kwargs={"seq_len": cfg.seq_len,
                      "feature_dim": cfg.feature_dim})

    plan = None
    t_kill = None
    kill_target = None
    if kill and n_workers > 1:
        kill_target = cfg.kill_worker
        if kill_target == "auto":
            kill_target = max(fleet.assignment().items(),
                              key=lambda kv: (len(kv[1]), kv[0]))[0]
        t_kill = cfg.kill_frac * (len(sched) / cfg.tps)
        plan = ChaosPlan([FaultWindow("worker_kill", "cluster",
                                      t_kill, t_kill + 0.05)])
        plan.bind("worker_kill", WorkerKill(fleet, kill_target))

    pre_kill_assignment = fleet.assignment()
    next_i = 0
    n = len(sched)
    affinity_violations = 0
    handoff_pause_s = None
    moved_parts: set = set()

    while True:
        now = clock[0]
        if plan is not None:
            plan.poll(now)
            if not moved_parts:
                for ev in fleet.events:
                    if ev["event"] == "worker_kill":
                        moved_parts = set(ev.get("partitions") or ())
                        break
        while next_i < n and sched[next_i][0] <= now:
            ts, txn = sched[next_i]
            next_i += 1
            broker.produce(T.TRANSACTIONS, txn,
                           key=str(txn["user_id"]), timestamp=ts)
        progressed = False
        for w in fleet.alive_workers():
            while w.in_flight and w.in_flight[0][1] <= now:
                ctx, tdone = w.in_flight.popleft()
                if ctx is not None:
                    w.job.complete_batch(ctx, now=tdone)
                    if (handoff_pause_s is None and t_kill is not None
                            and tdone >= t_kill and moved_parts
                            and any(r.partition in moved_parts
                                    for r in ctx.fresh)):
                        # takeover gap: kill → first inherited-partition
                        # record completed by its new owner
                        handoff_pause_s = tdone - t_kill
                w.on_batch_complete()
                progressed = True
            if len(w.in_flight) < cfg.inflight_depth:
                batch = w.assembler.next_batch(block=False)
                if not batch and next_i >= n:
                    batch = w.assembler.flush()
                if batch:
                    owned = set(w.store.owned())
                    if any(r.partition not in owned for r in batch):
                        affinity_violations += 1
                    ctx = w.job.dispatch_batch(batch, now=now)
                    start = max(now, w.busy_until)
                    done = start + cfg.cost_s(len(batch))
                    w.busy_until = done
                    w.in_flight.append((ctx, done))
                    progressed = True
        if progressed:
            continue
        alive = fleet.alive_workers()
        if (next_i >= n and fleet.lag() == 0
                and not any(w.in_flight for w in alive)
                and not any(w.assembler._pending for w in alive)):
            break
        targets: List[float] = []
        if next_i < n:
            targets.append(sched[next_i][0])
        for w in alive:
            if w.in_flight:
                targets.append(w.in_flight[0][1])
            if w.assembler._first_ts is not None:
                targets.append(w.assembler._first_ts
                               + cfg.max_delay_ms / 1e3)
        if plan is not None:
            for fw in plan.windows:
                for edge in (fw.t_start, fw.t_end):
                    if edge > now:
                        targets.append(edge)
        clock[0] = max(now + 1e-9,
                       min(targets) if targets else now + 0.01)

    makespan = clock[0]

    # ---- ledger: read the predictions topic back (one pass: the scored
    # ledger AND the per-key ordering check — within each predictions
    # partition every user's transactions must appear in event order; txn
    # ids are globally sequence-numbered by the generator) ----------------
    preds: List[Tuple[str, float, str, str]] = []
    order_ok = True
    last_seq: Dict[Tuple[int, str], int] = {}
    pred_part: Dict[str, int] = {}
    for p in range(broker.partitions(T.PREDICTIONS)):
        off = 0
        while True:
            recs = broker.read(T.PREDICTIONS, p, off, 4096)
            if not recs:
                break
            off = recs[-1].offset + 1
            for r in recs:
                v = r.value if isinstance(r.value, dict) else {}
                ex = v.get("explanation") or {}
                kind = ("shed" if ex.get("shed")
                        else "replayed" if ex.get("replayed_from_cache")
                        else "error" if ex.get("error")
                        else "scored")
                tid = str(v.get("transaction_id", ""))
                preds.append((tid,
                              round(float(v.get("fraud_score", -1.0)), 6),
                              str(v.get("decision", "")), kind))
                uid = str(r.key or "")
                try:
                    seq = int(tid.rsplit("_", 1)[-1])
                except ValueError:
                    continue
                keyp = (p, uid)
                if last_seq.get(keyp, -1) >= seq:
                    order_ok = False
                last_seq[keyp] = seq
                pred_part[tid] = p

    tx_ends = broker.end_offsets(T.TRANSACTIONS)
    committed = [broker.committed(fleet.group_id, T.TRANSACTIONS, p)
                 for p in range(len(tx_ends))]

    digests: Dict[int, str] = {}
    for w in fleet.alive_workers():
        for p, d in w.store.digests(now=makespan).items():
            digests[p] = d

    digest = hashlib.sha256(json.dumps({
        "preds": sorted(preds),
        "committed": committed,
        "assignment": fleet.assignment(),
        "state": sorted(digests.items()),
        "events": [{k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in ev.items()} for ev in fleet.events],
    }, sort_keys=True).encode()).hexdigest()

    return {
        "n_workers": n_workers,
        "kill_target": kill_target,
        "makespan_s": round(makespan, 4),
        "preds": preds,
        "committed": committed,
        "tx_ends": tx_ends,
        "order_ok": order_ok,
        "digests": digests,
        "affinity_violations": affinity_violations,
        "handoff_pause_s": (round(handoff_pause_s, 4)
                            if handoff_pause_s is not None else None),
        "moved_partitions": sorted(moved_parts),
        "pre_kill_assignment": pre_kill_assignment,
        "fleet": fleet.snapshot(),
        "counters": fleet.counters(),
        "router": fleet.router,
        "digest": digest,
    }


# ------------------------------------------------------------------ drill


def run_shard_drill(config: Optional[ShardDrillConfig] = None,
                    fast: bool = False) -> Dict[str, Any]:
    """Run the shard drill: fleet-with-kill vs single-worker oracle, plus
    the bit-identical replay; assemble the verdict."""
    cfg = config or (ShardDrillConfig.fast() if fast
                     else ShardDrillConfig())
    sched = _build_schedule(cfg)
    fleet_out = _run_fleet(cfg, sched, cfg.n_workers, kill=True)
    oracle_out = _run_fleet(cfg, sched, 1, kill=False)

    produced = [str(txn["transaction_id"]) for _, txn in sched]
    by_id: Dict[str, Dict[str, int]] = {}
    score_by_id: Dict[str, float] = {}
    for tid, score, _dec, kind in fleet_out["preds"]:
        by_id.setdefault(tid, {})
        by_id[tid][kind] = by_id[tid].get(kind, 0) + 1
        if kind == "scored":
            score_by_id[tid] = score
    oracle_scores = {tid: score
                     for tid, score, _dec, kind in oracle_out["preds"]
                     if kind == "scored"}

    covered = set(by_id)
    lost = len(set(produced) - covered)
    double = sum(1 for kinds in by_id.values()
                 if kinds.get("scored", 0) + kinds.get("error", 0) > 1)
    score_mismatches = sum(
        1 for tid, s in score_by_id.items()
        if oracle_scores.get(tid) != s)

    # router agreement + bounded movement
    router = fleet_out["router"]
    fleet_assign = fleet_out["fleet"]["router"]["assignment"]
    owner_of = {p: m for m, parts in fleet_assign.items() for p in parts}
    sample_users = {str(txn["user_id"]) for _, txn in sched[::97]}
    router_disagreements = sum(
        1 for uid in sample_users
        if router.route(uid) != owner_of.get(router.partition_of(uid)))
    pre = fleet_out["pre_kill_assignment"]
    post = {m: set(parts) for m, parts in fleet_assign.items()}
    survivors_stable = all(
        set(parts) <= post.get(m, set())
        for m, parts in pre.items() if m in post)
    moved = set(fleet_out["moved_partitions"])
    dead_parts = set(pre.get(fleet_out["kill_target"] or "", ()))

    replay_identical = None
    if cfg.replay_check:
        second = _run_fleet(cfg, _build_schedule(cfg), cfg.n_workers,
                            kill=True)
        replay_identical = second["digest"] == fleet_out["digest"]

    fl = fleet_out["fleet"]
    checks = {
        "workers_enough": cfg.n_workers >= 4,
        "worker_killed": fl["kills"] == 1,
        "zero_lost": lost == 0,
        "zero_double_scored": double == 0,
        "every_txn_scored_once": all(
            kinds.get("scored", 0) == 1 for kinds in by_id.values())
        and covered == set(produced),
        "offsets_gap_free": (fleet_out["committed"]
                             == fleet_out["tx_ends"]),
        "per_key_order_preserved": fleet_out["order_ok"],
        "state_equals_oracle": (fleet_out["digests"]
                                == oracle_out["digests"]),
        "scores_equal_oracle": score_mismatches == 0,
        "handoff_replay_exercised": fl["replayed_total"] >= 1,
        "handoff_observed": fleet_out["handoff_pause_s"] is not None,
        "affinity_clean": fleet_out["affinity_violations"] == 0,
        "router_agrees_with_fleet": router_disagreements == 0,
        "only_dead_partitions_moved": (moved == dead_parts
                                       and survivors_stable),
    }
    if replay_identical is not None:
        checks["replay_bit_identical"] = bool(replay_identical)

    summary: Dict[str, Any] = {
        "metric": "shard_drill",
        "passed": all(bool(v) for v in checks.values()),
        "checks": checks,
        "n_workers": cfg.n_workers,
        "n_partitions": cfg.n_partitions,
        "num_users": cfg.num_users,
        "produced": len(produced),
        "scored": fleet_out["counters"]["scored"],
        "duplicates_skipped": fleet_out["counters"]["duplicates_skipped"],
        "lost": lost,
        "double_scored": double,
        "score_mismatches": score_mismatches,
        "router_disagreements": router_disagreements,
        "moved_partitions": sorted(moved),
        "dead_worker_partitions": sorted(dead_parts),
        "handoff_pause_s": fleet_out["handoff_pause_s"],
        "replayed_total": fl["replayed_total"],
        "checkpoints_total": fl["checkpoints_total"],
        "fleet_makespan_s": fleet_out["makespan_s"],
        "oracle_makespan_s": oracle_out["makespan_s"],
        "virtual_speedup_vs_oracle": round(
            oracle_out["makespan_s"]
            / max(fleet_out["makespan_s"], 1e-9), 3),
        "fleet": {k: v for k, v in fl.items() if k != "events"},
        "events": fl["events"],
        "replay_identical": replay_identical,
        "digest": fleet_out["digest"],
    }
    return summary


def compact_shard_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line digest (bench.py convention: full
    result on the preceding line, compact parseable verdict last)."""
    compact = {
        "metric": "shard_drill",
        "passed": summary.get("passed"),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "n_workers": summary.get("n_workers"),
        "num_users": summary.get("num_users"),
        "produced": summary.get("produced"),
        "scored": summary.get("scored"),
        "lost": summary.get("lost"),
        "double_scored": summary.get("double_scored"),
        "score_mismatches": summary.get("score_mismatches"),
        "moved_partitions": summary.get("moved_partitions"),
        "handoff_pause_s": summary.get("handoff_pause_s"),
        "replayed_total": summary.get("replayed_total"),
        "virtual_speedup_vs_oracle": summary.get(
            "virtual_speedup_vs_oracle"),
        "digest": (summary.get("digest") or "")[:16],
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:
        for victim in ("checks", "moved_partitions", "digest",
                       "summary_of"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "shard_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact


# ------------------------------------------------------------- bench hook


def run_shard_scaling(seed: int = 7,
                      workers: Tuple[int, ...] = (1, 2, 4),
                      ) -> Dict[str, Any]:
    """The ``bench.py shard_scaling`` stage: aggregate virtual txn/s at
    1/2/4 workers over one saturating schedule (offered load ≥ the
    4-worker capacity, so every fleet is compute-bound and the makespan
    ratio IS the scaling), plus the kill run's handoff pause."""
    base = ShardDrillConfig.fast()
    cfg = dataclasses.replace(
        base, seed=seed, replay_check=False,
        tps=max(workers) * 1.5 * base.capacity_tps())
    sched = _build_schedule(cfg)
    per_w: Dict[int, Dict[str, Any]] = {}
    for w in sorted(workers):
        run_cfg = dataclasses.replace(cfg, n_workers=w)
        out = _run_fleet(run_cfg, sched, w, kill=False)
        per_w[w] = {
            "makespan_s": out["makespan_s"],
            "txn_per_s": round(len(sched) / max(out["makespan_s"], 1e-9),
                               1),
        }
    kill_out = _run_fleet(cfg, sched, max(workers), kill=True)
    w1 = per_w[min(workers)]["txn_per_s"]
    wmax = max(workers)
    return {
        "n_txns": len(sched),
        "n_partitions": cfg.n_partitions,
        "workers": {str(w): v for w, v in per_w.items()},
        "single_worker_txn_per_s": w1,
        "aggregate_txn_per_s": per_w[wmax]["txn_per_s"],
        "scaling_vs_single": round(per_w[wmax]["txn_per_s"]
                                   / max(w1, 1e-9), 3),
        "scaling_efficiency": round(
            per_w[wmax]["txn_per_s"] / max(w1, 1e-9) / wmax, 3),
        "handoff": {
            "pause_s": kill_out["handoff_pause_s"],
            "replayed": kill_out["fleet"]["replayed_total"],
            "moved_partitions": len(kill_out["moved_partitions"]),
        },
    }

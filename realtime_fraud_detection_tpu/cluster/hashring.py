"""Consistent-hash placement: key → partition → worker.

Two layers, deliberately separate:

1. **key → partition** is the TRANSPORT's hash — ``partition_for_key`` is
   bit-identical to ``stream/transport.InMemoryBroker.select_partition``
   (crc32, matching stream/kafka.py's partitioner), so broker-partition
   affinity IS state affinity: the worker consuming a user's partition
   owns that user's profile/velocity/history/dedup state, and the serving
   router lands ``/predict`` for that user on the same worker. This layer
   never changes with membership — a user's partition is a fixed fact.

2. **partition → worker** is a consistent-hash ring (`HashRing`): each
   worker projects ``virtual_nodes`` points onto a 64-bit ring and a
   partition belongs to the first worker point at or after its own hash.
   Membership change moves ONLY the arcs the joining/leaving worker
   touches — expected K/N of K partitions for a fleet of N — instead of
   the ~K(N-1)/N a modulo assignment reshuffles. The fleet's coordinator
   and the serving router both compute placement from (members,
   n_partitions) alone, so they agree without talking to each other
   (arXiv:2109.09541 §4: identical workers, deterministic routing).

``ShardRouter`` is the thin serving-facing wrapper: route a user key to
the owning worker, account key movement across membership changes (the
``cluster_router_moved_keys_total`` series).
"""

from __future__ import annotations

import hashlib
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["partition_for_key", "HashRing", "ShardRouter"]


def partition_for_key(key: str, n_partitions: int) -> int:
    """The transport's key→partition hash (transport.select_partition /
    stream/kafka.py partitioner): crc32, NOT ``hash()`` — Python salts
    ``str.__hash__`` per process, and state affinity must survive worker
    restarts."""
    if n_partitions <= 0:
        raise ValueError(f"n_partitions must be >= 1, got {n_partitions}")
    return zlib.crc32(key.encode()) % n_partitions


def _ring_point(label: str) -> int:
    """Stable 64-bit ring coordinate. blake2b, not crc32: the ring needs
    well-spread points for the K/N movement bound to hold at small
    virtual-node counts; crc32's 32-bit space with structured labels
    ("w0#17") clusters measurably."""
    return int.from_bytes(
        hashlib.blake2b(label.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring over worker ids with virtual nodes.

    Placement is a pure function of (members, virtual_nodes): every
    caller that knows the membership computes the same assignment, so the
    fleet coordinator (partition ownership) and the serving router (key
    routing) never exchange assignment tables.
    """

    def __init__(self, members: Sequence[str] = (),
                 virtual_nodes: int = 256):
        if virtual_nodes < 1:
            raise ValueError(
                f"virtual_nodes must be >= 1, got {virtual_nodes}")
        self.virtual_nodes = int(virtual_nodes)
        self._members: List[str] = []
        self._points: List[Tuple[int, str]] = []   # sorted (point, member)
        for m in members:
            self.add(m)

    # ------------------------------------------------------------ membership
    def members(self) -> List[str]:
        return sorted(self._members)

    def add(self, member: str) -> None:
        if not member:
            raise ValueError("member id must be non-empty")
        if member in self._members:
            return
        self._members.append(member)
        for v in range(self.virtual_nodes):
            self._points.append((_ring_point(f"{member}#{v}"), member))
        # ties broken by member id so placement is total-ordered even on
        # the (astronomically unlikely) 64-bit point collision
        self._points.sort()

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.remove(member)
        self._points = [(p, m) for p, m in self._points if m != member]

    # ------------------------------------------------------------- placement
    def owner_of_partition(self, partition: int) -> str:
        """The worker owning a partition: first ring point at or after the
        partition's own 64-bit coordinate (wrapping)."""
        if not self._points:
            raise ValueError("hash ring has no members")
        target = _ring_point(f"partition:{partition}")
        lo, hi = 0, len(self._points)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._points[mid][0] < target:
                lo = mid + 1
            else:
                hi = mid
        return self._points[lo % len(self._points)][1]

    def assignment(self, n_partitions: int) -> Dict[str, List[int]]:
        """member → sorted owned partitions, exhaustive over
        ``range(n_partitions)``. Every member appears (possibly empty)."""
        out: Dict[str, List[int]] = {m: [] for m in self.members()}
        for p in range(n_partitions):
            out[self.owner_of_partition(p)].append(p)
        return out

    def route_key(self, key: str, n_partitions: int) -> str:
        """user key → owning worker, THROUGH the transport's partition
        hash — so routing agrees with broker-partition consumption by
        construction."""
        return self.owner_of_partition(partition_for_key(key, n_partitions))


class ShardRouter:
    """Thin consistent-hash router in front of serving.

    Maps ``/predict`` user keys to the owning worker and accounts key
    movement across membership changes. ``set_membership`` measures the
    moved set in PARTITIONS (the unit of state handoff — a moved
    partition moves every key in it) and exposes the cumulative count for
    the ``cluster_router_moved_keys_total`` mirror.
    """

    def __init__(self, n_partitions: int, members: Sequence[str] = (),
                 virtual_nodes: int = 256,
                 addresses: Optional[Dict[str, str]] = None):
        if n_partitions < 1:
            raise ValueError(
                f"n_partitions must be >= 1, got {n_partitions}")
        self.n_partitions = int(n_partitions)
        self.ring = HashRing(members, virtual_nodes=virtual_nodes)
        self.addresses = dict(addresses or {})
        self.rebalances = 0
        self.moved_partitions_total = 0
        self.moved_keys_total = 0          # partition moves × keys ≈ tracked
        self._routed = 0

    # --------------------------------------------------------------- routing
    def route(self, user_key: str) -> str:
        """The worker owning this user's partition."""
        self._routed += 1
        return self.ring.route_key(str(user_key), self.n_partitions)

    def partition_of(self, user_key: str) -> int:
        return partition_for_key(str(user_key), self.n_partitions)

    def address_of(self, worker_id: str) -> Optional[str]:
        return self.addresses.get(worker_id)

    def assignment(self) -> Dict[str, List[int]]:
        return self.ring.assignment(self.n_partitions)

    # ------------------------------------------------------------ membership
    def set_membership(self, members: Sequence[str],
                       keys_per_partition: float = 1.0) -> int:
        """Adopt a new member set; returns the number of partitions whose
        owner changed. ``keys_per_partition`` scales the moved-keys
        counter (a fleet that knows its live key population per partition
        passes the real density; the default counts partitions)."""
        before = (self.ring.assignment(self.n_partitions)
                  if self.ring.members() else {})
        owner_before = {p: m for m, parts in before.items() for p in parts}
        for m in list(self.ring.members()):
            if m not in members:
                self.ring.remove(m)
        for m in members:
            self.ring.add(m)
        moved = 0
        if owner_before:
            after = self.ring.assignment(self.n_partitions)
            owner_after = {p: m for m, parts in after.items() for p in parts}
            moved = sum(1 for p, m in owner_after.items()
                        if owner_before.get(p) != m)
        self.rebalances += 1
        self.moved_partitions_total += moved
        self.moved_keys_total += int(round(moved * keys_per_partition))
        return moved

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able state for ``GET /cluster`` and ``sync_cluster``."""
        return {
            "members": self.ring.members(),
            "n_partitions": self.n_partitions,
            "virtual_nodes": self.ring.virtual_nodes,
            "assignment": {m: parts
                           for m, parts in self.assignment().items()},
            "rebalances": self.rebalances,
            "moved_partitions_total": self.moved_partitions_total,
            "moved_keys_total": self.moved_keys_total,
            "routed": self._routed,
        }

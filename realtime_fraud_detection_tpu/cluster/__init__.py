"""Partition-parallel worker plane: shard the world.

All host state used to live in one ``StreamJob`` process — a cap on the
user population and a single point of failure. This package makes the
broker PARTITION the unit of both consumption and state ownership:

- ``cluster.hashring`` — key→partition (the transport's crc32, so broker
  affinity IS state affinity) and partition→worker (consistent-hash ring,
  bounded movement on membership change) + the serving ``ShardRouter``;
- ``cluster.partition`` — the state stores behind a key-partitioned
  interface (``PartitionedStore``) with snapshot/restore/digest per
  partition;
- ``cluster.fleet`` — N partition-scoped StreamJob workers in one
  consumer group with checkpointed state handoff on worker loss
  (``WorkerFleet`` / ``HandoffStore``);
- ``cluster.drill`` — ``rtfd shard-drill``, the deterministic acceptance
  artifact (1M-user population, mid-stream worker kill, zero lost /
  double-scored, oracle state equality, bit-identical replay);
- ``cluster.handoff`` — the network-served handoff store (TCP server +
  client, crash-safe atomic blobs, sha256-verified restore, offset-epoch
  zombie fencing) that survives any worker process's death;
- ``cluster.autoscale`` — the elastic controller feeding the tuning
  plane's arrival forecaster into target worker count (lead horizon,
  asymmetric hysteresis, deterministic decision ledger);
- ``cluster.procfleet`` — the fleet across the PROCESS boundary: workers
  as spawned OS processes in one consumer group over the TCP netbroker,
  two-phase rebalances, graceful drain, real-SIGKILL recovery;
- ``cluster.elastic_drill`` — ``rtfd elastic-drill``, the acceptance
  artifact for all of the above (10M-user id space, >= 8 OS processes,
  SIGKILL mid-peak, autoscale ahead of the diurnal ramp, deterministic
  verdict).
"""

from realtime_fraud_detection_tpu.cluster.hashring import (
    HashRing,
    ShardRouter,
    partition_for_key,
)
from realtime_fraud_detection_tpu.cluster.partition import (
    PartitionNotOwned,
    PartitionState,
    PartitionedStore,
)
from realtime_fraud_detection_tpu.cluster.fleet import (
    ClusterWorker,
    HandoffStore,
    WorkerFleet,
)
from realtime_fraud_detection_tpu.cluster.handoff import (
    FencedEpochError,
    HandoffClient,
    HandoffServer,
)
from realtime_fraud_detection_tpu.cluster.autoscale import (
    AutoscaleController,
)

__all__ = [
    "HashRing",
    "ShardRouter",
    "partition_for_key",
    "PartitionNotOwned",
    "PartitionState",
    "PartitionedStore",
    "ClusterWorker",
    "HandoffStore",
    "WorkerFleet",
    "HandoffServer",
    "HandoffClient",
    "FencedEpochError",
    "AutoscaleController",
]

"""Elastic drill: prove the distributed, autoscaled process fleet.

``rtfd elastic-drill`` is the acceptance artifact for the process-mode
cluster (cluster/procfleet.py). One seeded diurnal-ramp timeline
(``sim/arrivals.DiurnalBurstProcess``) over a **10M-user id space** drives
a fleet of REAL OS worker processes (spawned ``rtfd cluster-worker``
subprocesses in one consumer group over the TCP netbroker), with:

- an **elastic autoscale controller** (cluster/autoscale.py) feeding the
  tuning plane's arrival forecaster into target worker count — the fleet
  grows AHEAD of the forecast peak (scale-up = spawn + network-checkpoint
  restore + committed-gap replay) and drains after it (scale-down =
  graceful final checkpoint + offset commit before exit);
- a **real SIGKILL** at the busiest worker mid-peak (the chaos plane's
  ``WorkerKill`` bound to the ``ProcessFleet`` — the kernel delivers the
  fault, returncode ``-9`` is checked), recovered through the network
  handoff store's fence + sha256-verified restore + replay path.

Checked contract (all enforced, fast AND full):

- **effectively-once scoring**: zero lost transactions, zero records
  whose scored emissions disagree, committed offsets gap-free at every
  partition's end, per-key order preserved on first emission, and the
  final per-partition state digests EQUAL a single-process oracle that
  applies each partition's records in offset order (scores are
  state-coupled — a lost velocity update or a double-applied profile
  write flips later scores, so the equality is falsifiable). Emission is
  at-least-once across the SIGKILL window by design — a prediction
  produced in the instant between fan-out and commit is re-emitted with
  an IDENTICAL score by the inheritor, and downstream consumers dedupe
  by transaction id (the documented contract since PR 1); the drill
  counts those duplicates and proves none of them disagree.
- **autoscaler ahead of the ramp**: at every decision boundary the
  provisioned capacity (ledger target × per-worker capacity) covers the
  TRUE diurnal envelope rate at that instant (a reactive scaler trails a
  steep ramp and fails this), the last scale-up decision lands before
  the peak and reaches the max target, ≥ 8 distinct workers join the
  ring and serve, and after the ramp the controller drains the fleet
  back to the floor (peak CONCURRENCY is wall-dependent and reported,
  never gated — a loaded machine can stretch a spawn past the scale
  window without changing what the fleet scored or where);
- **bounded movement**: every rebalance moves only the joining/leaving/
  dead workers' partitions (consistent hashing — survivors' partitions
  never move), ~K/N per single-member change;
- **deterministic verdict**: a second fully fresh run (new broker, new
  handoff dir, new processes) produces the same sha256 digest over the
  content invariants + the autoscale decision ledger. Host-timing fields
  (wall latencies, rebalance pauses, spawn timings) are reported but
  excluded from the digest — the machine's scheduler is not part of the
  contract.

The 10M-user population is an id SPACE, not 10M materialized profiles:
a seeded synthetic stream draws a hot cohort (repeat customers — the
state the oracle comparison exercises) plus a uniform long tail across
the full space, schema-complete for the stream sanitizer, O(1) memory.
(``TransactionGenerator`` at 10M users materializes ~3.6 GB of profiles
the drill's state-coupled stand-in scorer never reads.)
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.cluster.autoscale import (
    AutoscaleController,
)
from realtime_fraud_detection_tpu.cluster.hashring import partition_for_key
from realtime_fraud_detection_tpu.cluster.procfleet import (
    DIGEST_NOW,
    ProcessFleet,
)
from realtime_fraud_detection_tpu.sim.arrivals import (
    DiurnalBurstConfig,
    DiurnalBurstProcess,
)
from realtime_fraud_detection_tpu.stream import topics as T

__all__ = ["ElasticDrillConfig", "run_elastic_drill",
           "compact_elastic_summary", "run_elastic_scaling",
           "build_elastic_schedule"]


def _wall() -> float:
    # rtfd-lint: allow[wall-clock] real OS processes are paced/measured on the wall clock by definition
    return time.time()


@dataclasses.dataclass
class ElasticDrillConfig:
    """Drill sizes. Defaults = the full drill (10M-user id space);
    ``fast()`` = the tier-1 smoke — same fleet shape (>= 8 processes, the
    kill, the full autoscale cycle), compressed timeline."""

    seed: int = 7
    n_partitions: int = 12          # the transactions topic's contract
    num_users: int = 10_000_000
    num_merchants: int = 2_000
    hot_users: int = 4_000          # repeat-customer cohort (state depth)
    hot_frac: float = 0.35
    # offered load: one diurnal cycle, peak mid-run
    duration_s: float = 28.0
    trough_tps: float = 250.0
    peak_tps: float = 1_600.0
    burst_mult: float = 1.25        # mild bursts ride the full config
    burst_every_s: float = 9.0
    burst_duration_s: float = 0.5
    # fleet + autoscale: per_worker_tps is the controller's capacity
    # model; the service-cost model below keeps real capacity ~20% above
    # it so an adequately-scaled fleet drains its backlog
    min_workers: int = 4
    max_workers: int = 8
    per_worker_tps: float = 250.0
    headroom: float = 1.25
    lead_s: float = 2.0
    decide_interval_s: float = 0.5
    down_patience: int = 4
    forecast_bucket_s: float = 0.25
    # worker knobs (wall-time service-cost model stands in for device
    # compute, like the in-process drills' virtual cost — paid for real)
    batch: int = 64
    max_delay_ms: float = 25.0
    checkpoint_every: int = 5
    base_ms: float = 10.0
    per_txn_ms: float = 3.2
    autotune: bool = True           # tuner trials in-flight depth live
    autotune_interval: int = 10     # short epochs: depth trials fit a run
    # the SIGKILL lands at this fraction of the timeline (the peak)
    kill_frac: float = 0.5
    ack_timeout_s: float = 120.0
    drain_timeout_s: float = 180.0
    # second, fully fresh run compared digest-for-digest with the first
    replay_check: bool = True

    @classmethod
    def fast(cls) -> "ElasticDrillConfig":
        """Tier-1 smoke: every phase (autoscale cycle, >= 8 processes,
        SIGKILL, replay, drain) still runs; timeline and id space shrink.
        """
        return cls(num_users=200_000, num_merchants=400, hot_users=1_500,
                   duration_s=12.0, trough_tps=100.0, peak_tps=700.0,
                   burst_mult=1.0, burst_duration_s=0.0,
                   per_worker_tps=110.0, lead_s=1.5, down_patience=3,
                   base_ms=10.0, per_txn_ms=7.0, checkpoint_every=4)

    def peak_time(self) -> float:
        return 0.5 * self.duration_s     # raised-cosine peak, one cycle

    def envelope(self) -> DiurnalBurstProcess:
        """The burst-free diurnal envelope — the deterministic intensity
        the ahead-of-ramp check compares provisioned capacity against
        (bursts are absorbed by headroom, not by permanent capacity)."""
        return DiurnalBurstProcess(DiurnalBurstConfig(
            trough_tps=self.trough_tps, peak_tps=self.peak_tps,
            period_s=self.duration_s, burst_duration_s=0.0),
            seed=self.seed)

    def arrivals(self) -> DiurnalBurstProcess:
        return DiurnalBurstProcess(DiurnalBurstConfig(
            trough_tps=self.trough_tps, peak_tps=self.peak_tps,
            period_s=self.duration_s, burst_mult=max(1.0, self.burst_mult),
            burst_every_s=self.burst_every_s,
            burst_duration_s=self.burst_duration_s), seed=self.seed)


# ------------------------------------------------------------- the stream


def build_elastic_schedule(cfg: ElasticDrillConfig,
                           ) -> List[Tuple[float, Dict[str, Any]]]:
    """Seeded (event_ts, txn) timeline: diurnal arrival instants joined to
    a synthetic transaction stream over the 10M-user id space — a hot
    repeat-customer cohort (per-user state actually accumulates) plus a
    uniform long tail, schema-complete for ``sanitize_for_stream``."""
    times = cfg.arrivals().generate(cfg.duration_s)
    n = len(times)
    rng = np.random.default_rng(cfg.seed + 1)
    hot_pool = rng.integers(0, cfg.num_users, size=max(1, cfg.hot_users))
    take_hot = rng.random(n) < cfg.hot_frac
    uid_idx = np.where(
        take_hot,
        hot_pool[rng.integers(0, len(hot_pool), size=n)],
        rng.integers(0, cfg.num_users, size=n))
    mid_idx = rng.integers(0, cfg.num_merchants, size=n)
    amounts = np.round(rng.lognormal(3.2, 0.9, size=n), 2)
    sched: List[Tuple[float, Dict[str, Any]]] = []
    for i in range(n):
        t = round(float(times[i]), 9)
        sched.append((t, {
            "transaction_id": f"etx_{i}",
            "user_id": f"user_{int(uid_idx[i])}",
            "merchant_id": f"m_{int(mid_idx[i])}",
            "amount": float(amounts[i]),
            "payment_method": "card",
            "event_ts": t,
        }))
    return sched


# ---------------------------------------------------------------- oracle


def run_elastic_oracle(cfg: ElasticDrillConfig,
                       sched: List[Tuple[float, Dict[str, Any]]],
                       ) -> Dict[str, Any]:
    """Single-process oracle: apply each partition's records in offset
    (== schedule) order through the SAME state-coupled scorer the workers
    run. Per-user state lives entirely inside the user's partition, so
    this is exactly the state/score truth any correct fleet must land on,
    independent of batching, membership, kills, or rebalances."""
    from realtime_fraud_detection_tpu.cluster.drill import ShardScorer
    from realtime_fraud_detection_tpu.cluster.partition import (
        PartitionedStore,
    )

    store = PartitionedStore(
        cfg.n_partitions, seq_len=4, feature_dim=4,
        cache_kwargs={"txn_ttl_s": 1e12, "features_ttl_s": 1e12})
    for p in range(cfg.n_partitions):
        store.acquire(p)
    scorer = ShardScorer(store)
    scores: Dict[str, Tuple[float, str]] = {}
    for _, txn in sched:
        res = scorer._score_and_update(txn)
        scores[res["transaction_id"]] = (res["fraud_score"],
                                         res["decision"])
    return {
        "scores": scores,
        "digests": {p: d for p, d in store.digests(now=DIGEST_NOW).items()},
    }


# ------------------------------------------------------------- fleet run


def _run_elastic_fleet(cfg: ElasticDrillConfig,
                       sched: List[Tuple[float, Dict[str, Any]]],
                       ) -> Dict[str, Any]:
    """One fresh fleet run over the schedule: own broker server, own
    handoff server + blob dir, own worker processes. Returns the raw
    outcome (ledger, digests, autoscale ledger, fleet events, digest)."""
    from realtime_fraud_detection_tpu.chaos.faults import (
        ChaosPlan,
        FaultWindow,
        WorkerKill,
    )
    from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer
    from realtime_fraud_detection_tpu.tuning.forecast import (
        ArrivalForecaster,
    )

    broker_srv = BrokerServer(port=0).start()
    tmp = tempfile.mkdtemp(prefix="rtfd-elastic-")
    handoff_srv = None
    fleet = None
    try:
        from realtime_fraud_detection_tpu.cluster.handoff import (
            HandoffServer,
        )

        handoff_srv = HandoffServer(
            blob_dir=os.path.join(tmp, "blobs")).start()
        fleet = ProcessFleet(
            f"127.0.0.1:{broker_srv.port}",
            f"127.0.0.1:{handoff_srv.port}",
            n_partitions=cfg.n_partitions,
            ack_timeout_s=cfg.ack_timeout_s,
            # workers are pure host arithmetic: pin them to the CPU
            # platform so a drill on a TPU host never touches the chips
            spawn_env={**os.environ, "JAX_PLATFORMS": "cpu"},
            worker_spec={
                "batch": cfg.batch, "max_delay_ms": cfg.max_delay_ms,
                "checkpoint_every": cfg.checkpoint_every,
                "seq_len": 4, "feature_dim": 4,
                "base_ms": cfg.base_ms, "per_txn_ms": cfg.per_txn_ms,
                "autotune": cfg.autotune,
                "autotune_interval": cfg.autotune_interval,
            })
        controller = AutoscaleController(
            per_worker_tps=cfg.per_worker_tps,
            min_workers=cfg.min_workers, max_workers=cfg.max_workers,
            headroom=cfg.headroom, lead_s=cfg.lead_s,
            decide_interval_s=cfg.decide_interval_s,
            down_patience=cfg.down_patience,
            forecaster=ArrivalForecaster(bucket_s=cfg.forecast_bucket_s))
        t_spawn0 = _wall()
        fleet.start(cfg.min_workers, now=0.0)
        spawn_floor_s = round(_wall() - t_spawn0, 3)

        t_kill = cfg.kill_frac * cfg.duration_s
        plan = ChaosPlan([FaultWindow("worker_kill", "cluster",
                                      t_kill, t_kill + 0.05)])
        kill = WorkerKill(fleet, "busiest")
        plan.bind("worker_kill", kill)

        alive_timeline: List[Tuple[float, int]] = []
        start_wall = _wall()
        next_i, n = 0, len(sched)
        produced = 0
        while True:
            now_ev = _wall() - start_wall
            if next_i < n:
                j = next_i
                items = []
                while j < n and sched[j][0] <= now_ev:
                    t_ev, txn = sched[j]
                    items.append((txn["user_id"], txn, start_wall + t_ev))
                    # strict event order into the controller: boundary
                    # decisions interleave deterministically (autoscale.py)
                    controller.observe(t_ev, 1)
                    j += 1
                if items:
                    fleet.client.produce_batch_stamped(T.TRANSACTIONS,
                                                       items)
                    produced += len(items)
                    next_i = j
            controller.observe(now_ev, 0)
            plan.poll(now_ev)
            fleet.tick(now_ev)
            # asynchronous scale execution: spawns never stall production
            # (the forecast lead pays for startup), joins batch into one
            # rebalance per loop pass, drains stay graceful
            fleet.ensure_target(controller.target, now=now_ev)
            alive_timeline.append((round(now_ev, 3),
                                   len(fleet.ready_ids())))
            if next_i >= n:
                lag = fleet.client.lag(fleet.group_id, T.TRANSACTIONS)
                if lag == 0 and controller.target == len(fleet.ready_ids()) \
                        and controller.target == cfg.min_workers:
                    break
                if now_ev > cfg.duration_s + cfg.drain_timeout_s:
                    raise RuntimeError(
                        f"drain timeout: lag={lag} "
                        f"target={controller.target} "
                        f"alive={len(fleet.ready_ids())}")
            time.sleep(0.02)
        makespan = _wall() - start_wall

        fleet.shutdown_all(now=_wall() - start_wall)
        byes = fleet.all_byes()   # drained workers' summaries included
        digests: Dict[int, str] = {}
        counters = {"scored": 0, "duplicates_skipped": 0, "errors": 0,
                    "batches": 0}
        lat_by_depth: Dict[str, Dict[str, Any]] = {}
        for wid, bye in sorted(byes.items()):
            for p, d in (bye.get("digests") or {}).items():
                digests[int(p)] = d
            for k in counters:
                counters[k] += int((bye.get("counters") or {}).get(k, 0))
            for depth, stats in (bye.get("latency_by_depth") or {}).items():
                cur = lat_by_depth.setdefault(depth, {"n": 0, "p99_ms": 0.0})
                cur["n"] += stats["n"]
                cur["p99_ms"] = max(cur["p99_ms"], stats["p99_ms"])

        # ---- predictions ledger: one pass over the topic (coverage +
        # score agreement + first-emission per-key order) ------------------
        inner = broker_srv.broker
        preds: Dict[str, List[Tuple[float, str, str]]] = {}
        order_ok = True
        last_seq: Dict[Tuple[int, str], int] = {}
        emissions = 0
        for p in range(inner.partitions(T.PREDICTIONS)):
            off = 0
            while True:
                recs = inner.read(T.PREDICTIONS, p, off, 4096)
                if not recs:
                    break
                off = recs[-1].offset + 1
                for r in recs:
                    v = r.value if isinstance(r.value, dict) else {}
                    ex = v.get("explanation") or {}
                    kind = ("replayed" if ex.get("replayed_from_cache")
                            else "error" if ex.get("error") else "scored")
                    tid = str(v.get("transaction_id", ""))
                    emissions += 1
                    first = tid not in preds
                    preds.setdefault(tid, []).append(
                        (round(float(v.get("fraud_score", -1.0)), 6),
                         str(v.get("decision", "")), kind))
                    if first:
                        uid = str(r.key or "")
                        try:
                            seq = int(tid.rsplit("_", 1)[-1])
                        except ValueError:
                            continue
                        keyp = (p, uid)
                        if last_seq.get(keyp, -1) >= seq:
                            order_ok = False
                        last_seq[keyp] = seq

        tx_ends = inner.end_offsets(T.TRANSACTIONS)
        committed = [inner.committed(fleet.group_id, T.TRANSACTIONS, p)
                     for p in range(len(tx_ends))]

        snap = fleet.snapshot()
        auto = controller.snapshot()
        digest = hashlib.sha256(json.dumps({
            "produced": produced,
            # unique (score, decision) per transaction: duplicate
            # emissions across the SIGKILL window collapse (identical by
            # the oracle property — checked separately), so the digest
            # depends only on content, never on where the kill landed
            "preds": sorted((tid, sorted({(s, d) for s, d, _ in e}))
                            for tid, e in preds.items()),
            "committed": committed,
            "state": sorted((p, d) for p, d in digests.items()),
            "autoscale": auto["decisions"],
        }, sort_keys=True).encode()).hexdigest()

        return {
            "produced": produced,
            "preds": preds,
            "emissions": emissions,
            "order_ok": order_ok,
            "committed": committed,
            "tx_ends": tx_ends,
            "digests": digests,
            "counters": counters,
            "byes": {w: {k: v for k, v in b.items() if k != "digests"}
                     for w, b in byes.items()},
            "latency_by_depth": lat_by_depth,
            "autoscale": auto,
            "fleet": snap,
            "kill": kill.last_result,
            "t_kill": t_kill,
            "alive_timeline": alive_timeline,
            "spawn_floor_s": spawn_floor_s,
            "handoff_stats": fleet.handoff.stats(),
            "makespan_s": round(makespan, 3),
            "digest": digest,
        }
    finally:
        if fleet is not None:
            fleet.terminate()
        if handoff_srv is not None:
            handoff_srv.stop()
        broker_srv.stop()


# ------------------------------------------------------------------ drill


def _movement_checks(cfg: ElasticDrillConfig,
                     events: List[Dict[str, Any]]) -> Tuple[bool, int]:
    """Only the joining/leaving/dead members' partitions may move on any
    rebalance (survivor stability — the consistent-hash contract), and a
    single-member change stays within ~2x the K/N expectation."""
    ok = True
    max_single = 0
    prev_assign: Optional[Dict[str, List[int]]] = None
    prev_members: set = set()
    for ev in events:
        if ev.get("event") != "rebalance":
            continue
        assign = ev["assignment"]
        members = set(ev["members"])
        if prev_assign is not None:
            joiners = members - prev_members
            leavers = prev_members - members
            owner_old = {p: w for w, ps in prev_assign.items() for p in ps}
            owner_new = {p: w for w, ps in assign.items() for p in ps}
            moved = set(ev["moved"])
            for p in moved:
                # every moved partition either lands ON a joiner or
                # leaves FROM a leaver/dead member — survivors never
                # exchange partitions among themselves
                if owner_new.get(p) not in joiners \
                        and owner_old.get(p) not in leavers:
                    ok = False
            if len(joiners | leavers) == 1:
                bound = 2 * math.ceil(cfg.n_partitions
                                      / max(1, len(members | prev_members)))
                max_single = max(max_single, len(moved))
                if len(moved) > bound:
                    ok = False
        prev_assign, prev_members = assign, members
    return ok, max_single


def run_elastic_drill(config: Optional[ElasticDrillConfig] = None,
                      fast: bool = False) -> Dict[str, Any]:
    """Run the elastic drill: process fleet with SIGKILL + autoscale vs
    the single-process oracle, plus the fresh-run determinism check."""
    cfg = config or (ElasticDrillConfig.fast() if fast
                     else ElasticDrillConfig())
    sched = build_elastic_schedule(cfg)
    oracle = run_elastic_oracle(cfg, sched)
    out = _run_elastic_fleet(cfg, sched)

    produced_ids = {txn["transaction_id"] for _, txn in sched}
    preds = out["preds"]
    lost = len(produced_ids - set(preds))
    conflicting = 0
    score_mismatches = 0
    duplicate_emissions = 0
    for tid, emits in preds.items():
        scored = [(s, d) for s, d, kind in emits if kind == "scored"]
        if len(scored) > 1:
            duplicate_emissions += len(scored) - 1
        if len(set(scored)) > 1:
            conflicting += 1
        want = oracle["scores"].get(tid)
        if scored and want is not None and any(sd != want for sd in scored):
            score_mismatches += 1
    errors = sum(1 for emits in preds.values()
                 for _, _, kind in emits if kind == "error")

    # --- autoscale: provably ahead of the (deterministic) diurnal ramp ---
    env = cfg.envelope()
    decisions = out["autoscale"]["decisions"]
    target_at: List[Tuple[float, int]] = [(0.0, cfg.min_workers)]
    for d in decisions:
        target_at.append((d["t"], d["target"]))
    probe_ts = [i * cfg.decide_interval_s
                for i in range(int(cfg.duration_s / cfg.decide_interval_s)
                               + 1)]

    def _target(t: float) -> int:
        cur = cfg.min_workers
        for td, tg in target_at:
            if td <= t:
                cur = tg
            else:
                break
        return cur

    ahead = all(_target(t) * cfg.per_worker_tps >= env.rate_at(t) - 1e-6
                for t in probe_ts)
    ups = [d for d in decisions if d["direction"] == "up"]
    downs = [d for d in decisions if d["direction"] == "down"]
    peak_t = cfg.peak_time()
    peak_target = max((d["target"] for d in ups), default=cfg.min_workers)
    scaled_up_before_peak = bool(ups) and ups[-1]["t"] < peak_t \
        and peak_target >= 8
    drained_after_peak = bool(downs) and all(d["t"] > peak_t for d in downs)
    max_alive = max(a for _, a in out["alive_timeline"])
    final_alive = out["alive_timeline"][-1][1]
    # distinct workers that actually joined the ring and served — the
    # deterministic form of "scored across >= 8 OS processes" (peak
    # CONCURRENCY is wall-dependent: on a loaded box a spawn can outlast
    # the scale window; it is reported, never gated)
    joiners = set()
    for ev in out["fleet"]["events"]:
        if ev.get("event") == "rebalance":
            joiners.update(ev.get("members") or ())
    movement_ok, max_single_move = _movement_checks(
        cfg, out["fleet"]["events"])

    kill = out["kill"] or {}
    replayed_after_kill = int(kill.get("replayed", 0))

    replay_identical = None
    second_digest = None
    if cfg.replay_check:
        second = _run_elastic_fleet(cfg, sched)
        second_digest = second["digest"]
        replay_identical = second_digest == out["digest"]

    distinct_pids = {st["pid"]
                     for st in out["fleet"]["workers"].values()}
    checks = {
        "processes_real": (len(distinct_pids)
                           == len(out["fleet"]["workers"])
                           and os.getpid() not in distinct_pids),
        "processes_enough": (out["fleet"]["spawns"] >= 8
                             and len(joiners) >= 8
                             and peak_target == cfg.max_workers),
        "sigkill_real": (bool(kill.get("killed"))
                         and kill.get("returncode") == -9),
        "zero_lost": lost == 0,
        "zero_double_scored": conflicting == 0,
        "zero_errors": errors == 0,
        "offsets_gap_free": out["committed"] == out["tx_ends"],
        "per_key_order_preserved": out["order_ok"],
        "state_equals_oracle": out["digests"] == oracle["digests"],
        "scores_equal_oracle": score_mismatches == 0,
        "handoff_replay_exercised": replayed_after_kill >= 1,
        "autoscale_ahead_of_ramp": ahead,
        "scaled_up_before_peak": scaled_up_before_peak,
        "drained_after_peak": (drained_after_peak
                               and final_alive == cfg.min_workers),
        "movement_bounded": movement_ok,
    }
    if replay_identical is not None:
        checks["replay_deterministic"] = bool(replay_identical)

    summary: Dict[str, Any] = {
        "metric": "elastic_drill",
        "passed": all(bool(v) for v in checks.values()),
        "checks": checks,
        "num_users": cfg.num_users,
        "n_partitions": cfg.n_partitions,
        "produced": out["produced"],
        "scored": out["counters"]["scored"],
        "emissions": out["emissions"],
        "duplicate_emissions": duplicate_emissions,
        "lost": lost,
        "conflicting_scored": conflicting,
        "score_mismatches": score_mismatches,
        "processes_spawned": out["fleet"]["spawns"],
        "workers_joined": len(joiners),
        "max_alive": max_alive,
        "final_alive": final_alive,
        "kill": kill,
        "t_kill": out["t_kill"],
        "replayed_after_kill": replayed_after_kill,
        "replayed_total": out["fleet"]["replayed_total"],
        "handoffs_total": out["fleet"]["handoffs_total"],
        "handoff_server": out["handoff_stats"],
        "autoscale_decisions": decisions,
        "autoscale_events": out["autoscale"]["events"],
        "peak_time_s": peak_t,
        "max_single_member_move": max_single_move,
        # wall-clock report (NEVER in the digest): real-machine numbers
        "wall": {
            "makespan_s": out["makespan_s"],
            "spawn_floor_s": out["spawn_floor_s"],
            "rebalance_pauses_s": out["fleet"]["rebalance_pauses_s"],
            "latency_by_depth_ms": out["latency_by_depth"],
        },
        "events": out["fleet"]["events"],
        "replay_identical": replay_identical,
        "digest": out["digest"],
        "second_digest": second_digest,
    }
    return summary


def compact_elastic_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line verdict (bench.py convention: full
    result on the preceding line, compact parseable verdict last)."""
    compact = {
        "metric": "elastic_drill",
        "passed": summary.get("passed"),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "num_users": summary.get("num_users"),
        "produced": summary.get("produced"),
        "scored": summary.get("scored"),
        "lost": summary.get("lost"),
        "conflicting_scored": summary.get("conflicting_scored"),
        "duplicate_emissions": summary.get("duplicate_emissions"),
        "processes_spawned": summary.get("processes_spawned"),
        "workers_joined": summary.get("workers_joined"),
        "max_alive": summary.get("max_alive"),
        "kill_returncode": (summary.get("kill") or {}).get("returncode"),
        "replayed_after_kill": summary.get("replayed_after_kill"),
        "autoscale_events": summary.get("autoscale_events"),
        "makespan_s": (summary.get("wall") or {}).get("makespan_s"),
        "digest": (summary.get("digest") or "")[:16],
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:
        for victim in ("checks", "autoscale_events", "digest",
                       "summary_of"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "elastic_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact


# ------------------------------------------------------------- bench hook


def run_elastic_scaling(seed: int = 7,
                        workers: Tuple[int, ...] = (2, 4, 8),
                        n_txns: int = 3_000) -> Dict[str, Any]:
    """The ``bench.py elastic_scaling`` stage: REAL aggregate txn/s of the
    process fleet at pinned 2/4/8 OS processes over the TCP netbroker
    (autoscale off — the fleet is pinned per run), plus a SIGKILL run's
    rebalance pause and replay depth. The per-batch service-cost model is
    fixed, so the ratio measures the orchestration overhead (TCP round
    trips, partition-scoped consumption, commit traffic) on top of
    perfectly-parallel modeled compute — the honest process-plane analog
    of ``shard_scaling``'s virtual-clock story."""
    from realtime_fraud_detection_tpu.cluster.handoff import HandoffServer
    from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer

    spec = {"batch": 64, "max_delay_ms": 10.0, "checkpoint_every": 6,
            "seq_len": 4, "feature_dim": 4, "base_ms": 6.0,
            "per_txn_ms": 1.2, "autotune": False}
    cfg = ElasticDrillConfig.fast()
    cfg = dataclasses.replace(cfg, seed=seed)
    sched = build_elastic_schedule(cfg)[:n_txns]

    def _one(n_workers: int, kill: bool) -> Dict[str, Any]:
        broker_srv = BrokerServer(port=0).start()
        tmp = tempfile.mkdtemp(prefix="rtfd-escale-")
        handoff_srv = HandoffServer(
            blob_dir=os.path.join(tmp, "blobs")).start()
        fleet = ProcessFleet(
            f"127.0.0.1:{broker_srv.port}",
            f"127.0.0.1:{handoff_srv.port}",
            n_partitions=cfg.n_partitions, worker_spec=spec,
            spawn_env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            fleet.start(n_workers)
            t0 = _wall()
            items = [(txn["user_id"], txn, t + t0) for t, txn in sched]
            fleet.client.produce_batch_stamped(T.TRANSACTIONS, items)
            killed = None
            deadline = _wall() + 240
            while _wall() < deadline:
                fleet.tick()
                lag = fleet.client.lag(fleet.group_id, T.TRANSACTIONS)
                if kill and killed is None and lag < len(sched) // 2:
                    killed = fleet.kill_worker("busiest")
                if lag == 0:
                    break
                time.sleep(0.02)
            else:
                raise RuntimeError("elastic_scaling drain timeout")
            wall = _wall() - t0
            snap = fleet.snapshot()
            return {
                "wall_s": round(wall, 3),
                "txn_per_s": round(len(sched) / max(wall, 1e-9), 1),
                "kill": killed,
                "replayed": snap["replayed_total"],
                "rebalance_pauses_s": snap["rebalance_pauses_s"],
            }
        finally:
            fleet.terminate()
            handoff_srv.stop()
            broker_srv.stop()

    per_w = {w: _one(w, kill=False) for w in sorted(workers)}
    kill_out = _one(max(workers), kill=True)
    w_min, w_max = min(workers), max(workers)
    base = per_w[w_min]["txn_per_s"]
    return {
        "n_txns": len(sched),
        "n_partitions": cfg.n_partitions,
        "workers": {str(w): {k: v for k, v in r.items()
                             if k in ("wall_s", "txn_per_s")}
                    for w, r in per_w.items()},
        "aggregate_txn_per_s": per_w[w_max]["txn_per_s"],
        "scaling_vs_min": round(per_w[w_max]["txn_per_s"]
                                / max(base, 1e-9), 3),
        "scaling_efficiency": round(
            per_w[w_max]["txn_per_s"] / max(base, 1e-9)
            / (w_max / w_min), 3),
        "kill_run": {
            "returncode": (kill_out["kill"] or {}).get("returncode"),
            "replayed": kill_out["replayed"],
            "rebalance_pause_s": (max(kill_out["rebalance_pauses_s"][1:])
                                  if len(kill_out["rebalance_pauses_s"]) > 1
                                  else None),
        },
    }

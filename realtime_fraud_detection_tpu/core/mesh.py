"""Device mesh construction and named-sharding helpers.

The reference system's only parallelism is data parallelism (Kafka partitions x
Flink parallelism 12, SURVEY.md section 2.8). The TPU-native equivalent is a
``jax.sharding.Mesh`` whose ``data`` axis shards the microbatch across chips
over ICI; XLA inserts the collectives. Two further axes are first-class from
day one so tensor parallelism (the BERT branch) and sequence/context
parallelism are config choices, not rewrites:

- ``data``  - batch dimension (always present; the Flink-parallelism analog)
- ``model`` - tensor-parallel axis, reserved for the BERT encoder
- ``seq``   - sequence/context-parallel axis for blockwise attention

Multi-host (DCN) story (SURVEY.md §5.8): ``init_distributed`` bootstraps the
cross-host control plane (``jax.distributed`` — the NCCL/MPI-rendezvous
analog of the reference's 3-TaskManager Flink cluster,
docker-compose.yml:287-354), and ``build_multihost_mesh`` lays the global
mesh out PROCESS-MAJOR along ``data``: the ``model``/``seq`` axes never
cross a host, so their per-layer all-reduces ride ICI, while the ``data``
axis's once-per-step gradient all-reduce is the only collective that
touches DCN — the layering the scaling playbook prescribes. The same
jitted step runs unchanged; only the mesh construction differs.

Reference parity notes: Flink parallelism=12 over 3 TMs
(reference docker-compose.yml:265-268) maps to ``data=n_devices`` here.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
AXIS_NAMES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``data=None`` means "all remaining devices"."""

    data: int | None = None
    model: int = 1
    seq: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int]:
        ms = self.model * self.seq
        if n_devices % ms != 0:
            raise ValueError(
                f"model*seq={ms} does not divide device count {n_devices}"
            )
        data = self.data if self.data is not None else n_devices // ms
        if data * ms != n_devices:
            raise ValueError(
                f"mesh {data}x{self.model}x{self.seq} != {n_devices} devices"
            )
        return (data, self.model, self.seq)


def build_mesh(
    config: MeshConfig | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a 3-axis (data, model, seq) mesh over ``devices``.

    On a single chip this degrades to a (1, 1, 1) mesh so every code path is
    identical between 1-chip dev and a v5e-8 / multi-host pod.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    shape = config.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_NAMES)


def init_distributed(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> None:
    """Bootstrap the cross-host (DCN) control plane.

    One call per process, BEFORE any backend use. After it,
    ``jax.devices()`` is the global device set and ``build_multihost_mesh``
    lays meshes over all hosts. This is the framework's analog of the
    reference's TaskManager->JobManager registration
    (docker-compose.yml:287-354) — except the data plane it unlocks is XLA
    collectives over DCN, not Akka RPC.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def build_multihost_mesh(
    config: MeshConfig | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Global (data, model, seq) mesh with a PROCESS-MAJOR data axis.

    Devices are ordered (process_index, id) before reshaping, which pins
    the physical layout: every ``model`` x ``seq`` tile sits inside one
    process (so TP/SP collectives — several per layer — stay on ICI), and
    crossing a ``data``-axis process boundary happens only in the
    once-per-step DP gradient sync, the one collective cheap enough for
    DCN. ``model * seq`` must divide the per-process device count or the
    tile would straddle hosts — refused loudly.

    Single-process: identical to ``build_mesh`` (devices are already one
    process), so code written against this helper runs unchanged from a
    dev box to a multi-host pod.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    devices.sort(key=lambda d: (d.process_index, d.id))
    n_local = min(
        sum(1 for d in devices if d.process_index == p)
        for p in {d.process_index for d in devices}
    )
    ms = config.model * config.seq
    if n_local % ms != 0:
        raise ValueError(
            f"model*seq={ms} does not divide the per-process device count "
            f"{n_local}: a TP/SP tile would straddle a host boundary and "
            f"put per-layer collectives on DCN")
    shape = config.resolve(len(devices))
    return Mesh(np.asarray(devices, dtype=object).reshape(shape), AXIS_NAMES)


def make_global_batch(mesh: Mesh, tree: Any, shardings: Any) -> Any:
    """Assemble a global batch from per-process local shards.

    Each process passes the rows it owns (its slice of the data axis);
    the result is one logical array spanning all hosts. Single-process
    degrades to a plain sharded device_put. The multi-host twist: hosts
    never exchange batch bytes — each feeds only its own chips, exactly
    like the reference's per-TM Kafka partition assignment.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)
    return jax.tree_util.tree_map(
        lambda x, s: jax.make_array_from_process_local_data(s, np.asarray(x)),
        tree, shardings,
    )


def local_mesh_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding for a [B, ...] tensor: batch over ``data``, rest replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * extra_dims)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree: Any) -> Any:
    """Device-put every [B, ...] leaf of a pytree sharded over the data axis.

    Host->device transfer point for microbatches: leaves keep their rank, the
    leading dim is split across the ``data`` axis. Scalars/0-d are replicated.

    Batches whose leading dim does not divide the data axis (drain/flush
    tails smaller than the device count included) are padded up to
    ``pad_batch_to_mesh`` by replicating row 0 — the ``pad_to_bucket``
    staging convention, so a pad row is always a well-formed record, never
    zeros that could NaN a branch. Callers that track validity keep their
    own mask (the scorer's staging mask rides INSIDE the packed blobs and
    already marks these rows invalid); callers without one slice results
    back to the original row count.
    """
    d = local_mesh_size(mesh)

    def _put(x):
        arr = np.asarray(x)
        if arr.ndim == 0:
            return jax.device_put(arr, replicated_sharding(mesh))
        n = arr.shape[0]
        if n % d != 0:
            m = pad_batch_to_mesh(n, mesh)
            arr = np.concatenate(
                [arr, np.broadcast_to(arr[:1], (m - n,) + arr.shape[1:])],
                axis=0)
        return jax.device_put(arr, batch_sharding(mesh, arr.ndim - 1))

    return jax.tree_util.tree_map(_put, tree)


def pad_batch_to_mesh(n: int, mesh: Mesh) -> int:
    """Smallest batch >= max(n, 1) divisible by the data axis size.

    Tolerates n smaller than the device count (a 3-row flush tail on an
    8-chip mesh pads to 8, never crashes); n == 0 still returns one full
    data-axis row so a degenerate caller gets a shardable shape."""
    d = local_mesh_size(mesh)
    return int(math.ceil(max(n, 1) / d) * d)

"""Batch bucketing: pad dynamic microbatches onto a fixed set of shapes.

Everything under ``jit`` is compiled per input shape. A latency-bounded
microbatcher produces arbitrary batch sizes; compiling per size would be a
recompile storm. We therefore round every microbatch up to a bucket from
``BATCH_BUCKETS`` and carry a validity mask. The bucket set matches the
TF-Serving batching config the reference ships but never exercises
(reference k8s/manifests/ml-models-deployment.yaml:270-290: allowed sizes
1..128, max 128) extended to 256 for the TPU's appetite.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

BATCH_BUCKETS: tuple[int, ...] = (1, 8, 32, 128, 256)


def bucket_for(
    n: int, buckets: tuple[int, ...] = BATCH_BUCKETS, multiple_of: int = 1
) -> int:
    """Smallest bucket >= n; multiples of the largest bucket for huge n.

    ``multiple_of`` (typically the mesh ``data``-axis size) guarantees the
    result is shardable: buckets below it are rounded up to it.
    """
    if n <= 0:
        raise ValueError(f"batch size must be positive, got {n}")

    def _round_up(size: int) -> int:
        if size % multiple_of:
            size = ((size + multiple_of - 1) // multiple_of) * multiple_of
        return size

    for b in buckets:
        if n <= b:
            return _round_up(b)
    top = buckets[-1]
    return _round_up(((n + top - 1) // top) * top)


def pad_to_bucket(
    tree: Any,
    n: int,
    buckets: tuple[int, ...] = BATCH_BUCKETS,
    multiple_of: int = 1,
) -> Tuple[Any, np.ndarray, int]:
    """Pad every [n, ...] leaf to the bucket size; return (padded, mask, size).

    Padding replicates row 0 (keeps values in-distribution so padded rows
    can't produce inf/nan that would poison reductions); the mask is False on
    padded rows. Pass ``multiple_of=mesh data-axis size`` so the result is
    always shardable by ``shard_batch``.
    """
    size = bucket_for(n, buckets, multiple_of)
    pad = size - n

    def _pad(x):
        arr = np.asarray(x)
        if arr.ndim == 0 or arr.shape[0] != n:
            return arr
        if pad == 0:
            return arr
        filler = np.broadcast_to(arr[:1], (pad,) + arr.shape[1:])
        return np.concatenate([arr, filler], axis=0)

    import jax

    padded = jax.tree_util.tree_map(_pad, tree)
    mask = np.zeros((size,), dtype=bool)
    mask[:n] = True
    return padded, mask, size


def unpad(tree: Any, n: int, padded_size: int | None = None) -> Any:
    """Strip bucket padding back to the true batch size.

    When ``padded_size`` (the size returned by ``pad_to_bucket``) is given,
    only leaves whose leading dim equals it are cut — auxiliary leaves that
    were never padded pass through untouched.
    """
    import jax

    def _cut(x):
        arr = np.asarray(x)
        if arr.ndim == 0:
            return arr
        if padded_size is not None and arr.shape[0] != padded_size:
            return arr
        return arr[:n]

    return jax.tree_util.tree_map(_cut, tree)

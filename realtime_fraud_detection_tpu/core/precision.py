"""Precision policy: bf16 compute / f32 accumulate, f32 params.

TPU MXUs natively consume bfloat16 with float32 accumulation; the policy
object makes that the default everywhere while keeping scoring outputs and
ensemble math in float32 (the decision thresholds in the reference --
ensemble_predictor.py:344-369 -- are sensitive to ~1e-2, far above bf16 error
for [0,1] probabilities, but we keep the combine step in f32 anyway).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32

    def cast_to_compute(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )

    def cast_to_output(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.output_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )

    def matmul_precision(self):
        return jax.lax.Precision.DEFAULT


DEFAULT_POLICY = Policy()
FULL_PRECISION = Policy(compute_dtype=jnp.float32)

"""Transfer packing: collapse a [B, ...] pytree into 3 contiguous buffers.

Motivation (measured on the tunneled v5e relay, round 4): a blocked
host->device round trip costs ~85 ms regardless of payload, and
``jax.device_put`` of the 65-leaf ScoreBatch costs 2-3 round trips plus
per-leaf serialization on the host (~35 ms). Packing every float leaf into
one f32[B, Wf] matrix, every int leaf into i32[B, Wi] and every bool leaf
into u8[B, Wb] turns the microbatch transfer into three dense buffers —
one logical h2d payload — and the device-side unpack is free: XLA fuses the
slice/reshape/cast back-out into the consumers, so no extra HBM traffic.

This is the TPU-native analog of the reference's serde layer
(TransactionDeserializer.java / serialization.py): where the reference
encodes per-record JSON for Kafka hops, this packs per-microbatch dense
tensors for the PCIe/network hop — the hop that actually matters here.

The spec (treedef + per-leaf layout) is static and hashable, so jitted
consumers take it as a static argument and compile once per bucket shape.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import ml_dtypes
import numpy as np
from jax import tree_util

_BF16 = np.dtype(ml_dtypes.bfloat16)

# leaf dtype kind -> (blob name, transfer dtype)
_KIND_TO_BLOB = {
    "f": ("f32", np.float32),
    "i": ("i32", np.int32),
    "u": ("i32", np.int32),
    "b": ("u8", np.uint8),
}

BLOB_NAMES = ("f32", "i32", "u8", "bf16")


def _blob_for(dtype: np.dtype) -> Tuple[str, np.dtype]:
    """Blob assignment for one leaf dtype. bfloat16 leaves ride their own
    half-width blob — the caller opts a tensor into bf16 transfer by casting
    it before packing (e.g. the LSTM history, ~45% of the ScoreBatch bytes),
    halving its wire size on bandwidth-bound links."""
    if dtype == _BF16:
        return "bf16", _BF16
    return _KIND_TO_BLOB[dtype.kind]


class PackSpec:
    """Static, hashable description of a packed pytree.

    ``entries[k] = (blob, offset, tail_shape, dtype_str)`` for leaf k in
    tree-flatten order; ``widths[blob]`` is each blob's total column count.
    """

    __slots__ = ("treedef", "entries", "widths", "_hash")

    def __init__(self, treedef, entries: Tuple, widths: Tuple):
        self.treedef = treedef
        self.entries = entries
        self.widths = widths
        self._hash = hash((treedef, entries, widths))

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return (isinstance(other, PackSpec)
                and self.treedef == other.treedef
                and self.entries == other.entries
                and self.widths == other.widths)


def pack_tree(tree: Any) -> Tuple[Dict[str, np.ndarray], PackSpec]:
    """Host side: flatten a pytree of [B, ...] arrays into 3 dense blobs.

    Every leaf must share the leading batch dim B. Ints must fit in int32
    (the ScoreBatch contract: codes, hours, token ids). Returns
    ``({"f32": [B,Wf], "i32": [B,Wi], "u8": [B,Wb]}, spec)``; empty blobs
    are [B, 0] so the device function signature is static.
    """
    leaves, treedef = tree_util.tree_flatten(tree)
    if not leaves:
        raise ValueError("pack_tree: empty pytree")
    b = int(np.shape(leaves[0])[0])
    parts: Dict[str, list] = {name: [] for name in BLOB_NAMES}
    offsets = {name: 0 for name in BLOB_NAMES}
    empty_dtype = {"f32": np.float32, "i32": np.int32, "u8": np.uint8,
                   "bf16": _BF16}
    entries = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        if arr.ndim == 0 or arr.shape[0] != b:
            raise ValueError(
                f"pack_tree: every leaf needs leading dim {b}, "
                f"got shape {arr.shape}")
        blob, cast = _blob_for(arr.dtype)
        if (blob == "i32" and arr.dtype.itemsize > 4 and arr.size
                and (arr.max() > np.iinfo(np.int32).max
                     or arr.min() < np.iinfo(np.int32).min)):
            # fail loudly rather than silently wrapping (e.g. a future
            # epoch-ms int64 field would otherwise corrupt features)
            raise ValueError(
                f"pack_tree: {arr.dtype} leaf exceeds int32 range "
                f"(min={arr.min()}, max={arr.max()}); the ScoreBatch "
                f"contract requires ints to fit in int32")
        tail = arr.shape[1:]
        width = int(math.prod(tail))
        parts[blob].append(
            np.ascontiguousarray(arr.reshape(b, width), dtype=cast))
        entries.append((blob, offsets[blob], tail, arr.dtype.name))
        offsets[blob] += width
    blobs = {
        name: (np.concatenate(p, axis=1) if p
               else np.zeros((b, 0), empty_dtype[name]))
        for name, p in parts.items()
    }
    spec = PackSpec(treedef, tuple(entries),
                    tuple(offsets[n] for n in BLOB_NAMES))
    return blobs, spec


def unpack_tree(blobs: Dict[str, Any], spec: PackSpec) -> Any:
    """Device side (jit-traceable): slice the blobs back into the pytree.

    Pure slice/reshape/cast — XLA fuses these into the consumers, so the
    unpack costs no extra memory traffic on the device.
    """
    leaves = []
    for blob, offset, tail, dtype_name in spec.entries:
        width = int(math.prod(tail))
        col = blobs[blob][:, offset:offset + width]
        col = col.reshape((col.shape[0],) + tuple(tail))
        leaves.append(col.astype(np.dtype(dtype_name)))
    return tree_util.tree_unflatten(spec.treedef, leaves)

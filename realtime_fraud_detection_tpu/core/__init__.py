from realtime_fraud_detection_tpu.core.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    batch_sharding,
    replicated_sharding,
    shard_batch,
    local_mesh_size,
)
from realtime_fraud_detection_tpu.core.precision import Policy, DEFAULT_POLICY  # noqa: F401
from realtime_fraud_detection_tpu.core.batching import (  # noqa: F401
    BATCH_BUCKETS,
    bucket_for,
    pad_to_bucket,
    unpad,
)
